"""Metrics/stats/alarms/$SYS — emqx_metrics/emqx_stats/emqx_alarm/emqx_sys
parity surface (SURVEY.md §5.5)."""

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.message import make_message
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.observe import Alarms, Metrics, Stats, SysBroker
from emqx_tpu.observe.metrics import METRIC_NAMES
from emqx_tpu.observe.wiring import observe


def test_metrics_fixed_names_and_inc():
    m = Metrics()
    assert "messages.received" in METRIC_NAMES
    m.inc("messages.received")
    m.inc("messages.received", 5)
    assert m.get("messages.received") == 6
    with pytest.raises(KeyError):
        m.inc("not.a.metric")


def test_metrics_packet_and_qos_families():
    m = Metrics()
    m.inc_recv_packet("connect", nbytes=12)
    m.inc_sent_packet("connack", nbytes=4)
    m.inc_msg_received(2)
    m.inc_msg_dropped("queue_full")
    assert m.get("packets.connect.received") == 1
    assert m.get("packets.connack.sent") == 1
    assert m.get("bytes.received") == 12 and m.get("bytes.sent") == 4
    assert m.get("messages.qos2.received") == 1
    assert m.get("messages.dropped") == 1
    assert m.get("messages.dropped.queue_full") == 1


def test_stats_watermarks():
    s = Stats()
    s.setstat("connections.count", 5)
    s.setstat("connections.count", 3)
    assert s.get("connections.count") == 3
    assert s.get("connections.max") == 5


def test_stats_pull_provider():
    s = Stats()
    n = {"v": 7}
    s.provide("topics.count", lambda: n["v"])
    assert s.get("topics.count") == 7
    n["v"] = 9
    assert s.all()["topics.count"] == 9


def test_alarms_lifecycle_and_events():
    events = []
    a = Alarms(history_size=2)
    a.on_change = lambda kind, alarm: events.append((kind, alarm.name))
    assert a.activate("high_cpu", {"usage": 0.93})
    assert not a.activate("high_cpu")  # idempotent
    assert a.is_active("high_cpu")
    assert a.deactivate("high_cpu")
    assert not a.deactivate("high_cpu")
    assert events == [("activate", "high_cpu"), ("deactivate", "high_cpu")]
    for i in range(4):
        a.activate(f"x{i}")
        a.deactivate(f"x{i}")
    assert len(a.history) == 2  # bounded


def test_sys_broker_tick_publishes_under_prefix():
    out = []
    sys = SysBroker("node1", lambda t, p: out.append((t, p)), interval=60)
    sys.attach(stats=lambda: {"connections.count": 2}, metrics=lambda: {"messages.received": 3})
    assert sys.tick(now=sys.start_time + 61)
    topics = [t for t, _ in out]
    assert "$SYS/brokers/node1/uptime" in topics
    assert "$SYS/brokers/node1/stats/connections.count" in topics
    assert "$SYS/brokers/node1/metrics/messages.received" in topics
    out.clear()
    assert not sys.tick(now=sys.start_time + 90)  # within interval


def test_observe_wires_broker_hooks():
    b = Broker()
    obs = observe(b)
    b.open_session("sub1")
    b.subscribe("sub1", "t/+")
    res = b.publish(make_message("pub", "t/1", b"x", qos=1))
    assert res.matched == 1
    m = obs.metrics
    assert m.get("messages.received") == 1
    assert m.get("messages.qos1.received") == 1
    assert m.get("messages.delivered") == 1
    assert m.get("session.created") == 1
    assert obs.stats.get("topics.count") == 1
    assert obs.stats.get("sessions.count") == 1
    assert obs.stats.get("subscriptions.count") == 1
    # no-subscriber drop accounted
    b.publish(make_message("pub", "none/here", b"x"))
    assert m.get("messages.dropped.no_subscribers") == 1


def test_sys_messages_do_not_count_as_received():
    b = Broker()
    obs = observe(b, sys_interval=0)
    b.open_session("s")
    b.subscribe("s", "$SYS/brokers/#", SubOpts())
    obs.sys.tick()
    assert obs.metrics.get("messages.received") == 0
    # but the subscriber saw the $SYS publishes
    sess = b.sessions["s"]
    assert sess is not None


def test_connections_count_tracks_live_channels():
    import asyncio

    """connections.count / live_connections.count come from the CM —
    regression: they were never wired and stayed 0 (found driving the
    dashboard against a live node)."""
    async def main():
        from emqx_tpu.client import Client
        from emqx_tpu.config import Config
        from emqx_tpu.node import BrokerNode

        node = BrokerNode(Config(
            file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n'))
        await node.start()
        try:
            port = node.listeners.all()[0].port
            cs = []
            for i in range(3):
                c = Client(clientid=f"cc{i}", port=port)
                await c.connect()
                cs.append(c)
            stats = node.observed.stats.all()
            assert stats["connections.count"] == 3
            assert stats["live_connections.count"] == 3
            assert stats["connections.max"] >= 3
            await cs[0].disconnect()
            await asyncio.sleep(0.05)
            assert node.observed.stats.all()["connections.count"] == 2
            for c in cs[1:]:
                await c.disconnect()
        finally:
            await node.stop()

    asyncio.run(main())


def test_topic_metrics_counts_and_rest():
    """emqx_topic_metrics analog: exact-topic counters over the publish
    path + REST lifecycle."""
    import asyncio

    async def main():
        import json as _json

        from emqx_tpu.bridge import httpc
        from emqx_tpu.client import Client
        from emqx_tpu.config import Config
        from emqx_tpu.node import BrokerNode

        node = BrokerNode(Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'dashboard.enable = true\ndashboard.listen = "127.0.0.1:0"\n'
            'api_key.enable = true\napi_key.key = "k"\n'
            'api_key.secret = "s"\n')))
        await node.start()
        try:
            base = f"http://127.0.0.1:{node.mgmt_server.port}/api/v5"
            r = await httpc.request("POST", f"{base}/login", body=_json.dumps(
                {"username": "admin", "password": "public"}).encode())
            tok = _json.loads(r.body)["token"]
            hdr = {"authorization": f"Bearer {tok}"}

            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "m/1"}')
            assert r.status == 201
            # wildcards rejected; duplicates 409
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "m/+"}')
            assert r.status == 400
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "m/1"}')
            assert r.status == 409

            port = node.listeners.all()[0].port
            sub = Client(clientid="tm-s", port=port)
            await sub.connect()
            await sub.subscribe("m/1")
            pub = Client(clientid="tm-p", port=port)
            await pub.connect()
            for i in range(3):
                await pub.publish("m/1", b"x", qos=1)
            await pub.publish("m/other", b"x")  # unregistered: no count
            await asyncio.wait_for(sub.messages.get(), 5)

            r = await httpc.request("GET", f"{base}/mqtt/topic_metrics",
                                    headers=hdr)
            data = _json.loads(r.body)["data"]
            assert len(data) == 1
            rec = data[0]
            assert rec["topic"] == "m/1"
            assert rec["messages.in"] == 3
            assert rec["messages.qos1.in"] == 3
            assert rec["messages.out"] >= 1

            # reset zeroes counters and rate
            r = await httpc.request(
                "PUT", f"{base}/mqtt/topic_metrics/m/1/reset",
                headers=hdr)
            assert r.status == 204
            r = await httpc.request("GET", f"{base}/mqtt/topic_metrics",
                                    headers=hdr)
            rec = _json.loads(r.body)["data"][0]
            assert rec["messages.in"] == 0 and rec["rate.in"] == 0.0
            # invalid names: embedded wildcard chars and non-strings
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr,
                                    body=b'{"topic": "a/x+y"}')
            assert r.status == 400
            r = await httpc.request("POST", f"{base}/mqtt/topic_metrics",
                                    headers=hdr, body=b'{"topic": 123}')
            assert r.status == 400
            r = await httpc.request(
                "DELETE", f"{base}/mqtt/topic_metrics/m/1", headers=hdr)
            assert r.status == 204
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    asyncio.run(main())
