"""Multi-node cluster tests on one host — the reference's CT
slave/peer-node pattern (SURVEY.md §4): several broker nodes over
loopback with real route replication, forwarding, takeover, and
nodedown handling."""

import asyncio

import pytest

from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def start_cluster_node(name, seeds="", extra="", **over):
    cfg = Config(
        file_text=(
            f'node.name = "{name}"\n'
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'cluster.enable = true\n'
            'cluster.listen = "127.0.0.1:0"\n'
            f'cluster.seeds = "{seeds}"\n'
            'cluster.heartbeat_interval = 200ms\n'
            'cluster.node_timeout = 1500ms\n'
            + extra
        )
    )
    node = BrokerNode(cfg)
    await node.start()
    # speed the delta sync for tests
    node.cluster.SYNC_INTERVAL = 0.02
    node.cluster.RECONNECT_INTERVAL = 0.3
    node.cluster.durable.SYNC_INTERVAL = 0.05
    return node


def mqtt_port(node):
    return node.listeners.all()[0].port


def cluster_addr(node):
    return f"127.0.0.1:{node.cluster.listen_port}"


async def settle(pred, timeout=5.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


async def peered(a, b):
    return await settle(
        lambda: b.cluster.name in a.cluster.peers
        and a.cluster.peers[b.cluster.name].up
        and a.cluster.name in b.cluster.peers
        and b.cluster.peers[a.cluster.name].up
    )


# ---------------------------------------------------------------------------


def test_two_node_route_replication_and_forwarding():
    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)

            sub = Client(clientid="s1", port=mqtt_port(n1))
            await sub.connect()
            await sub.subscribe("t/+/x", qos=1)
            # the wildcard route must replicate to n2
            assert await settle(
                lambda: n2.broker.router.has_route("t/+/x", "n1@test")
            )

            pub = Client(clientid="p1", port=mqtt_port(n2))
            await pub.connect()
            await pub.publish("t/a/x", b"cross", qos=1)
            msg = await sub.recv()
            assert (msg.topic, msg.payload) == ("t/a/x", b"cross")

            # unsubscribe removes the replicated route
            await sub.unsubscribe("t/+/x")
            assert await settle(
                lambda: not n2.broker.router.has_route("t/+/x", "n1@test")
            )
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_late_join_bootstraps_routes():
    async def main():
        n1 = await start_cluster_node("n1@test")
        sub = Client(clientid="s1", port=mqtt_port(n1))
        await sub.connect()
        await sub.subscribe("pre/existing/#", qos=0)
        # n2 joins AFTER the subscription exists: snapshot bootstrap
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            assert await settle(
                lambda: n2.broker.router.has_route("pre/existing/#", "n1@test")
            )
            pub = Client(clientid="p1", port=mqtt_port(n2))
            await pub.connect()
            await pub.publish("pre/existing/topic", b"boot")
            msg = await sub.recv()
            assert msg.payload == b"boot"
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_shared_subscription_across_nodes():
    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            a = Client(clientid="a", port=mqtt_port(n1))
            b = Client(clientid="b", port=mqtt_port(n2))
            await a.connect()
            await b.connect()
            await a.subscribe("$share/g/load/t", qos=0)
            await b.subscribe("$share/g/load/t", qos=0)
            assert await settle(
                lambda: n1.broker.router.has_route("load/t", ("g", "n2@test"))
                and n2.broker.router.has_route("load/t", ("g", "n1@test"))
            )
            pub = Client(clientid="p", port=mqtt_port(n1))
            await pub.connect()
            n = 20
            for i in range(n):
                await pub.publish("load/t", f"m{i}".encode())
            # every message delivered exactly once across the group
            got = []

            async def drain(c):
                try:
                    while True:
                        got.append((await c.recv(timeout=0.5)).payload)
                except asyncio.TimeoutError:
                    pass

            await drain(a)
            await drain(b)
            assert sorted(got) == sorted(f"m{i}".encode() for i in range(n))
            await pub.disconnect()
            await a.disconnect()
            await b.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_session_takeover_across_nodes():
    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            c1 = Client(clientid="roam", port=mqtt_port(n1), proto_ver=5,
                        clean_start=False,
                        properties={"Session-Expiry-Interval": 300})
            await c1.connect()
            await c1.subscribe("offline/q", qos=1)
            await c1.disconnect()
            # registry replicated: n2 knows n1 owns 'roam'
            assert await settle(
                lambda: n2.cluster.owner_of("roam") == "n1@test"
            )
            # a message lands while the client is away → queued on n1
            pub = Client(clientid="p", port=mqtt_port(n1))
            await pub.connect()
            await pub.publish("offline/q", b"while-away", qos=1)
            await pub.disconnect()

            # reconnect on the OTHER node with clean_start=False
            c2 = Client(clientid="roam", port=mqtt_port(n2), proto_ver=5,
                        clean_start=False)
            ack = await c2.connect()
            assert ack.session_present
            msg = await c2.recv()
            assert msg.payload == b"while-away"
            # session now lives on n2; old node dropped it
            assert await settle(lambda: "roam" not in n1.broker.sessions)
            assert "roam" in n2.broker.sessions
            # replication is eventually consistent: wait for n1 to learn
            # the migrated route before publishing through it
            assert await settle(
                lambda: n1.broker.router.has_route("offline/q", "n2@test")
            )
            pub2 = Client(clientid="p2", port=mqtt_port(n1))
            await pub2.connect()
            await pub2.publish("offline/q", b"after-move", qos=1)
            msg = await c2.recv()
            assert msg.payload == b"after-move"
            await pub2.disconnect()
            await c2.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_nodedown_purges_routes():
    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            sub = Client(clientid="s1", port=mqtt_port(n2))
            await sub.connect()
            await sub.subscribe("dying/#", qos=0)
            assert await settle(
                lambda: n1.broker.router.has_route("dying/#", "n2@test")
            )
            # hard-stop n2 (no Leave: simulates a crash) → n1 times it out
            n2.cluster._running = False
            for t in n2.cluster._tasks:
                t.cancel()
            for peer in n2.cluster.peers.values():
                if peer.conn is not None:
                    peer.conn.close()
            await n2.cluster._server.stop()
            assert await settle(
                lambda: not n1.broker.router.has_route("dying/#", "n2@test"),
                timeout=8.0,
            )
            # publishing on n1 must not crash with the peer gone
            pub = Client(clientid="p", port=mqtt_port(n1))
            await pub.connect()
            await pub.publish("dying/t", b"x")
            await pub.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_hello_rejected_on_name_conflict():
    async def main():
        n1 = await start_cluster_node("same@test")
        n2 = await start_cluster_node("same@test", seeds=cluster_addr(n1))
        try:
            await asyncio.sleep(0.5)
            assert "same@test" not in n1.cluster.peers
            assert not any(p.up for p in n2.cluster.peers.values())
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_cluster_config_sync_two_phase():
    """emqx_conf analog: a validated config put on node A applies on
    node B; a joiner adopts runtime overrides from the snapshot; local
    validation failure broadcasts nothing."""
    async def main():
        n1 = await start_cluster_node("cs1@test")
        n2 = await start_cluster_node("cs2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)

            n1.config.put("mqtt.max_inflight", 7)
            assert await settle(
                lambda: n2.config.get("mqtt.max_inflight") == 7)

            # B -> A direction too
            n2.config.put("flapping_detect.max_count", 42)
            assert await settle(
                lambda: n1.config.get("flapping_detect.max_count") == 42)

            # invalid value: rejected locally, nothing broadcast
            with pytest.raises(Exception):
                n1.config.put("mqtt.max_inflight", "not-a-number")
            await asyncio.sleep(0.1)
            assert n2.config.get("mqtt.max_inflight") == 7

            # a NEW joiner adopts the overrides via snapshot bootstrap
            n3 = await start_cluster_node("cs3@test",
                                          seeds=cluster_addr(n1))
            try:
                assert await settle(
                    lambda: n3.config.get("mqtt.max_inflight") == 7
                    and n3.config.get("flapping_detect.max_count") == 42)
            finally:
                await n3.stop()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_config_sync_survives_origin_restart():
    """A restarted node's config updates must not be discarded by peers
    holding the previous life's txn high-water mark."""
    async def main():
        n1 = await start_cluster_node("cr1@test")
        n2 = await start_cluster_node("cr2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            for i in range(3):
                n1.config.put("mqtt.max_inflight", 10 + i)
            assert await settle(
                lambda: n2.config.get("mqtt.max_inflight") == 12)

            name = "cr1@test"
            await n1.stop()
            # same node name rejoins with a fresh Cluster instance
            n1b = await start_cluster_node(name, seeds=cluster_addr(n2))
            try:
                assert await peered(n1b, n2)
                n1b.config.put("mqtt.max_inflight", 99)
                assert await settle(
                    lambda: n2.config.get("mqtt.max_inflight") == 99)
            finally:
                await n1b.stop()
        finally:
            await n2.stop()

    run(main())


def test_retained_replicates_and_survives_node_loss():
    """VERDICT r4 item 5 (retained half): a retained message stored on
    node A is replicated into B's OWN retainer (emqx_retainer_mnesia
    replicated-table semantics) and still serves subscribe-replay on B
    after A dies."""

    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            pub = Client(clientid="rp", port=mqtt_port(n1))
            await pub.connect()
            await pub.publish("cfg/device/9", b"retained-cfg", retain=True)
            await pub.disconnect()
            # live replication into n2's local retainer
            assert await settle(
                lambda: n2.retainer.get("cfg/device/9") is not None
            )
            await n1.stop()     # A dies

            sub = Client(clientid="rs", port=mqtt_port(n2))
            await sub.connect()
            await sub.subscribe("cfg/+/9")
            msg = await sub.recv()
            assert (msg.topic, msg.payload, msg.retain) == \
                ("cfg/device/9", b"retained-cfg", True)
            await sub.disconnect()
        finally:
            await n2.stop()
            try:
                await n1.stop()
            except Exception:
                pass

    run(main())


def test_retained_delete_propagates_tombstone():
    """An empty-payload retained delete on A removes the topic from B's
    replica and a tombstone blocks resurrection via snapshot merge."""

    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            pub = Client(clientid="rp", port=mqtt_port(n1))
            await pub.connect()
            await pub.publish("gone/soon", b"x", retain=True)
            assert await settle(
                lambda: n2.retainer.get("gone/soon") is not None)
            await pub.publish("gone/soon", b"", retain=True)  # delete
            assert await settle(lambda: n2.retainer.get("gone/soon") is None)
            assert n2.cluster.durable._retain_tombstones.get("gone/soon")
            await pub.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


def test_durable_session_promoted_after_node_loss():
    """VERDICT r4 item 5 (session half): a persistent session created on
    A — subscriptions and queued QoS1 messages — is promoted from B's
    replica when A dies and the client reconnects to B."""

    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            c1 = Client(clientid="phoenix", port=mqtt_port(n1), proto_ver=5,
                        clean_start=False,
                        properties={"Session-Expiry-Interval": 300})
            await c1.connect()
            await c1.subscribe("dr/q", qos=1)
            await c1.disconnect()

            # wait for the route so a publish via n2 forwards to n1
            assert await settle(
                lambda: n2.broker.router.has_route("dr/q", "n1@test"))
            # a message lands while the client is away -> queued on n1
            pub = Client(clientid="p", port=mqtt_port(n2))
            await pub.connect()
            await pub.publish("dr/q", b"while-away", qos=1)
            await pub.disconnect()
            # the replica on n2 must include the queued message
            assert await settle(
                lambda: "phoenix" in n2.cluster.durable.session_replicas
                and (n2.cluster.durable.session_replicas["phoenix"][1]
                     .get("pending"))
            )
            await n1.stop()     # owner dies

            c2 = Client(clientid="phoenix", port=mqtt_port(n2), proto_ver=5,
                        clean_start=False)
            ack = await c2.connect()
            assert ack.session_present, "replica promotion lost the session"
            msg = await c2.recv()
            assert msg.payload == b"while-away"
            assert n2.cluster.durable.promotions == 1
            # the promoted session is live on n2: new publishes deliver
            pub2 = Client(clientid="p2", port=mqtt_port(n2))
            await pub2.connect()
            await pub2.publish("dr/q", b"after-failover", qos=1)
            msg = await c2.recv()
            assert msg.payload == b"after-failover"
            await pub2.disconnect()
            await c2.disconnect()
        finally:
            await n2.stop()
            try:
                await n1.stop()
            except Exception:
                pass

    run(main())


def test_clean_start_discards_replica():
    """A clean-start reconnect after owner death discards the replica
    instead of resurrecting old state."""

    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node("n2@test", seeds=cluster_addr(n1))
        try:
            assert await peered(n1, n2)
            c1 = Client(clientid="fresh", port=mqtt_port(n1), proto_ver=5,
                        clean_start=False,
                        properties={"Session-Expiry-Interval": 300})
            await c1.connect()
            await c1.subscribe("cs/q", qos=1)
            await c1.disconnect()
            assert await settle(
                lambda: "fresh" in n2.cluster.durable.session_replicas)
            await n1.stop()

            c2 = Client(clientid="fresh", port=mqtt_port(n2), proto_ver=5,
                        clean_start=True)
            ack = await c2.connect()
            assert not ack.session_present
            assert "fresh" not in n2.cluster.durable.session_replicas
            assert n2.cluster.durable.promotions == 0
            await c2.disconnect()
        finally:
            await n2.stop()
            try:
                await n1.stop()
            except Exception:
                pass

    run(main())


def test_replica_promotion_survives_full_restart(tmp_path):
    """The replica table is persisted: B restarts AFTER A died and can
    STILL promote A's durable session from its disk copy."""

    async def main():
        n1 = await start_cluster_node("n1@test")
        n2 = await start_cluster_node(
            "n2@test", seeds=cluster_addr(n1),
            extra=f'node.data_dir = "{tmp_path}/n2"\n')
        try:
            assert await peered(n1, n2)
            c1 = Client(clientid="lazarus", port=mqtt_port(n1), proto_ver=5,
                        clean_start=False,
                        properties={"Session-Expiry-Interval": 300})
            await c1.connect()
            await c1.subscribe("fr/q", qos=1)
            await c1.disconnect()
            assert await settle(
                lambda: "lazarus" in n2.cluster.durable.session_replicas)
            await n1.stop()
            await n2.stop()    # flushes session_replicas to disk

            n2b = await start_cluster_node(
                "n2@test",
                extra=f'node.data_dir = "{tmp_path}/n2"\n')
            try:
                assert "lazarus" in n2b.cluster.durable.session_replicas
                c2 = Client(clientid="lazarus", port=mqtt_port(n2b),
                            proto_ver=5, clean_start=False)
                ack = await c2.connect()
                assert ack.session_present
                assert "fr/q" in n2b.broker.sessions["lazarus"].subscriptions
                await c2.disconnect()
            finally:
                await n2b.stop()
        finally:
            try:
                await n1.stop()
            except Exception:
                pass

    run(main())


def test_reuseport_shared_port_across_cluster_nodes():
    """SO_REUSEPORT connection-plane scale-out (VERDICT r4 item 3): two
    clustered broker nodes bind the SAME MQTT port; the kernel spreads
    accepted connections across them and cross-node routing makes
    placement transparent to clients."""

    async def main():
        extra = 'listeners.tcp.default.reuse_port = true\n'
        n1 = await start_cluster_node("n1@test", extra=extra)
        port = mqtt_port(n1)
        n2 = await start_cluster_node(
            "n2@test", seeds=cluster_addr(n1),
            extra=extra + f'listeners.tcp.default.bind = "127.0.0.1:{port}"\n')
        try:
            assert await peered(n1, n2)
            assert mqtt_port(n2) == port
            # enough clients that the kernel hash lands on both sockets
            clients = []
            for i in range(24):
                c = Client(clientid=f"rp{i}", port=port)
                await c.connect()
                clients.append(c)
            placed1 = len(n1.connections)
            placed2 = len(n2.connections)
            assert placed1 + placed2 == 24
            assert placed1 > 0 and placed2 > 0, (
                f"kernel placed all connections on one node "
                f"({placed1}/{placed2}); reuse_port not balancing")
            # pub/sub across whatever placement happened: wait until the
            # NON-owning node learns the route toward rp0's actual home
            owner = "n1@test" if "rp0" in n1.connections else "n2@test"
            other = n2 if owner == "n1@test" else n1
            await clients[0].subscribe("rp/t", qos=1)
            assert await settle(
                lambda: other.broker.router.has_route("rp/t", owner))
            # publish from every other client: all must arrive
            for i in range(1, 24):
                await clients[i].publish("rp/t", f"m{i}".encode(), qos=1)
            got = set()
            for _ in range(23):
                got.add((await clients[0].recv(timeout=5)).payload)
            assert got == {f"m{i}".encode() for i in range(1, 24)}
            for c in clients:
                await c.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())
