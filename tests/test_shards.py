"""Connection-plane sharding (transport/shards.py): e2e delivery over
a multi-shard node, the marshal ordering discipline, cross-shard
takeover, listener aggregation, config gating, the batched handoff
contract, and the ``shard.handoff`` chaos seam."""

import asyncio

import pytest

from emqx_tpu import faultinject
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.mqtt import frame as F
from emqx_tpu.mqtt import packet as P
from emqx_tpu.node import BrokerNode
from emqx_tpu.transport.shards import Handoff


def run(coro):
    return asyncio.run(coro)


async def until(pred, timeout=8.0):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while not pred() and loop.time() < deadline:
        await asyncio.sleep(0.005)
    return pred()


async def start_node(shards=2, **cfg_puts):
    cfg = Config(file_text=(
        'listeners.tcp.default.bind = "127.0.0.1:0"\n'
        'broker.fanout.enable = true\n'
    ))
    cfg.put("tpu.enable", False)
    cfg.put("broker.conn.shards", shards)
    cfg.put("supervisor.backoff_base", 0.01)
    for k, v in cfg_puts.items():
        cfg.put(k, v)
    node = BrokerNode(cfg)
    await node.start()
    return node, node.listeners.all()[0].port


# ---------------------------------------------------------------------------
# e2e over shards
# ---------------------------------------------------------------------------

def test_sharded_node_qos1_exactly_once_and_aggregated_info():
    async def main():
        node, port = await start_node(shards=2)
        try:
            assert node.shard_pool is not None
            assert node.observed.metrics.get("broker.conn.shards") == 2
            sub = Client(clientid="s1", port=port)
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            pub = Client(clientid="p1", port=port)
            await pub.connect()
            for i in range(50):
                await pub.publish("t/x", b"m%d" % i, qos=1)
            msgs = []
            while len(msgs) < 50:
                msgs += await sub.recv_many(timeout=5)
            assert len(msgs) == 50
            assert [m.payload for m in msgs] == [b"m%d" % i
                                                 for i in range(50)]
            assert not any(m.dup for m in msgs)
            info = node.listeners.all()[0].info()
            # per-shard counts aggregate on the listener
            assert info["current_connections"] == 2
            assert sum(s["connections"] for s in info["shards"]) == 2
            assert all(s["alive"] for s in info["shards"])
            await sub.disconnect()
            await pub.disconnect()
            assert await until(
                lambda: node.listeners.all()[0].current_connections == 0)
        finally:
            await node.stop()

    run(main())


def test_sharded_node_qos2_exactly_once():
    async def main():
        node, port = await start_node(shards=2)
        try:
            sub = Client(clientid="s1", port=port)
            await sub.connect()
            await sub.subscribe("q/#", qos=2)
            pub = Client(clientid="p1", port=port)
            await pub.connect()
            for i in range(20):
                await pub.publish("q/x", b"m%d" % i, qos=2)
            msgs = []
            while len(msgs) < 20:
                msgs += await sub.recv_many(timeout=5)
            assert sorted(m.payload for m in msgs) == sorted(
                b"m%d" % i for i in range(20))
            assert len(msgs) == 20
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_pipelined_connect_subscribe_publish_in_one_write():
    """The marshal-queue ordering discipline: CONNECT + SUBSCRIBE +
    PUBLISH pipelined into one TCP segment must apply strictly in
    order (subscribe lands before the publish routes)."""
    async def main():
        node, port = await start_node(shards=2)
        try:
            r, w = await asyncio.open_connection("127.0.0.1", port)
            w.write(
                F.serialize(P.Connect(proto_ver=4, clientid="pipe",
                                      clean_start=True, keepalive=0))
                + F.serialize(P.Subscribe(
                    packet_id=1, topic_filters=[("loop/me", {"qos": 0})]))
                + F.serialize(P.Publish(qos=1, topic="loop/me",
                                        packet_id=7, payload=b"self"))
            )
            parser = F.Parser()
            got = []
            while not any(p.type == P.PUBLISH for p in got):
                data = await asyncio.wait_for(r.read(65536), 5)
                assert data
                got += parser.feed(data)
            types = [p.type for p in got]
            # CONNACK, SUBACK, PUBACK, then our own publish delivered
            assert types.index(P.CONNACK) < types.index(P.SUBACK)
            assert types.index(P.SUBACK) < types.index(P.PUBLISH)
            pub = [p for p in got if p.type == P.PUBLISH][0]
            assert pub.payload == b"self"
            w.close()
        finally:
            await node.stop()

    run(main())


def test_cross_shard_takeover():
    """A reconnect with the same clientid displaces the old connection
    even when the two land on different shards (the takeover routes to
    the owning loop)."""
    async def main():
        node, port = await start_node(shards=2)
        try:
            c1 = Client(clientid="dup", port=port)
            await c1.connect()
            c2 = Client(clientid="dup", port=port)
            await c2.connect()
            # old connection is closed by the broker
            assert await until(lambda: not c1.connected)
            # the new one is live
            await c2.subscribe("tk/1", qos=0)
            assert await until(
                lambda: node.connections.get("dup") is not None)
            await c2.disconnect()
        finally:
            await node.stop()

    run(main())


def test_shards_disabled_without_fanout_flag():
    async def main():
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", False)
        cfg.put("broker.conn.shards", 2)
        node = BrokerNode(cfg)
        await node.start()
        try:
            assert node.shard_pool is None   # flag off: PR-5 datapath
            c = Client(clientid="c", port=node.listeners.all()[0].port)
            await c.connect()
            await c.disconnect()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# the batched handoff
# ---------------------------------------------------------------------------

def test_handoff_batches_one_wakeup_per_drain():
    async def main():
        loop = asyncio.get_running_loop()
        got = []
        h = Handoff(loop, got.append, name="t")
        calls = []
        orig = loop.call_soon_threadsafe

        def spy(cb, *a):
            calls.append(cb)
            return orig(cb, *a)

        loop.call_soon_threadsafe = spy
        try:
            for i in range(100):
                h.put(i)
            # one scheduled drain for the whole burst
            assert len(calls) == 1
            await asyncio.sleep(0.01)
            assert got and got[0] == list(range(100))
            assert h.drains == 1 and h.items == 100
        finally:
            loop.call_soon_threadsafe = orig

    run(main())


def test_handoff_chaos_seam_drop_and_heal():
    """An injected ``shard.handoff`` drop loses one drained batch (the
    QoS0-style loss the seam models); subsequent traffic flows."""
    async def main():
        node, port = await start_node(shards=1)
        try:
            sub = Client(clientid="s", port=port)
            await sub.connect()
            await sub.subscribe("c/#", qos=0)
            pub = Client(clientid="p", port=port)
            await pub.connect()
            await pub.publish("c/x", b"pre", qos=1)
            got = [await sub.recv(timeout=5)]
            faultinject.install(FaultInjector(rules=[
                {"point": "shard.handoff", "action": "drop", "times": 1},
            ]))
            try:
                await pub.publish("c/x", b"lost", qos=1)
                await asyncio.sleep(0.1)
                await pub.publish("c/x", b"post", qos=1)
                got.append(await sub.recv(timeout=5))
            finally:
                faultinject.uninstall()
            payloads = [m.payload for m in got]
            assert payloads == [b"pre", b"post"]
            fired = faultinject.get() is None  # uninstalled
            assert fired
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_publish_runs_on_shard_fast_path():
    """A pipelined QoS1 burst from one client parses as a PublishRun on
    the shard and still delivers everything in order."""
    async def main():
        node, port = await start_node(shards=1)
        try:
            sub = Client(clientid="s", port=port)
            await sub.connect()
            await sub.subscribe("r/#", qos=0)
            r, w = await asyncio.open_connection("127.0.0.1", port)
            w.write(F.serialize(P.Connect(proto_ver=4, clientid="p",
                                          clean_start=True, keepalive=0)))
            parser = F.Parser()
            while not any(p.type == P.CONNACK for p in parser.feed(
                    await r.read(65536))):
                pass
            # one TCP segment with 8 QoS1 publishes → one PublishRun
            w.write(b"".join(
                F.serialize(P.Publish(qos=1, topic="r/t", packet_id=i + 1,
                                      payload=b"b%d" % i))
                for i in range(8)))
            msgs = []
            while len(msgs) < 8:
                msgs += await sub.recv_many(timeout=5)
            assert [m.payload for m in msgs] == [b"b%d" % i
                                                 for i in range(8)]
            # the ack burst came back (8 PUBACKs)
            acks = []
            while len(acks) < 8:
                data = await asyncio.wait_for(r.read(65536), 5)
                assert data
                for p in parser.feed(data):
                    if p.type == P.PUBACK:
                        acks.append(p.packet_id)
            assert acks == list(range(1, 9))
            w.close()
            await sub.disconnect()
        finally:
            await node.stop()

    run(main())
