"""PostgreSQL authn/authz backends against an in-test mock server that
speaks the v3 wire protocol (startup, md5 / cleartext / SCRAM-SHA-256
auth, extended query) — through full CONNECT/SUBSCRIBE round trips
(emqx_authn/postgresql, emqx_authz/postgresql analogs)."""

import asyncio
import hashlib
import struct

import pytest

from emqx_tpu.auth import AuthChain, Authz
from emqx_tpu.auth.authn import Credentials, hash_password
from emqx_tpu.auth.postgres import (
    PgClient, PgError, PostgresAuthenticator, PostgresAuthzSource,
    compile_template,
)
from emqx_tpu.auth.scram import ScramAuthenticator
from emqx_tpu.client import Client, MqttError
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


def _msg(kind: bytes, payload: bytes = b"") -> bytes:
    return kind + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class MockPg:
    """Minimal server side of the v3 protocol.

    ``tables`` maps a substring of the SQL (e.g. "mqtt_user") to a
    function(params) -> (cols, rows).  ``auth`` is "md5", "cleartext",
    "scram", or "trust".
    """

    def __init__(self, tables, *, auth="md5", user="broker",
                 password="dbpw"):
        self.tables = tables
        self.auth = auth
        self.user = user
        self.password = password
        self.queries = []
        self._conns = set()
        self.port = 0

    async def _read_msg(self, reader):
        head = await reader.readexactly(5)
        kind, ln = head[:1], struct.unpack("!I", head[1:])[0]
        return kind, await reader.readexactly(ln - 4)

    async def _authenticate(self, reader, writer) -> bool:
        if self.auth == "trust":
            writer.write(_msg(b"R", struct.pack("!I", 0)))
            return True
        if self.auth == "cleartext":
            writer.write(_msg(b"R", struct.pack("!I", 3)))
            await writer.drain()
            _, payload = await self._read_msg(reader)
            return payload.rstrip(b"\x00").decode() == self.password
        if self.auth == "md5":
            salt = b"\x01\x02\x03\x04"
            writer.write(_msg(b"R", struct.pack("!I", 5) + salt))
            await writer.drain()
            _, payload = await self._read_msg(reader)
            inner = hashlib.md5(
                self.password.encode() + self.user.encode()).hexdigest()
            want = "md5" + hashlib.md5(inner.encode() + salt).hexdigest()
            return payload.rstrip(b"\x00").decode() == want
        # SCRAM-SHA-256 via the repo's own server-side implementation
        sa = ScramAuthenticator()
        sa.add_user(self.user, self.password.encode())
        writer.write(_msg(b"R", struct.pack("!I", 10)
                          + _cstr("SCRAM-SHA-256") + b"\x00"))
        await writer.drain()
        _, payload = await self._read_msg(reader)
        mech_end = payload.index(b"\x00")
        (n,) = struct.unpack("!I", payload[mech_end + 1:mech_end + 5])
        client_first = payload[mech_end + 5:mech_end + 5 + n]
        verdict = sa.start("", self.user, client_first)
        if verdict[0] != "continue":
            return False
        _, server_first, state = verdict
        writer.write(_msg(b"R", struct.pack("!I", 11) + server_first))
        await writer.drain()
        _, payload = await self._read_msg(reader)
        verdict = sa.continue_auth(state, payload)
        if verdict[0] != "ok":
            return False
        writer.write(_msg(b"R", struct.pack("!I", 12) + verdict[3]))
        return True

    def _execute(self, sql, params):
        for needle, fn in self.tables.items():
            if needle in sql:
                return fn(params)
        return [], []

    async def start(self):
        async def handle(reader, writer):
            self._conns.add(writer)
            try:
                head = await reader.readexactly(8)
                _, proto = struct.unpack("!II", head)
                assert proto == 196608
                rest = (await reader.readexactly(
                    struct.unpack("!I", head[:4])[0] - 8))
                assert b"user\x00" in rest
                if not await self._authenticate(reader, writer):
                    writer.write(_msg(
                        b"E", b"SFATAL\x00C28P01\x00Mbad password\x00\x00"))
                    await writer.drain()
                    return
                writer.write(_msg(b"R", struct.pack("!I", 0))
                             + _msg(b"S", _cstr("server_version")
                                    + _cstr("16.0-mock"))
                             + _msg(b"Z", b"I"))
                await writer.drain()
                sql, params = "", []
                while True:
                    kind, payload = await self._read_msg(reader)
                    if kind == b"P":
                        end = payload.index(b"\x00")           # portal name
                        end2 = payload.index(b"\x00", end + 1)
                        sql = payload[end + 1:end2].decode()
                    elif kind == b"B":
                        off = payload.index(b"\x00") + 1       # portal
                        off = payload.index(b"\x00", off) + 1  # statement
                        (nfmt,) = struct.unpack("!H", payload[off:off + 2])
                        off += 2 + 2 * nfmt
                        (np,) = struct.unpack("!H", payload[off:off + 2])
                        off += 2
                        params = []
                        for _ in range(np):
                            (ln,) = struct.unpack("!i", payload[off:off + 4])
                            off += 4
                            if ln < 0:
                                params.append(None)
                            else:
                                params.append(payload[off:off + ln].decode())
                                off += ln
                    elif kind == b"S":
                        self.queries.append((sql, tuple(params)))
                        cols, rows = self._execute(sql, params)
                        out = [_msg(b"1"), _msg(b"2")]
                        coldesc = [struct.pack("!H", len(cols))]
                        for c in cols:
                            coldesc.append(
                                _cstr(c) + struct.pack(
                                    "!IHIhih", 0, 0, 25, -1, -1, 0))
                        out.append(_msg(b"T", b"".join(coldesc)))
                        for r in rows:
                            cells = [struct.pack("!H", len(r))]
                            for v in r:
                                if v is None:
                                    cells.append(struct.pack("!i", -1))
                                else:
                                    b = str(v).encode()
                                    cells.append(
                                        struct.pack("!I", len(b)) + b)
                            out.append(_msg(b"D", b"".join(cells)))
                        out.append(_msg(b"C", _cstr(f"SELECT {len(rows)}")))
                        out.append(_msg(b"Z", b"I"))
                        writer.write(b"".join(out))
                        await writer.drain()
                    elif kind == b"X":
                        return
            except Exception:
                pass
            finally:
                self._conns.discard(writer)
                writer.close()

        self.server = await asyncio.start_server(handle, "127.0.0.1", 0)
        self.port = self.server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        for w in list(self._conns):
            w.close()
        self.server.close()
        await self.server.wait_closed()


SALT = "pgsalt"


def user_table(params):
    if params and params[0] == "paula":
        return (["password_hash", "salt", "is_superuser"],
                [[hash_password(b"ppw", "sha256", SALT.encode()), SALT,
                  "f"]])
    return ["password_hash", "salt", "is_superuser"], []


def acl_table(params):
    if params and params[0] == "paula":
        return (["permission", "action", "topic"],
                [["allow", "all", "open/#"],
                 ["deny", "subscribe", "secret/#"],
                 ["allow", "publish", "wr/%u/own"]])
    return ["permission", "action", "topic"], []


async def start_node(auth_chain=None, authz=None):
    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    node = BrokerNode(cfg, auth_chain=auth_chain, authz=authz)
    await node.start()
    return node


def port_of(node):
    return node.listeners.all()[0].port


def test_compile_template():
    sql, vars_ = compile_template(
        "SELECT h FROM u WHERE n = ${username} AND c = ${clientid} "
        "OR n = ${username}")
    assert sql == "SELECT h FROM u WHERE n = $1 AND c = $2 OR n = $1"
    assert vars_ == ["username", "clientid"]
    assert compile_template("no placeholders") == ("no placeholders", [])


def test_pg_authn_and_authz_roundtrip():
    async def main():
        pg = await MockPg({"mqtt_user": user_table,
                           "mqtt_acl": acl_table}).start()
        server = f"127.0.0.1:{pg.port}"
        chain = AuthChain(allow_anonymous=False).add(
            PostgresAuthenticator(server, user="broker", password="dbpw"))
        authz = Authz(
            sources=[PostgresAuthzSource(server, user="broker",
                                         password="dbpw")],
            no_match="deny", cache_enable=False,
        )
        node = await start_node(auth_chain=chain, authz=authz)
        try:
            ok = Client(clientid="c1", port=port_of(node),
                        username="paula", password=b"ppw")
            await ok.connect()
            assert await ok.subscribe("open/news") == [0]
            assert (await ok.subscribe("secret/x"))[0] >= 0x80
            # publish-only rule must not grant subscribe
            assert (await ok.subscribe("wr/paula/own"))[0] >= 0x80
            await ok.disconnect()

            bad = Client(clientid="c2", port=port_of(node),
                         username="paula", password=b"wrong")
            with pytest.raises(MqttError):
                await bad.connect()
            # unknown user -> ignore -> anonymous policy (deny)
            unk = Client(clientid="c3", port=port_of(node),
                         username="ghost", password=b"x")
            with pytest.raises(MqttError):
                await unk.connect()
            # the SQL went through Bind parameters, never spliced
            assert any(p == ("paula",) for _, p in pg.queries)
            assert all("paula" not in q for q, _ in pg.queries)
        finally:
            await node.stop()
            await pg.stop()

    run(main())


def test_pg_scram_and_cleartext_server_auth():
    async def main():
        for mode in ("scram", "cleartext", "trust"):
            pg = await MockPg({"mqtt_user": user_table},
                              auth=mode).start()
            a = PostgresAuthenticator(f"127.0.0.1:{pg.port}",
                                      user="broker", password="dbpw")
            res = await a.authenticate_async(
                Credentials("c", "paula", b"ppw"))
            assert res.outcome == "ok", mode
            await pg.stop()

    run(main())


def test_pg_bad_db_password_and_down_server_ignore():
    async def main():
        pg = await MockPg({"mqtt_user": user_table}).start()
        wrong = PostgresAuthenticator(f"127.0.0.1:{pg.port}",
                                      user="broker", password="nope",
                                      timeout=2.0)
        res = await wrong.authenticate_async(
            Credentials("c", "paula", b"ppw"))
        assert res.outcome == "ignore"   # infra failure never denies
        await pg.stop()

        dead = PostgresAuthenticator("127.0.0.1:1", timeout=0.3)
        res = await dead.authenticate_async(Credentials("c", "paula", b"p"))
        assert res.outcome == "ignore"

        deadz = PostgresAuthzSource("127.0.0.1:1", timeout=0.3)
        out = await deadz.prefetch_async("c", "paula", None, "publish", "t")
        assert out == "nomatch"

    run(main())


def test_pg_client_reconnects_after_drop():
    async def main():
        pg = await MockPg({"mqtt_user": user_table}).start()
        c = PgClient(f"127.0.0.1:{pg.port}", user="broker",
                     password="dbpw")
        cols, rows = await c.query(
            "SELECT password_hash, salt, is_superuser FROM mqtt_user "
            "WHERE username = $1", ("paula",))
        assert cols[0] == "password_hash" and len(rows) == 1
        # sever every server-side connection; next query must reconnect
        for w in list(pg._conns):
            w.close()
        await asyncio.sleep(0.05)
        with pytest.raises(Exception):
            await c.query("SELECT 1 FROM mqtt_user WHERE username = $1",
                          ("paula",))
        cols, rows = await c.query(
            "SELECT 1 FROM mqtt_user WHERE username = $1", ("ghost",))
        assert rows == []
        await c.close()
        await pg.stop()

    run(main())
