"""Snappy codec + CRC-32C + Kafka batch compression.

Reference analog: snappy/crc32cer NIFs in the reference's Kafka bridge
dep tree (SURVEY.md §2.4).  The native and pure-Python paths must agree
byte-for-byte on decode and produce mutually-decodable encodings.
"""

import os
import random

import pytest

from emqx_tpu.native import snappy as sz
from emqx_tpu.bridge.kafka import (
    crc32c, parse_batches, parse_record_batch, record_batch,
)


def _cases():
    random.seed(1234)
    return [
        b"",
        b"x",
        b"abc" * 1,
        b"ab" * 5000,                       # highly compressible
        os.urandom(4096),                   # incompressible
        bytes(random.randrange(4) for _ in range(150000)),  # >64K window
        ("the quick brown fox " * 997).encode(),
        os.urandom(3) + b"\x00" * 70000 + os.urandom(3),    # long run
    ]


def test_roundtrip_native_and_python():
    for d in _cases():
        c = sz.compress(d)
        assert sz.decompress(c) == d
        assert sz._py_decompress(c) == d          # py decodes native
        pc = sz._py_compress(d)
        assert sz.decompress(pc) == d             # native decodes py
        assert sz._py_decompress(pc) == d


def test_compression_actually_compresses():
    if not sz.available():
        pytest.skip("no native toolchain")
    d = b"ab" * 5000
    assert len(sz.compress(d)) < len(d) // 10


def test_xerial_roundtrip_and_bare_fallback():
    for d in _cases():
        assert sz.decompress_xerial(sz.compress_xerial(d)) == d
    # a bare raw block (non-Java producers) is accepted too
    assert sz.decompress_xerial(sz.compress(b"hello")) == b"hello"


def test_xerial_multiblock():
    d = os.urandom(100000)                        # > one 32K block
    x = sz.compress_xerial(d)
    assert x.startswith(b"\x82SNAPPY\x00")
    assert sz.decompress_xerial(x) == d


def test_corrupt_input_raises():
    good = sz.compress(b"hello world, hello world, hello world")
    for bad in (b"", b"\xff\xff\xff\xff\xff\xff",  # overlong preamble
                good[:-2],                         # truncated
                b"\x05\x09\x00\x01"):              # copy before start
        with pytest.raises(ValueError):
            sz.decompress(bad)
        with pytest.raises(ValueError):
            sz._py_decompress(bad)


def test_crc32c_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43
    # incremental == one-shot, native == python
    a, b = os.urandom(1023), os.urandom(77)
    assert crc32c(b, crc32c(a)) == crc32c(a + b)
    assert sz._py_crc32c(a + b) == crc32c(a + b)


@pytest.mark.parametrize("codec", ["snappy", "gzip"])
def test_record_batch_compressed_roundtrip(codec):
    recs = [(b"k%d" % i, os.urandom(50) + b"value" * i)
            for i in range(20)] + [(None, b"no-key")]
    batch = record_batch(recs, compression=codec)
    assert parse_record_batch(batch) == recs
    # and through the fetch-side concatenated-stream parser
    out, nxt, skipped = parse_batches(batch)
    assert skipped == 0
    assert [(k, v) for _, k, v in out] == recs
    assert nxt == len(recs)


def test_record_batch_snappy_smaller_on_redundant_payloads():
    if not sz.available():
        pytest.skip("no native toolchain")
    recs = [(None, b"sensor/temperature reading=21.5 unit=C " * 8)
            for _ in range(64)]
    assert len(record_batch(recs, compression="snappy")) \
        < len(record_batch(recs)) // 4


def test_reserved_codec_batch_skipped_with_offset_advance():
    """Codec bits 5-7 are reserved/unknown: skip, never stall (zstd
    — codec 4 — now DECODES; see test_zstd.py)."""
    batch = bytearray(record_batch([(b"k", b"v")]))
    import struct
    attrs_off = 21
    struct.pack_into("!h", batch, attrs_off, 6)
    after = bytes(batch[attrs_off:])
    struct.pack_into("!I", batch, 17, crc32c(after))
    out, nxt, skipped = parse_batches(bytes(batch))
    assert out == [] and skipped == 1 and nxt == 1


def test_zstd_codec_bit_with_garbage_payload_is_an_error():
    """A batch FLAGGED zstd whose records section is not a zstd frame
    is a producer bug (CRC already passed) — surfaced as KafkaError,
    not silently skipped."""
    from emqx_tpu.bridge.kafka import KafkaError
    from emqx_tpu.native import zstd as _zs
    if not _zs.available():
        pytest.skip("no native toolchain")
    batch = bytearray(record_batch([(b"k", b"v")]))
    import struct
    attrs_off = 21
    struct.pack_into("!h", batch, attrs_off, 4)
    after = bytes(batch[attrs_off:])
    struct.pack_into("!I", batch, 17, crc32c(after))
    with pytest.raises(KafkaError):
        parse_batches(bytes(batch))


def test_kafka_connector_rejects_unknown_codec():
    from emqx_tpu.bridge.kafka import KafkaConnector
    with pytest.raises(ValueError):
        KafkaConnector({"compression": "brotli"})
    KafkaConnector({"compression": "snappy"})     # accepted
    KafkaConnector({"compression": "zstd"})       # accepted (round 5)
    KafkaConnector({"compression": "none"})
    KafkaConnector({})


def test_compressed_control_batch_still_skipped():
    """attrs = snappy|control must be skipped like any control batch,
    never surfaced as data (review finding, round 5)."""
    import struct
    batch = bytearray(record_batch([(b"k", b"v")], compression="snappy"))
    attrs_off = 21
    (attrs,) = struct.unpack_from("!h", batch, attrs_off)
    struct.pack_into("!h", batch, attrs_off, attrs | 0x20)
    after = bytes(batch[attrs_off:])
    struct.pack_into("!I", batch, 17, crc32c(after))
    out, nxt, skipped = parse_batches(bytes(batch))
    assert out == [] and skipped == 1 and nxt == 1


def test_hostile_preamble_rejected_before_allocation():
    """A few-byte input claiming a ~4 GiB uncompressed size must be
    rejected by the sanity cap, not allocated (review finding, r5)."""
    import struct
    # varint 0xFFFFFFFF (4 GiB - 1) + one tag byte
    hostile = b"\xff\xff\xff\xff\x0f" + b"\x00"
    for fn in (sz.decompress, sz._py_decompress):
        with pytest.raises(ValueError):
            fn(hostile)
    # ...and via the Kafka fetch path (xerial framing)
    framed = (b"\x82SNAPPY\x00" + struct.pack("!ii", 1, 1)
              + struct.pack("!i", len(hostile)) + hostile)
    with pytest.raises(ValueError):
        sz.decompress_xerial(framed)
    # legitimate high-ratio input still fine (well under the cap)
    big = b"\x00" * 200000
    assert sz.decompress(sz.compress(big)) == big


def test_record_batch_lz4_roundtrip():
    recs = [(b"k%d" % i, os.urandom(40) + b"telemetry" * (i % 7))
            for i in range(25)] + [(None, b"tail")]
    batch = record_batch(recs, compression="lz4")
    assert parse_record_batch(batch) == recs
    out, nxt, skipped = parse_batches(batch)
    assert skipped == 0 and [(k, v) for _, k, v in out] == recs
