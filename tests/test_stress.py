"""Concurrency stress harness — SURVEY.md §5.2: the host control plane
(asyncio, table mutation vs snapshot shipping) needs explicit stress
coverage since there is no BEAM share-nothing safety net.

One live node with the device match path pinned on; many concurrent
actors churning connect/subscribe/publish/unsubscribe/disconnect,
config hot-updates, rule create/delete, and management kicks — while
invariant checkers assert:

* every delivery a subscriber receives matches one of ITS filters at
  some point in its lifetime (no cross-wiring);
* the broker's route table and the device mirror converge once churn
  stops (no leaked filters, refcounts clean);
* no actor crashes, the node stays responsive.
"""

import asyncio
import random

import pytest

from emqx_tpu import topic as T
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=20.0, interval=0.05):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


FILTER_POOL = [
    "s/+/t", "s/#", "s/1/t", "q/+/+/r", "q/a/b/r", "w/#", "w/x/+",
    "deep/a/b/c/d/e/+", "plain/topic", "+/mid/+",
]
TOPIC_POOL = [
    "s/1/t", "s/2/t", "s/9/zz", "q/a/b/r", "q/z/z/r", "w/x/y",
    "deep/a/b/c/d/e/f", "plain/topic", "n/mid/n", "nomatch/at/all",
]


def test_churn_storm_invariants():
    async def main():
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", True)
        cfg.put("tpu.mirror_refresh_interval", 0.005)
        cfg.put("tpu.bypass_rate", 0.0)
        node = BrokerNode(cfg)
        await node.start()
        port = node.listeners.all()[0].port
        rng = random.Random(1234)
        errors: list = []
        violations: list = []
        stop = asyncio.Event()

        async def subscriber(n):
            """Churning subscriber that validates every delivery against
            the set of filters it EVER held this connection."""
            try:
                while not stop.is_set():
                    c = Client(clientid=f"sub{n}", port=port)
                    await c.connect()
                    held = set()
                    for _ in range(rng.randint(2, 12)):
                        if stop.is_set():
                            break
                        roll = rng.random()
                        if roll < 0.5 or not held:
                            f = rng.choice(FILTER_POOL)
                            await c.subscribe(f, qos=rng.randint(0, 1))
                            held.add(f)
                        elif roll < 0.7:
                            f = rng.choice(sorted(held))
                            await c.unsubscribe(f)
                            # deliveries already queued may still arrive:
                            # keep it in `held` for validation purposes
                        else:
                            try:
                                msg = await c.recv(timeout=0.05)
                                if not any(T.match(msg.topic, f)
                                           for f in held):
                                    violations.append(
                                        (f"sub{n}", msg.topic, sorted(held)))
                            except asyncio.TimeoutError:
                                pass
                        await asyncio.sleep(rng.random() * 0.01)
                    await c.disconnect()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - harness records all
                errors.append(("subscriber", n, repr(e)))

        async def publisher(n):
            try:
                c = Client(clientid=f"pub{n}", port=port)
                await c.connect()
                while not stop.is_set():
                    await c.publish(rng.choice(TOPIC_POOL),
                                    f"m{n}".encode(), qos=rng.randint(0, 1))
                    await asyncio.sleep(rng.random() * 0.004)
                await c.disconnect()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                errors.append(("publisher", n, repr(e)))

        async def admin():
            try:
                i = 0
                while not stop.is_set():
                    i += 1
                    roll = rng.random()
                    if roll < 0.4:
                        node.config.put("mqtt.max_inflight",
                                        rng.randint(8, 64))
                    elif roll < 0.7:
                        rid = f"sr{i % 3}"
                        if rid in node.rule_engine.rules:
                            node.rule_engine.delete_rule(rid)
                        else:
                            node.rule_engine.create_rule(
                                rid, f'SELECT * FROM "{rng.choice(FILTER_POOL)}"')
                    else:
                        node.kick_client(f"sub{rng.randint(0, 3)}")
                    await asyncio.sleep(0.03)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                errors.append(("admin", 0, repr(e)))

        actors = [asyncio.ensure_future(subscriber(i)) for i in range(4)]
        actors += [asyncio.ensure_future(publisher(i)) for i in range(3)]
        actors.append(asyncio.ensure_future(admin()))
        await asyncio.sleep(6.0)
        stop.set()
        await asyncio.gather(*actors, return_exceptions=True)

        assert not violations, violations[:5]
        # connection churn races management kicks: losing a socket (and
        # the in-flight request that dies with it) is expected collateral;
        # anything else is a bug
        benign = ("ConnectionError", "ConnectionResetError",
                  "IncompleteReadError", "connection closed",
                  "TimeoutError", "kick")
        real = [e for e in errors
                if not any(b.lower() in e[2].lower() for b in benign)]
        assert not real, real[:5]

        # node still responsive after the storm
        probe = Client(clientid="probe", port=port)
        await probe.connect()
        await probe.subscribe("s/1/t")
        await probe.publish("s/1/t", b"alive")
        msg = await probe.recv(timeout=5)
        assert msg.payload == b"alive"
        await probe.disconnect()

        # mirror converges with the router once churn stops
        ms = node.match_service
        if ms is not None:
            assert await settle(
                lambda: set(node.broker.router.wildcard_filters())
                == {f for f, n in ms._ref.items() if n > 0}
            ), "device mirror diverged from the router"
        await node.stop()

    run(main())


def test_session_takeover_storm():
    """Rapid same-clientid reconnects (the classic takeover race):
    exactly one live session survives, no exceptions leak."""
    async def main():
        node = BrokerNode(Config(
            file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n'))
        await node.start()
        port = node.listeners.all()[0].port
        errors = []

        async def fighter(k):
            for _ in range(15):
                try:
                    c = Client(clientid="contested", port=port,
                               clean_start=False)
                    await c.connect()
                    await c.subscribe("fight/#")
                    await asyncio.sleep(random.random() * 0.02)
                    await c.close()
                except (ConnectionError, asyncio.IncompleteReadError,
                        asyncio.TimeoutError, OSError):
                    pass  # takeover races close sockets mid-handshake
                except Exception as e:  # noqa: BLE001
                    # the LOSER of each takeover gets its socket closed
                    # mid-request — correct behavior, not a defect
                    if "connection closed" not in repr(e).lower() and \
                            "taken over" not in repr(e).lower():
                        errors.append(repr(e))

        await asyncio.gather(*[fighter(k) for k in range(5)])
        assert not errors, errors[:5]
        assert len([c for c in node.broker.sessions
                    if c == "contested"]) <= 1
        # the surviving session still works
        c = Client(clientid="contested", port=port, clean_start=False)
        await c.connect()
        pub = Client(clientid="p", port=port)
        await pub.connect()
        await pub.publish("fight/ok", b"won")
        msg = await c.recv(timeout=5)
        assert msg.payload == b"won"
        await c.disconnect()
        await pub.disconnect()
        await node.stop()

    run(main())
