"""Chaos suite: kill/wound each delivery subsystem MID-TRAFFIC and
assert the invariants PR 1/2 established survive supervised recovery —
QoS1 delivery_ratio 1.0 after recovery, zero DUPs on the clean path,
fanout remainder re-queued under injected cancellation, and restart
counts visible on ``broker.supervisor.*``."""

import asyncio

import pytest

from emqx_tpu import faultinject
from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, make_message
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.observe.metrics import Metrics
from emqx_tpu.supervise import Supervisor


def run(coro):
    return asyncio.run(coro)


async def until(pred, timeout=8.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not pred() and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.002)
    return pred()


def fast_sup(**kw):
    kw.setdefault("backoff_base", 0.001)
    kw.setdefault("backoff_max", 0.01)
    kw.setdefault("jitter", 0.0)
    return Supervisor(**kw)


# ---------------------------------------------------------------------------
# 1. fanout pipeline killed mid-traffic (QoS1, acks flowing)
# ---------------------------------------------------------------------------

def test_chaos_fanout_kill_midtraffic_qos1_exactly_once():
    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        sess, _ = b.open_session("sub", max_inflight=64)
        b.subscribe("sub", "t/#", SubOpts(qos=1))
        got = []
        dups = [0]

        def on_deliver(cid, pubs):
            # an acking QoS1 consumer: every grant PUBACKs immediately
            # so the window keeps moving through kills
            stack = list(pubs)
            while stack:
                p = stack.pop(0)
                got.append(bytes(p.msg.payload))
                if p.msg.dup:
                    dups[0] += 1
                if p.pid is not None:
                    _, more = sess.puback(p.pid)
                    stack.extend(more)

        b.on_deliver = on_deliver
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m)
        await p.start()
        b.fanout = p
        n = 400
        for i in range(n):
            assert p.offer(make_message("pub", "t/x", b"%d" % i, qos=1))
            if i % 50 == 49:
                p._child.kill()             # wound the drain loop
                await asyncio.sleep(0.003)  # let the restart land
        assert await until(lambda: len(got) >= n)
        # delivery_ratio 1.0, exactly once, zero DUPs, order preserved
        assert [int(x) for x in got] == list(range(n))
        assert dups[0] == 0
        assert m.get("broker.supervisor.restarts") >= 1
        await p.stop()
        await sup.stop()

    run(main())


def test_chaos_fanout_injected_drain_faults_recover():
    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            int(p.msg.payload) for p in pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m)
        await p.start()
        b.fanout = p
        inj = faultinject.install(FaultInjector([
            {"point": "fanout.drain", "action": "raise",
             "skip": 1, "times": 2},
        ]))
        try:
            n = 100
            for i in range(n):
                assert p.offer(make_message("pub", "t", b"%d" % i))
                if i % 20 == 0:
                    await asyncio.sleep(0.002)
            assert await until(lambda: len(got) == n)
            assert got == list(range(n))    # nothing lost, in order
            assert inj.fired.get("fanout.drain") == 2
            assert m.get("broker.supervisor.restarts") == 2
        finally:
            faultinject.uninstall()
        await p.stop()
        await sup.stop()

    run(main())


def test_chaos_overload_sheds_per_policy():
    """Sustained (injected) overload: QoS0 drops first, retained
    defers until the overload clears, QoS1 keeps flowing."""
    from emqx_tpu.broker.olp import Olp

    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        olp = Olp(max_queue_depth=10, cooloff=0.05)
        sess, _ = b.open_session("sub", max_inflight=256)
        b.subscribe("sub", "t", SubOpts(qos=1))
        got = []

        def on_deliver(cid, pubs):
            stack = list(pubs)
            while stack:
                p = stack.pop(0)
                got.append(bytes(p.msg.payload))
                if p.pid is not None:
                    _, more = sess.puback(p.pid)
                    stack.extend(more)

        b.on_deliver = on_deliver
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m,
                           olp=olp)
        await p.start()
        b.fanout = p
        olp.report(queue_depth=100)         # overload signal
        assert olp.overloaded()
        assert p.offer(make_message("pub", "t", b"q0"))         # shed
        assert m.get("broker.olp.shed_qos0") == 1
        retained = make_message("pub", "t", b"ret", retain=True)
        assert p.offer(retained)                                 # deferred
        assert m.get("broker.olp.deferred") == 1
        assert len(p._deferred) == 1
        assert p.offer(make_message("pub", "t", b"q1", qos=1))  # flows
        assert await until(lambda: b"q1" in got)
        assert b"q0" not in got
        # overload clears → the deferred retained publish is delivered
        await asyncio.sleep(0.06)           # past cooloff
        olp.report(queue_depth=0)
        assert not olp.overloaded()
        p.offer(make_message("pub", "t", b"after", qos=1))  # wake drain
        assert await until(lambda: b"ret" in got and b"after" in got)
        await p.stop()
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# 2. cluster replication loop killed mid-traffic
# ---------------------------------------------------------------------------

async def _start_cluster_node(name, seeds=""):
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    cfg = Config(file_text=(
        f'node.name = "{name}"\n'
        'listeners.tcp.default.bind = "127.0.0.1:0"\n'
        'cluster.enable = true\n'
        'cluster.listen = "127.0.0.1:0"\n'
        f'cluster.seeds = "{seeds}"\n'
        'cluster.heartbeat_interval = 200ms\n'
        'cluster.node_timeout = 1500ms\n'
    ))
    cfg.put("tpu.enable", False)
    node = BrokerNode(cfg)
    await node.start()
    node.cluster.SYNC_INTERVAL = 0.02
    node.cluster.RECONNECT_INTERVAL = 0.3
    return node


def test_chaos_cluster_sync_loop_kill_recovers():
    from emqx_tpu.client import Client

    async def main():
        n1 = await _start_cluster_node("c1@chaos")
        n2 = await _start_cluster_node(
            "c2@chaos", seeds=f"127.0.0.1:{n1.cluster.listen_port}")
        try:
            assert await until(
                lambda: n2.cluster.name in n1.cluster.peers
                and n1.cluster.peers[n2.cluster.name].up
                and n1.cluster.name in n2.cluster.peers
                and n2.cluster.peers[n1.cluster.name].up)
            # wound n1's route-replication loop mid-operation
            child = n1.supervisor.lookup("cluster.sync")
            assert child is not None and child.kill()
            # a subscription taken on n1 AFTER the kill must still
            # replicate (the restarted loop re-broadcasts the delta)
            sub = Client(clientid="s1",
                         port=n1.listeners.all()[0].port)
            await sub.connect()
            await sub.subscribe("chaos/+/x", qos=1)
            assert await until(
                lambda: n2.broker.router.match_routes("chaos/a/x"))
            # and forwarding works end to end: publish on n2 → n1 sub
            pub = Client(clientid="p1",
                         port=n2.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("chaos/a/x", b"hello", qos=1)
            got = await sub.recv(timeout=5)
            assert got.payload == b"hello"
            assert n1.observed.metrics.get(
                "broker.supervisor.restarts") >= 1
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


# ---------------------------------------------------------------------------
# 3. bridge sink killed + wounded mid-traffic
# ---------------------------------------------------------------------------

def test_chaos_bridge_sink_kill_and_fault_at_least_once():
    from emqx_tpu.bridge.resource import BufferedWorker, Connector

    class SinkConnector(Connector):
        def __init__(self):
            self.got = []

        async def send(self, items):
            self.got.extend(items)

    async def main():
        m = Metrics()
        sup = fast_sup(metrics=m)
        conn = SinkConnector()
        w = BufferedWorker(conn, name="chaos", batch_size=4,
                           retry_base=0.001, retry_max=0.01)
        w.supervisor = sup
        await w.start()
        inj = faultinject.install(FaultInjector([
            {"point": "bridge.sink", "action": "raise",
             "skip": 3, "times": 2},
        ]))
        try:
            items = [f"item-{i}" for i in range(40)]
            for i, it in enumerate(items):
                w.enqueue(it)
                if i == 20:
                    w._tasks[0].kill()      # wound the worker loop
                    await asyncio.sleep(0.002)
                await asyncio.sleep(0)
            assert await until(lambda: set(conn.got) >= set(items))
            # at-least-once into the remote; the injected SendErrors
            # rode the normal retry/backoff path
            assert inj.fired.get("bridge.sink") == 2
            assert w.metrics["retried"] >= 1
            assert m.get("broker.supervisor.restarts") >= 1
            assert w.status == "connected"
        finally:
            faultinject.uninstall()
        await w.stop()
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# 4. exhook channel killed mid-stream
# ---------------------------------------------------------------------------

def test_chaos_exhook_sender_kill_recovers():
    grpc = pytest.importorskip("grpc")  # noqa: F841  (manager imports it)
    import types

    from emqx_tpu.exhook.manager import (
        ExHookManager, ServerSpec, _ServerState,
    )

    class FakeStub:
        def __init__(self):
            self.calls = []

        def OnClientConnected(self, req):
            async def go():
                self.calls.append(req)
            return go()

    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        node = types.SimpleNamespace(broker=b, supervisor=sup,
                                     started_at=0.0)
        mgr = ExHookManager(node, [])
        st = _ServerState(spec=ServerSpec(name="s1", url="inproc"))
        st.stub = FakeStub()
        st.hooks = ["client.connected"]
        mgr.servers = [st]
        st.sender = sup.start_child("exhook.sender.s1",
                                    lambda: mgr._sender_loop(st))
        for i in range(3):
            st.queue.put_nowait(("OnClientConnected", i))
        assert await until(lambda: len(st.stub.calls) == 3)
        # wound the notification channel mid-stream
        assert st.sender.kill()
        for i in range(3, 6):
            st.queue.put_nowait(("OnClientConnected", i))
        assert await until(lambda: len(st.stub.calls) == 6)
        assert st.stub.calls == list(range(6))
        assert m.get("broker.supervisor.restarts") >= 1
        st.sender.cancel()
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# 5. transport write faults heal through the retry machinery
# ---------------------------------------------------------------------------

def test_chaos_injected_cluster_frame_drops_heal():
    """Dropped cluster frames (the cast seam) must not wedge
    replication: the seq-gap detection re-bootstraps."""
    from emqx_tpu.client import Client

    async def main():
        n1 = await _start_cluster_node("d1@chaos")
        n2 = await _start_cluster_node(
            "d2@chaos", seeds=f"127.0.0.1:{n1.cluster.listen_port}")
        try:
            assert await until(
                lambda: n2.cluster.name in n1.cluster.peers
                and n1.cluster.peers[n2.cluster.name].up)
            inj = faultinject.install(FaultInjector([
                # drop a few cluster frames, then run clean
                {"point": "cluster.rpc", "action": "drop", "times": 3},
            ]))
            try:
                sub = Client(clientid="s1",
                             port=n1.listeners.all()[0].port)
                await sub.connect()
                await sub.subscribe("heal/#", qos=1)
                # keep mutating the route table: once the drops exhaust,
                # the next delta batch exposes the seq gap and the
                # receiver re-bootstraps (snapshot covers heal/#)
                for i in range(20):
                    await sub.subscribe(f"heal{i}/#", qos=0)
                    await asyncio.sleep(0.1)
                    if n2.broker.router.match_routes("heal/x"):
                        break
                assert n2.broker.router.match_routes("heal/x")
                assert inj.fired.get("cluster.rpc", 0) >= 1
                await sub.disconnect()
            finally:
                faultinject.uninstall()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


# ---------------------------------------------------------------------------
# 8. gateway datapaths under injected transport.write drops
# ---------------------------------------------------------------------------

async def _start_gateway_node(extra=""):
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    cfg = Config(file_text=(
        'listeners.tcp.default.bind = "127.0.0.1:0"\n' + extra))
    node = BrokerNode(cfg)
    await node.start()
    return node


def test_chaos_mqttsn_retry_heals_dropped_delivery():
    """An injected drop of the first QoS1 PUBLISH datagram to an
    MQTT-SN client: the gateway retry sweep must resend it, the client
    acks the redelivery, and the message lands exactly once with the
    session inflight drained (the peek/commit retry path — no
    committed resend for the dropped write's interval is required,
    only eventual delivery)."""
    import socket as _socket
    import struct

    from emqx_tpu.gateway.base import GatewayManager

    old_interval = GatewayManager.RETRY_INTERVAL
    GatewayManager.RETRY_INTERVAL = 0.05

    async def main():
        node = await _start_gateway_node(
            'gateway.mqttsn.enable = true\n'
            'gateway.mqttsn.bind = "127.0.0.1:0"\n')
        try:
            port = node.gateways.gateways["mqttsn"].port

            class Sn:
                def __init__(self):
                    self.sock = _socket.socket(_socket.AF_INET,
                                               _socket.SOCK_DGRAM)
                    self.sock.settimeout(5.0)
                    self.addr = ("127.0.0.1", port)

                def send(self, t, body=b""):
                    self.sock.sendto(bytes([len(body) + 2, t]) + body,
                                     self.addr)

                def recv(self, timeout=5.0):
                    self.sock.settimeout(timeout)
                    data, _ = self.sock.recvfrom(2048)
                    return data[1], data[2:data[0]]

            sn = Sn()

            def handshake():
                sn.send(0x04, bytes([0x04, 0x01])
                        + struct.pack(">H", 300) + b"sn-chaos")
                t, body = sn.recv()
                assert t == 0x05 and body[0] == 0
                # SUBSCRIBE qos1, concrete topic name
                sn.send(0x12, bytes([0x20]) + struct.pack(">H", 2)
                        + b"sn/q1")
                t, body = sn.recv()
                assert t == 0x13 and body[-1] == 0

            await asyncio.to_thread(handshake)
            # entries become due after the SESSION retry interval; the
            # sweep period only bounds how often the gateway looks
            node.broker.sessions["sn-chaos"].retry_interval = 0.02

            from emqx_tpu.client import Client

            mq = Client(clientid="mp",
                        port=node.listeners.all()[0].port)
            await mq.connect()
            inj = faultinject.install(FaultInjector([
                {"point": "transport.write", "action": "drop", "times": 1},
            ]))
            try:
                rc = await mq.publish("sn/q1", b"heal-me", qos=1)
                assert rc == 0
                assert inj.fired.get("transport.write") == 1

                def recv_retry_and_ack():
                    # first copy dropped on the wire; the retry sweep
                    # (50 ms) must resend it
                    t, body = sn.recv(timeout=5.0)
                    assert t == 0x0C, (t, body)
                    assert body[5:] == b"heal-me"
                    mid = struct.unpack(">H", body[3:5])[0]
                    assert mid != 0          # QoS1 delivery carries a pid
                    # PUBACK: topicid + msgid + rc
                    sn.send(0x0D, body[1:3] + struct.pack(">H", mid)
                            + b"\x00")
                    # exactly once: no further PUBLISH arrives
                    try:
                        t2, body2 = sn.recv(timeout=0.4)
                    except _socket.timeout:
                        return None
                    return (t2, body2)

                extra = await asyncio.to_thread(recv_retry_and_ack)
                assert extra is None, extra
                sess = node.broker.sessions.get("sn-chaos")
                assert await until(
                    lambda: sess is not None and len(sess.inflight) == 0)
                await mq.disconnect()
            finally:
                faultinject.uninstall()
            sn.sock.close()
        finally:
            await node.stop()

    try:
        run(main())
    finally:
        GatewayManager.RETRY_INTERVAL = old_interval


def test_chaos_coap_con_dedup_heals_dropped_reply():
    """An injected drop of a CoAP CON response: the client's protocol
    retransmit (same message id) must be answered from the §4.2 dedup
    cache — identical response bytes, and the publish side effect
    fires exactly once."""
    import socket as _socket

    async def main():
        from emqx_tpu.client import Client
        from emqx_tpu.gateway import coap as C

        node = await _start_gateway_node(
            'gateway.coap.enable = true\n'
            'gateway.coap.bind = "127.0.0.1:0"\n')
        try:
            cport = node.gateways.gateways["coap"].port
            mq = Client(clientid="watch",
                        port=node.listeners.all()[0].port)
            await mq.connect()
            await mq.subscribe("chaos/t", qos=0)

            req = C.encode(C.CoapMessage(
                C.CON, C.PUT, 77, b"tk",
                [(C.OPT_URI_PATH, b"ps"), (C.OPT_URI_PATH, b"chaos"),
                 (C.OPT_URI_PATH, b"t"),
                 (C.OPT_URI_QUERY, b"c=coapchaos")],
                b"v1",
            ))

            sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
            addr = ("127.0.0.1", cport)

            # pass 1 of the seam is the synchronous MQTT delivery to
            # the watcher (proto_conn deliver); pass 2 is the CoAP ACK
            # reply — skip the delivery, drop the reply
            inj = faultinject.install(FaultInjector([
                {"point": "transport.write", "action": "drop",
                 "skip": 1, "times": 1},
            ]))
            try:
                def send_and_retransmit():
                    sock.settimeout(0.4)
                    sock.sendto(req, addr)
                    try:
                        sock.recvfrom(2048)
                        raise AssertionError("reply should have dropped")
                    except _socket.timeout:
                        pass
                    # protocol retransmit: SAME mid → dedup cache answers
                    sock.settimeout(5.0)
                    sock.sendto(req, addr)
                    data, _ = sock.recvfrom(2048)
                    return data

                data = await asyncio.to_thread(send_and_retransmit)
                msg = C.decode(data)
                assert msg.type == C.ACK and msg.mid == 77
                assert msg.code == C.CHANGED
                assert inj.fired.get("transport.write") == 1
                # the publish fired exactly once despite two requests
                got = await mq.recv(timeout=5)
                assert (got.topic, got.payload) == ("chaos/t", b"v1")
                try:
                    dup = await mq.recv(timeout=0.4)
                    raise AssertionError(f"duplicate publish: {dup}")
                except (asyncio.TimeoutError, TimeoutError):
                    pass
                await mq.disconnect()
            finally:
                faultinject.uninstall()
            sock.close()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# 7. serve plane: match batch loop killed / wounded mid-publish-storm
#    (ISSUE 7 deadline-aware serve plane)
# ---------------------------------------------------------------------------

async def _start_match_node(**extra):
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    cfg.put("tpu.enable", True)
    cfg.put("tpu.mirror_refresh_interval", 0.01)
    cfg.put("tpu.bypass_rate", 0.0)
    cfg.put("match.deadline.enable", True)
    cfg.put("match.deadline_ms", 50.0)
    cfg.put("match.breaker.threshold", 3)
    cfg.put("match.breaker.probe_interval", 0.05)
    cfg.put("supervisor.backoff_base", 0.005)
    cfg.put("supervisor.backoff_max", 0.05)
    for k, v in extra.items():
        cfg.put(k, v)
    node = BrokerNode(cfg)
    await node.start()
    return node


async def _match_storm(node, got, n, base, kill_at=None):
    """Prefetch+publish storm through the serve plane; returns per-
    prefetch wall times (the waiter-resolution latencies the deadline
    machinery must bound).  Topics are UNIQUE per message so every
    prefetch really parks a waiter on the serve loop — repeated topics
    would serve from the hint cache and never touch it."""
    import time as _time

    from emqx_tpu.broker.message import make_message

    b = node.broker
    ms = node.match_service
    child = node.supervisor.lookup("match.batch")
    waits = []
    for i in range(n):
        topic = f"t/{base + i}/x"
        t0 = _time.perf_counter()
        await ms.prefetch(topic)
        waits.append(_time.perf_counter() - t0)
        b.publish(make_message("pub", topic, b"%d" % (base + i)))
        if kill_at is not None and i == kill_at:
            assert child.kill()
    return waits


def test_chaos_match_batch_kill_midstorm_delivery_holds():
    """Kill the match.batch serve loop mid-publish-storm (twice):
    delivery_ratio stays 1.0, every prefetch waiter resolves well under
    the budget-length stall the old loop burned, and the supervisor
    restart re-arms the loop (device serves again)."""

    async def main():
        node = await _start_match_node()
        try:
            b = node.broker
            ms = node.match_service
            assert ms is not None and ms.deadline
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(
                lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                timeout=60)

            n = 160
            waits = await _match_storm(node, got, n, 0, kill_at=40)
            waits += await _match_storm(node, got, n, 1000, kill_at=90)
            # delivery_ratio 1.0: every publish delivered exactly once
            assert len(got) == 2 * n
            assert sorted(int(x) for x in got) == sorted(
                list(range(n)) + list(range(1000, 1000 + n)))
            # every waiter resolved without a budget-length stall: the
            # old loop parked killed waiters for prefetch_timeout_s (0.5)
            assert max(waits) < ms.prefetch_timeout_s * 0.9, max(waits)
            m = node.observed.metrics
            assert m.get("broker.supervisor.restarts") >= 2
            assert m.get("broker.match.cpu_fallback") >= 0
            # the restarted loop serves from the device again
            assert await until(
                lambda: ms.ready and ms.dev.epoch == ms.inc.epoch)
            await ms.prefetch("t/readback/x")
            assert ms.hint_routes("t/readback/x") is not None
        finally:
            await node.stop()

    run(main())


def test_chaos_match_dispatch_faults_storm_delivery_holds():
    """10% injected match.dispatch faults through a publish storm:
    delivery_ratio 1.0, every waiter resolved promptly (failed batches
    answer from the CPU tables in one hop, no budget-length stalls)."""

    async def main():
        node = await _start_match_node()
        try:
            b = node.broker
            ms = node.match_service
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(
                lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                timeout=60)
            # fault-free baseline storm, then the wounded storm
            n = 150
            clean = await _match_storm(node, got, n, 0)
            inj = faultinject.install(FaultInjector([
                {"point": "match.dispatch", "action": "raise",
                 "prob": 0.1, "times": 0},
            ], seed=11))
            try:
                wounded = await _match_storm(node, got, n, 2000)
            finally:
                faultinject.uninstall()
            assert len(got) == 2 * n           # delivery_ratio 1.0
            assert len(set(got)) == 2 * n      # exactly once
            assert inj.fired.get("match.dispatch", 0) >= 1
            # no waiter stalled anywhere near the prefetch timeout: a
            # raised dispatch resolves its whole batch from CPU NOW
            assert max(wounded) < ms.prefetch_timeout_s * 0.9
            m = node.observed.metrics
            assert m.get("broker.match.cpu_fallback") >= 1
            # tail didn't collapse: the wounded storm stays within 2x
            # the fault-free storm's worst waiter (plus a floor for
            # scheduler noise on tiny absolute numbers)
            assert max(wounded) <= max(2.0 * max(clean), 0.1), (
                max(clean), max(wounded))
        finally:
            await node.stop()

    run(main())


def test_chaos_match_breaker_cpu_serve_with_alarm_and_recovery():
    """Breaker trip under persistent dispatch failures: serving
    continues on the CPU path with the match_degraded alarm active, and
    the supervised probe closes the breaker + clears the alarm once the
    device answers again (acceptance gate, ISSUE 7)."""

    async def main():
        from emqx_tpu.broker.message import make_message

        node = await _start_match_node()
        try:
            b = node.broker
            ms = node.match_service
            alarms = node.observed.alarms
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(
                lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                timeout=60)
            await ms.prefetch("t/warm/x")
            inj = faultinject.install(FaultInjector([
                {"point": "match.dispatch", "action": "raise", "times": 3},
            ]))
            try:
                for i in range(3):
                    await ms.prefetch(f"t/f{i}/x")
                assert ms._breaker_open
                assert alarms.is_active("match_degraded")
                # serving continues on the CPU path while open
                for i in range(20):
                    await ms.prefetch(f"t/open{i}/x")
                    b.publish(make_message(
                        "pub", f"t/open{i}/x", b"o%d" % i))
                assert len(got) == 20
                # faults exhausted → probe closes breaker, alarm clears
                assert await until(lambda: not ms._breaker_open,
                                   timeout=15)
                assert not alarms.is_active("match_degraded")
            finally:
                faultinject.uninstall()
            assert inj.fired.get("match.dispatch") == 3
            # recovered: the device mints hints again
            await ms.prefetch("t/rec/x")
            assert ms.hint_routes("t/rec/x") is not None
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# 7b. overlapped serve pipeline: match.readback child killed / wounded
#     mid-publish-storm (ISSUE 11)
# ---------------------------------------------------------------------------

async def _pipeline_storm(node, got, n, base, kill_at=None):
    """Prefetch+publish storm through the pipelined serve plane; kills
    target the match.readback child (the back half of the chain)."""
    import time as _time

    from emqx_tpu.broker.message import make_message

    b = node.broker
    ms = node.match_service
    child = node.supervisor.lookup("match.readback")
    waits = []
    for i in range(n):
        topic = f"t/{base + i}/x"
        t0 = _time.perf_counter()
        await ms.prefetch(topic)
        waits.append(_time.perf_counter() - t0)
        b.publish(make_message("pub", topic, b"%d" % (base + i)))
        if kill_at is not None and i == kill_at:
            assert child.kill()
    return waits


def test_chaos_pipeline_readback_kill_midstorm_delivery_holds():
    """Kill the match.readback child mid-publish-storm (twice):
    delivery_ratio stays 1.0, in-flight slot waiters fail over to the
    CPU trie immediately (no prefetch-timeout stalls), and the
    supervised restart resumes the two-phase readback."""

    async def main():
        node = await _start_match_node(**{
            "match.deadline.enable": False,
            "match.pipeline.enable": True,
        })
        try:
            b = node.broker
            ms = node.match_service
            assert ms is not None and ms.pipeline
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(
                lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                timeout=60)

            n = 120
            waits = await _pipeline_storm(node, got, n, 0, kill_at=40)
            waits += await _pipeline_storm(node, got, n, 1000,
                                           kill_at=70)
            assert len(got) == 2 * n        # delivery_ratio 1.0
            assert sorted(int(x) for x in got) == sorted(
                list(range(n)) + list(range(1000, 1000 + n)))
            assert max(waits) < ms.prefetch_timeout_s * 0.9, max(waits)
            m = node.observed.metrics
            assert m.get("broker.supervisor.restarts") >= 2
            # the restarted child reads back from the device again —
            # fresh hints mint and the two-phase byte counter advances
            rb0 = m.get("tpu.match.readback_bytes")
            await ms.prefetch("t/after/x")
            assert ms.hint_routes("t/after/x") is not None
            assert await until(
                lambda: m.get("tpu.match.readback_bytes") >= rb0)
            assert ms._inflight_n == 0
        finally:
            await node.stop()

    run(main())


def test_chaos_pipeline_injected_readback_faults_delivery_holds():
    """10% injected match.readback faults through a pipelined publish
    storm: delivery_ratio 1.0, every waiter failed over to the CPU trie
    in one hop (no budget-length stalls), device serving resumes
    between faults."""

    async def main():
        node = await _start_match_node(**{
            "match.deadline.enable": False,
            "match.pipeline.enable": True,
        })
        try:
            b = node.broker
            ms = node.match_service
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(
                lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                timeout=60)
            n = 150
            clean = await _pipeline_storm(node, got, n, 0)
            inj = faultinject.install(FaultInjector([
                {"point": "match.readback", "action": "raise",
                 "prob": 0.1, "times": 0},
            ], seed=19))
            try:
                wounded = await _pipeline_storm(node, got, n, 2000)
            finally:
                faultinject.uninstall()
            assert len(got) == 2 * n           # delivery_ratio 1.0
            assert len(set(got)) == 2 * n      # exactly once
            assert inj.fired.get("match.readback", 0) >= 1
            assert max(wounded) < ms.prefetch_timeout_s * 0.9
            m = node.observed.metrics
            assert m.get("broker.match.cpu_fallback") >= 1
            assert max(wounded) <= max(2.0 * max(clean), 0.1), (
                max(clean), max(wounded))
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# 8. shard loop killed mid-QoS1 traffic (PR 6 connection-plane sharding)
# ---------------------------------------------------------------------------

def test_chaos_shard_kill_midtraffic_qos1_exactly_once():
    """Kill one shard's event loop while a publisher on it is running
    acknowledged QoS1 traffic to a subscriber on the OTHER shard: the
    supervisor respawns the shard (fresh loop + SO_REUSEPORT listener,
    restart counted), the surviving shard's subscriber is unaffected,
    and every ACKED publish is delivered exactly once — acked messages
    cross the handoff into main-loop custody before the PUBACK leaves
    the shard, so a shard death cannot un-deliver them."""
    from emqx_tpu.client import Client, MqttError
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    async def main():
        cfg = Config(file_text=(
            'listeners.tcp.default.bind = "127.0.0.1:0"\n'
            'broker.fanout.enable = true\n'
        ))
        cfg.put("tpu.enable", False)
        cfg.put("broker.conn.shards", 2)
        cfg.put("supervisor.backoff_base", 0.01)
        cfg.put("supervisor.backoff_max", 0.05)
        node = BrokerNode(cfg)
        await node.start()
        try:
            port = node.listeners.all()[0].port
            sub = Client(clientid="sub", port=port)
            await sub.connect()
            await sub.subscribe("sh/#", qos=1)
            sub_shard = node.connections["sub"].shard
            assert sub_shard is not None
            # find a publisher landing on the OTHER shard (REUSEPORT
            # hashes the 4-tuple; 24 tries make a miss astronomically
            # unlikely)
            victim = None
            extras = []
            for i in range(24):
                p = Client(clientid=f"vp{i}", port=port)
                await p.connect()
                await until(lambda: f"vp{i}" in node.connections)
                if node.connections[f"vp{i}"].shard is not sub_shard:
                    victim = p
                    break
                extras.append(p)
            assert victim is not None, "all conns landed on one shard"
            victim_shard = node.connections[victim.clientid].shard
            acked = []
            killed = False
            for i in range(100):
                try:
                    await asyncio.wait_for(
                        victim.publish("sh/x", b"k%d" % i, qos=1), 2.0)
                    acked.append(b"k%d" % i)
                except (MqttError, asyncio.TimeoutError, TimeoutError,
                        ConnectionError):
                    break   # shard died under this publish
                if i == 30:
                    killed = victim_shard.kill()
            assert killed
            # supervisor respawns the shard
            assert await until(lambda: victim_shard.alive())
            assert node.observed.metrics.get(
                "broker.supervisor.restarts") >= 1
            # surviving shard unaffected: sub still serves — prove it
            # with a fresh publisher after the respawn
            p2 = Client(clientid="after", port=port)
            await p2.connect()
            await p2.publish("sh/x", b"post-respawn", qos=1)
            got = []
            deadline = asyncio.get_event_loop().time() + 8
            while asyncio.get_event_loop().time() < deadline:
                try:
                    got += [m.payload for m in await sub.recv_many(
                        timeout=0.5)]
                except (asyncio.TimeoutError, TimeoutError):
                    pass
                if b"post-respawn" in got:
                    break
            assert b"post-respawn" in got
            # exactly-once for every ACKED publish: all present, no dups
            for want in acked:
                assert got.count(want) == 1, (want, got.count(want))
            assert len(got) == len(set(got))
            await p2.disconnect()
            await sub.disconnect()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# 9. streaming table lifecycle chaos (ISSUE 9: table.load / table.swap)
# ---------------------------------------------------------------------------

async def _start_segment_node(seg_dir, **extra):
    extra.setdefault("tpu.table", "python")
    extra.setdefault("match.segments.enable", True)
    extra.setdefault("match.segments.dir", str(seg_dir))
    extra.setdefault("match.segments.compact_interval", 0.05)
    extra.setdefault("match.segments.compact_min_mutations", 1)
    return await _start_match_node(**extra)


def test_chaos_corrupt_segment_rejected_full_rebuild_serves(tmp_path):
    """Corrupt segment -> checksum reject -> cold start falls back to
    the full rebuild and delivery holds at 1.0."""
    async def main():
        from emqx_tpu.broker.message import make_message

        node = await _start_segment_node(tmp_path)
        try:
            ms = node.match_service
            assert ms is not None and ms.segments
            b = node.broker
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(lambda: ms._table_gen >= 1, timeout=30)
            seg_path = ms._segment_path
        finally:
            await node.stop()
        # flip bytes mid-file: the sha1 in the meta record must reject
        with open(seg_path, "r+b") as f:
            f.seek(300)
            f.write(b"\xde\xad\xbe\xef")
        node = await _start_segment_node(
            tmp_path, **{"match.segments.compact_interval": 30.0,
                         "match.segments.compact_min_mutations": 10**9})
        try:
            ms = node.match_service
            assert ms is not None
            assert not ms._segment_loaded   # rejected, rebuilt
            b = node.broker
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(lambda: ms.ready, timeout=30)
            n = 40
            for i in range(n):
                topic = f"t/{i}/x"
                await ms.prefetch(topic)
                b.publish(make_message("pub", topic, b"%d" % i))
            assert await until(lambda: len(got) >= n)
            assert len(got) == n   # delivery_ratio 1.0
        finally:
            await node.stop()

    run(main())


def test_chaos_injected_table_load_fault_falls_back(tmp_path):
    """A raise at the table.load seam behaves exactly like corruption:
    segment rejected, full rebuild serves."""
    async def main():
        node = await _start_segment_node(tmp_path)
        try:
            ms = node.match_service
            assert ms is not None
            b = node.broker
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(lambda: ms._table_gen >= 1, timeout=30)
        finally:
            await node.stop()
        faultinject.install(FaultInjector([
            {"point": "table.load", "action": "raise"}]))
        try:
            node = await _start_segment_node(
                tmp_path, **{"match.segments.compact_interval": 30.0,
                             "match.segments.compact_min_mutations": 10**9})
        finally:
            faultinject.uninstall()
        try:
            ms = node.match_service
            assert ms is not None and not ms._segment_loaded
            b = node.broker
            b.open_session("sub")
            b.subscribe("sub", "x/+/y", SubOpts())
            assert await until(lambda: ms.ready, timeout=30)
            await ms.prefetch("x/1/y")
            assert ms.hint_routes("x/1/y") is not None
        finally:
            await node.stop()

    run(main())


def test_chaos_compact_killed_midswap_serving_unaffected(tmp_path):
    """Kill table.compact mid-swap (both the supervised kill and the
    injected table.swap raise): no state mutates, serving continues,
    the supervised restart resumes compaction, delivery_ratio 1.0."""
    async def main():
        from emqx_tpu.broker.message import make_message

        node = await _start_segment_node(tmp_path)
        try:
            ms = node.match_service
            assert ms is not None
            b = node.broker
            m = node.observed.metrics
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(lambda: ms.ready, timeout=30)
            # phase 1: injected swap fault — the cycle dies BEFORE any
            # state mutates (atomic no-op) and the next cycle swaps
            faultinject.install(FaultInjector([
                {"point": "table.swap", "action": "raise", "times": 1}]))
            sent = 0
            for i in range(40):
                topic = f"t/{i}/x"
                b.subscribe("sub", f"churn/{i}/+", SubOpts())
                await ms.prefetch(topic)
                b.publish(make_message("pub", topic, b"%d" % i))
                sent += 1
            assert await until(lambda: ms._table_gen >= 1, timeout=30)
            inj = faultinject.get()
            assert inj.fired.get("table.swap") == 1
            faultinject.uninstall()
            # phase 2: kill the supervised child mid-cycle
            child = node.supervisor.lookup("table.compact")
            assert child is not None and child.kill()
            gen0 = ms._table_gen
            for i in range(40, 80):
                topic = f"t/{i}/x"
                b.subscribe("sub", f"churn/{i}/+", SubOpts())
                await ms.prefetch(topic)
                b.publish(make_message("pub", topic, b"%d" % i))
                sent += 1
            assert await until(lambda: ms._table_gen > gen0, timeout=30)
            assert m.get("broker.supervisor.restarts") >= 1
            assert await until(lambda: len(got) >= sent)
            assert len(got) == sent   # delivery_ratio 1.0
            # hints minted before the swaps still serve with parity
            await ms.prefetch("t/5/x")
            want = b.router.match_routes("t/5/x")
            hint = ms.hint_routes("t/5/x")
            assert hint is not None
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# 7c. flight recorder (ISSUE 12): breaker trip and brownout escalation
#     each produce exactly one well-formed Perfetto dump; a kill
#     mid-dump leaves no torn file
# ---------------------------------------------------------------------------

def _flightrec_files(node, reason):
    import glob
    import os

    return sorted(glob.glob(os.path.join(
        node.tracing.dir, f"flightrec-{reason}-*.json")))


def _assert_wellformed_dump(path, reason):
    """Valid trace-event JSON, reason recorded, slices time-ordered."""
    import json

    with open(path) as f:
        payload = json.load(f)
    assert payload["reason"] == reason
    evs = payload["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    ts = [e["ts"] for e in slices]
    assert ts == sorted(ts), "slices not time-ordered"
    for e in slices:
        assert e["name"] and "dur" in e and "args" in e
    return len(slices)


def test_chaos_breaker_trip_dumps_flightrec_once(tmp_path):
    """Breaker trip under persistent dispatch failures writes EXACTLY
    one well-formed flight-recorder dump carrying the serve path's
    recent stage events (the forensic acceptance gate, ISSUE 12)."""

    async def main():
        node = await _start_match_node()
        try:
            b = node.broker
            ms = node.match_service
            assert ms.flightrec is node.flightrec
            # isolate from the shared ./trace dir (dumps accumulate
            # across tests/runs there by design)
            node.flightrec.out_dir = str(tmp_path)
            node.tracing.dir = str(tmp_path)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(
                lambda: ms.ready and ms.dev.epoch == ms.inc.epoch,
                timeout=60)
            # a few healthy dispatches so the rings hold real events
            for i in range(5):
                await ms.prefetch(f"t/warm{i}/x")
            assert _flightrec_files(node, "breaker_trip") == []
            faultinject.install(FaultInjector([
                {"point": "match.dispatch", "action": "raise",
                 "times": 3},
            ]))
            try:
                for i in range(3):
                    await ms.prefetch(f"t/f{i}/x")
                assert ms._breaker_open
                files = _flightrec_files(node, "breaker_trip")
                assert len(files) == 1, files          # exactly one
                n_slices = _assert_wellformed_dump(
                    files[0], "breaker_trip")
                assert n_slices >= 1     # the warm dispatches' spans
                m = node.observed.metrics
                assert m.get("obs.flightrec.dumps") == 1
                # recovery does NOT dump again
                assert await until(lambda: not ms._breaker_open,
                                   timeout=15)
                assert len(_flightrec_files(node, "breaker_trip")) == 1
            finally:
                faultinject.uninstall()
        finally:
            await node.stop()

    run(main())


def test_chaos_brownout_escalation_dumps_flightrec_once(tmp_path):
    """Each brownout ESCALATION (level step up) dumps exactly once;
    de-escalation back to 0 does not."""

    async def main():
        node = await _start_match_node(**{
            "overload_protection.cooloff": 0.2,
        })
        try:
            ms = node.match_service
            olp = node.olp
            node.flightrec.out_dir = str(tmp_path)
            node.tracing.dir = str(tmp_path)
            assert _flightrec_files(node, "brownout") == []
            # drive the olp hot: queue depth over the limit → level 1
            olp.report(queue_depth=10 ** 9)
            assert olp.brownout_level() >= 1
            lvl = ms._brownout()
            assert lvl >= 1
            files = _flightrec_files(node, "brownout")
            assert len(files) == 1, files              # exactly one
            _assert_wellformed_dump(files[0], "brownout")
            # same level re-observed: no second dump
            assert ms._brownout() == lvl
            assert len(_flightrec_files(node, "brownout")) == 1
            # cooloff passes → level drops to 0 → still no new dump
            await asyncio.sleep(0.3)
            assert ms._brownout() == 0
            assert len(_flightrec_files(node, "brownout")) == 1
        finally:
            await node.stop()

    run(main())


def test_chaos_kill_mid_dump_leaves_no_torn_file(tmp_path):
    """A crash at ANY point of the dump write (simulated at the worst
    spot: mid-serialization) leaves neither a torn JSON nor a stray
    temp file — the temp-file + atomic-rename contract."""
    tmp_path2 = [tmp_path]

    async def main():
        import glob
        import json as _json
        import os
        from unittest import mock

        node = await _start_match_node()
        try:
            fr = node.flightrec
            fr.out_dir = node.tracing.dir = str(tmp_path2[0])
            fr.ring("fanout").push(1, 100, 50, batch=2)
            before = set(glob.glob(os.path.join(node.tracing.dir, "*")))

            def die_mid_write(obj, fh, **kw):
                fh.write('{"traceEvents": [{"torn":')   # partial bytes
                raise OSError("killed mid-dump")

            with mock.patch.object(_json, "dump", die_mid_write):
                assert fr.dump("manual") is None
            after = set(glob.glob(os.path.join(node.tracing.dir, "*")))
            assert after == before           # no torn file, no .tmp
            # the recorder survives and the next dump is whole
            path = fr.dump("manual")
            assert path is not None
            with open(path) as f:
                _json.load(f)                # parses end to end
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# 14. admission plane: scorer killed / held down / fault-stormed (ISSUE 14)
# ---------------------------------------------------------------------------

async def _start_admission_node(**extra):
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    cfg.put("tpu.enable", False)
    cfg.put("admission.enable", True)
    cfg.put("admission.tick", 0.02)
    cfg.put("admission.hold_ticks", 2)
    cfg.put("admission.decay_ticks", 1000)
    # the chaos storm drives both clients at the same msgs/s; only the
    # attacker's fresh-topic-per-message shape must trip (fan dimension)
    cfg.put("admission.max_publish_rate", 1_000_000.0)
    cfg.put("admission.fan_window", 0.1)
    cfg.put("admission.max_topic_fan", 50.0)
    cfg.put("supervisor.backoff_base", 0.005)
    cfg.put("supervisor.backoff_max", 0.05)
    for k, v in extra.items():
        cfg.put(k, v)
    node = BrokerNode(cfg)
    await node.start()
    return node


def _admission_storm(node, sent, seq, n_honest=40, atk_per=40):
    """Drive the real seams: honest QoS1 publishes (delivery-checked)
    + a QoS0 topic-scan flood that must hit the shed path when (and
    only when) the scorer stands."""
    b = node.broker
    adm = node.admission
    for _ in range(n_honest):
        i = seq[0]
        seq[0] += 1
        sent[0] += 1
        adm.note_publish("honest", "t/h", 64)
        b.publish(make_message("honest", "t/h", b"%d" % i, qos=1))
    for k in range(atk_per):
        topic = f"scan/{seq[0]}/{k}"
        adm.note_publish("attacker", topic, 64)
        b.publish(make_message("attacker", topic, b"a", qos=0))


def _acking_subscriber(node):
    sess, _ = node.broker.open_session("sub", max_inflight=64)
    node.broker.subscribe("sub", "t/#", SubOpts(qos=1))
    got = []

    def on_deliver(cid, pubs):
        stack = list(pubs)
        while stack:
            p = stack.pop(0)
            got.append(p.msg.payload)
            if p.pid is not None:
                _, more = sess.puback(p.pid)
                stack.extend(more)

    node.broker.on_deliver = on_deliver
    return got


def test_chaos_admission_scorer_kill_fails_open_and_recovers(tmp_path):
    """The fail-open acceptance gate: kill the admission.score child
    (held down by a persistent injected fault) mid-storm — every
    standing decision clears, the admission_degraded alarm raises,
    attacker traffic flows unscreened (never a new drop path), honest
    delivery stays 1.0; lifting the fault lets the supervised restart
    resume scoring, re-quarantine the attacker and clear the alarm."""

    async def main():
        node = await _start_admission_node()
        try:
            adm = node.admission
            alarms = node.observed.alarms
            node.flightrec.out_dir = node.tracing.dir = str(tmp_path)
            got = _acking_subscriber(node)
            sent, seq = [0], [0]
            # phase 1: the attacker climbs to quarantine; honest clean
            for _ in range(60):
                _admission_storm(node, sent, seq)
                await asyncio.sleep(0.01)
                if "attacker" in adm._shed:
                    break
            assert "attacker" in adm._shed
            assert adm.explain("honest")["level"] == 0
            # quarantine escalations dumped forensics exactly per tick
            dumps = _flightrec_files(node, "admission_escalation")
            assert len(dumps) >= 1
            _assert_wellformed_dump(dumps[0], "admission_escalation")
            shed_before = adm.shed_count
            _admission_storm(node, sent, seq)
            assert adm.shed_count > shed_before   # shed path is LIVE
            # phase 2: persistent fault + kill — fail-open must HOLD
            faultinject.install(FaultInjector([
                {"point": "admission.score", "action": "raise",
                 "times": 0}]))
            child = node.supervisor.lookup("admission.score")
            assert child is not None and child.kill()
            assert await until(
                lambda: adm.degraded
                and alarms.is_active("admission_degraded")
                and "attacker" not in adm._shed)
            frozen = adm.shed_count
            _admission_storm(node, sent, seq)
            assert adm.shed_count == frozen   # unscreened, no drops
            # phase 3: lift the fault → restart resumes, alarm clears
            faultinject.uninstall()
            deadline = asyncio.get_event_loop().time() + 10.0
            while "attacker" not in adm._shed \
                    and asyncio.get_event_loop().time() < deadline:
                _admission_storm(node, sent, seq)
                await asyncio.sleep(0.01)
            assert "attacker" in adm._shed
            assert await until(
                lambda: not alarms.is_active("admission_degraded"))
            # zero honest drops attributable to admission, end to end
            assert await until(lambda: len(got) >= sent[0])
            assert len(got) == sent[0]
            assert node.observed.metrics.get(
                "broker.supervisor.restarts") >= 1
            assert node.observed.metrics.get(
                "broker.admission.fail_open") >= 1
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


def test_chaos_admission_injected_fault_storm_delivery_holds():
    """10% admission.score faults mid-storm: wounded ticks fail open
    and restart, honest delivery stays 1.0 throughout, and scoring
    keeps converging between the wounds (the attacker still ends up
    screened)."""

    async def main():
        node = await _start_admission_node()
        try:
            adm = node.admission
            got = _acking_subscriber(node)
            sent, seq = [0], [0]
            inj = faultinject.install(FaultInjector([
                {"point": "admission.score", "action": "raise",
                 "prob": 0.1, "times": 0}], seed=5))
            screened = False
            for _ in range(120):
                _admission_storm(node, sent, seq)
                await asyncio.sleep(0.01)
                screened = screened or "attacker" in adm._shed
            faultinject.uninstall()
            assert inj.fired.get("admission.score", 0) >= 1
            assert screened
            assert adm.explain("honest")["level"] == 0
            assert not node.banned.check(clientid="honest")
            assert await until(lambda: len(got) >= sent[0])
            assert len(got) == sent[0]
            assert node.observed.metrics.get(
                "broker.supervisor.restarts") >= 1
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# 13. degraded mesh chaos (ISSUE 18): shard kill -> scoped failover ->
#     supervised online rebuild -> canary re-admit, delivery 1.0 all
#     the way through; a sustained fault storm marches the health
#     ladder to cpu-only and back
# ---------------------------------------------------------------------------

def test_chaos_mesh_kill_degraded_rebuild_readmit_delivery_holds():
    """Kill a mesh shard mid-storm with the degraded flag ON: serving
    continues scoped (survivor shards on-device, dead share CPU-filled),
    the mesh_degraded alarm + flightrec dump fire, the supervised
    rebuild survives one injected ``mesh.rebuild`` crash (restart
    counted), the canary re-admits the shard, and delivery_ratio is
    1.0 across the whole kill -> degraded -> rebuild -> readmit
    cycle."""

    async def main():
        node = await _start_match_node(**{
            "match.multichip.enable": True,
            "match.multichip.degraded.enable": True,
        })
        try:
            b = node.broker
            ms = node.match_service
            mc = ms.mc
            assert mc is not None and mc.degraded
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(lambda: ms.ready and mc.ready,
                               timeout=60)
            n = 60
            await _match_storm(node, got, n, 0)        # healthy phase
            faultinject.install(FaultInjector([
                {"point": "mesh.rebuild", "action": "raise",
                 "times": 1},
            ]))
            # shard 2 (not the micro merge owner: killing shard 0
            # would force a fresh micro_owner step compile mid-storm
            # and trip the deadline breaker — that migration path is
            # covered at matcher level)
            mc.kill_shard(2)
            await _match_storm(node, got, n, 1000)     # degraded phase
            assert mc.degraded_batches >= 1
            alarms = node.observed.alarms
            # the alarm may already have cleared if the rebuild beat the
            # sampling — the flight recorder's last dump is the durable
            # latch
            assert (alarms.is_active("mesh_degraded")
                    or node.flightrec.last_reason == "mesh_degraded")
            assert await until(lambda: not mc.dead_shards, timeout=60)
            faultinject.uninstall()
            assert await until(
                lambda: not alarms.is_active("mesh_degraded"),
                timeout=30)
            await _match_storm(node, got, n, 2000)     # readmitted
            assert await until(lambda: len(got) >= 3 * n)
            assert len(got) == 3 * n             # delivery_ratio 1.0
            assert sorted(int(x) for x in got) == sorted(
                list(range(n)) + list(range(1000, 1000 + n))
                + list(range(2000, 2000 + n)))
            m = node.observed.metrics
            assert m.get("broker.supervisor.restarts") >= 1
            assert mc.rebuilds >= 1
            assert m.get("tpu.mesh.degraded_batches") >= 1
            assert m.get("tpu.mesh.state") == 0
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


def test_chaos_mesh_sustained_shard_faults_ladder_to_cpu_only():
    """A sustained ``match.shard`` fault storm (every dispatch raises)
    marches the health ladder one shard at a time to the cpu-only rung
    — strikes attribute round-robin, two shards die, further dispatches
    are refused outright — while delivery stays 1.0 on the CPU trie.
    Rebuilds are pinned down by an injected ``mesh.rebuild`` fault so
    the ladder can't climb back mid-storm; lifting both faults stages
    the re-admit through degraded(S) back to healthy."""

    async def main():
        node = await _start_match_node(**{
            "match.multichip.enable": True,
            "match.multichip.degraded.enable": True,
            "match.multichip.degraded.fail_threshold": 2,
            "match.breaker.threshold": 1000,   # ladder, not breaker
        })
        try:
            b = node.broker
            ms = node.match_service
            mc = ms.mc
            assert mc is not None and mc.fail_threshold == 2
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(lambda: ms.ready and mc.ready,
                               timeout=60)
            n = 60
            inj = faultinject.install(FaultInjector([
                {"point": "match.shard", "action": "raise", "times": 0},
                {"point": "mesh.rebuild", "action": "raise", "times": 0},
            ]))
            try:
                await _match_storm(node, got, n, 0)
                # 2 strikes x round-robin killed two shards: cpu-only
                assert mc.mesh_state() == 2
                assert len(mc.dead_shards) >= 2
                assert inj.fired.get("match.shard", 0) >= 4
                assert node.observed.alarms.is_active("mesh_degraded")
                assert len(got) == n        # delivery held on the trie
            finally:
                faultinject.uninstall()
            # staged re-admit: the supervised rebuild (no longer pinned)
            # climbs cpu-only -> degraded(S) -> healthy
            assert await until(lambda: not mc.dead_shards, timeout=60)
            assert mc.rebuilds >= 2
            assert await until(
                lambda: not node.observed.alarms.is_active(
                    "mesh_degraded"), timeout=30)
            await _match_storm(node, got, n, 5000)
            assert await until(lambda: len(got) >= 2 * n)
            assert len(got) == 2 * n
            m = node.observed.metrics
            assert m.get("broker.supervisor.restarts") >= 1
            assert m.get("tpu.mesh.state") == 0
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())

# ---------------------------------------------------------------------------
# 14. load-adaptive plane chaos (ISSUE 20): the balance pass killed
#     mid-rebalance is a NO-OP (nothing staged, old placement serves),
#     and a degraded mesh defers the pass — delivery 1.0 throughout
# ---------------------------------------------------------------------------

def test_chaos_rebalance_killed_midpass_noop_delivery_holds():
    """Every balance pass on the compaction cadence dies at the
    injected ``ep.rebalance`` seam: the fault fires BEFORE anything is
    staged, so the crc32 placement keeps serving untouched and the
    storm delivers 1.0.  A shard killed afterwards makes the pass
    defer (return 0, stage nothing) until re-admission — then a clean
    pass may stage again."""
    import tempfile

    async def main():
        seg = tempfile.mkdtemp()
        node = await _start_match_node(**{
            "match.multichip.enable": True,
            "match.multichip.ep.enable": True,
            "match.multichip.ep.autotune.enable": True,
            "match.multichip.degraded.enable": True,
            "match.segments.enable": True,
            "match.segments.dir": seg,
            "match.segments.compact_interval": 0.1,
            "match.segments.compact_min_mutations": 1,
        })
        try:
            b = node.broker
            ms = node.match_service
            mc = ms.mc
            assert mc is not None and mc.ep_autotune
            got = []
            b.on_deliver = lambda cid, pubs: got.extend(
                bytes(p.msg.payload) for p in pubs)
            b.open_session("sub")
            b.subscribe("sub", "t/#", SubOpts())
            assert await until(lambda: ms.ready and mc.ready,
                               timeout=60)
            n = 60
            inj = faultinject.install(FaultInjector([
                {"point": "ep.rebalance", "action": "raise",
                 "times": 0},
            ]))
            try:
                await _match_storm(node, got, n, 0)
                # compactions keep firing on the 0.1 s cadence; every
                # balance pass dies at the seam = a no-op
                assert await until(
                    lambda: inj.fired.get("ep.rebalance", 0) >= 1,
                    timeout=30)
                assert mc._placement == {}
                assert mc._placement_next is None
                assert mc.ep_rebalances == 0
                assert len(got) == n          # delivery held at 1.0
            finally:
                faultinject.uninstall()
            # degraded race, deterministic: while a shard is dead the
            # pass returns 0 and stages nothing (never remaps onto a
            # dead owner; the canary judges the placement it was
            # built against)
            mc.kill_shard(2)
            assert mc.plan_rebalance() == 0
            assert mc._placement_next is None
            await _match_storm(node, got, n, 1000)
            assert await until(lambda: not mc.dead_shards, timeout=60)
            await _match_storm(node, got, n, 2000)
            assert await until(lambda: len(got) >= 3 * n)
            assert len(got) == 3 * n          # 1.0 across the cycle
            assert sorted(int(x) for x in got) == sorted(
                list(range(n)) + list(range(1000, 1000 + n))
                + list(range(2000, 2000 + n)))
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())
