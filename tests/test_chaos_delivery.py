"""Chaos suite: kill/wound each delivery subsystem MID-TRAFFIC and
assert the invariants PR 1/2 established survive supervised recovery —
QoS1 delivery_ratio 1.0 after recovery, zero DUPs on the clean path,
fanout remainder re-queued under injected cancellation, and restart
counts visible on ``broker.supervisor.*``."""

import asyncio

import pytest

from emqx_tpu import faultinject
from emqx_tpu.broker import Broker, FanoutPipeline, SubOpts, make_message
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.observe.metrics import Metrics
from emqx_tpu.supervise import Supervisor


def run(coro):
    return asyncio.run(coro)


async def until(pred, timeout=8.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while not pred() and asyncio.get_event_loop().time() < deadline:
        await asyncio.sleep(0.002)
    return pred()


def fast_sup(**kw):
    kw.setdefault("backoff_base", 0.001)
    kw.setdefault("backoff_max", 0.01)
    kw.setdefault("jitter", 0.0)
    return Supervisor(**kw)


# ---------------------------------------------------------------------------
# 1. fanout pipeline killed mid-traffic (QoS1, acks flowing)
# ---------------------------------------------------------------------------

def test_chaos_fanout_kill_midtraffic_qos1_exactly_once():
    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        sess, _ = b.open_session("sub", max_inflight=64)
        b.subscribe("sub", "t/#", SubOpts(qos=1))
        got = []
        dups = [0]

        def on_deliver(cid, pubs):
            # an acking QoS1 consumer: every grant PUBACKs immediately
            # so the window keeps moving through kills
            stack = list(pubs)
            while stack:
                p = stack.pop(0)
                got.append(bytes(p.msg.payload))
                if p.msg.dup:
                    dups[0] += 1
                if p.pid is not None:
                    _, more = sess.puback(p.pid)
                    stack.extend(more)

        b.on_deliver = on_deliver
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m)
        await p.start()
        b.fanout = p
        n = 400
        for i in range(n):
            assert p.offer(make_message("pub", "t/x", b"%d" % i, qos=1))
            if i % 50 == 49:
                p._child.kill()             # wound the drain loop
                await asyncio.sleep(0.003)  # let the restart land
        assert await until(lambda: len(got) >= n)
        # delivery_ratio 1.0, exactly once, zero DUPs, order preserved
        assert [int(x) for x in got] == list(range(n))
        assert dups[0] == 0
        assert m.get("broker.supervisor.restarts") >= 1
        await p.stop()
        await sup.stop()

    run(main())


def test_chaos_fanout_injected_drain_faults_recover():
    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        got = []
        b.on_deliver = lambda cid, pubs: got.extend(
            int(p.msg.payload) for p in pubs)
        b.open_session("sub")
        b.subscribe("sub", "t", SubOpts())
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m)
        await p.start()
        b.fanout = p
        inj = faultinject.install(FaultInjector([
            {"point": "fanout.drain", "action": "raise",
             "skip": 1, "times": 2},
        ]))
        try:
            n = 100
            for i in range(n):
                assert p.offer(make_message("pub", "t", b"%d" % i))
                if i % 20 == 0:
                    await asyncio.sleep(0.002)
            assert await until(lambda: len(got) == n)
            assert got == list(range(n))    # nothing lost, in order
            assert inj.fired.get("fanout.drain") == 2
            assert m.get("broker.supervisor.restarts") == 2
        finally:
            faultinject.uninstall()
        await p.stop()
        await sup.stop()

    run(main())


def test_chaos_overload_sheds_per_policy():
    """Sustained (injected) overload: QoS0 drops first, retained
    defers until the overload clears, QoS1 keeps flowing."""
    from emqx_tpu.broker.olp import Olp

    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        olp = Olp(max_queue_depth=10, cooloff=0.05)
        sess, _ = b.open_session("sub", max_inflight=256)
        b.subscribe("sub", "t", SubOpts(qos=1))
        got = []

        def on_deliver(cid, pubs):
            stack = list(pubs)
            while stack:
                p = stack.pop(0)
                got.append(bytes(p.msg.payload))
                if p.pid is not None:
                    _, more = sess.puback(p.pid)
                    stack.extend(more)

        b.on_deliver = on_deliver
        p = FanoutPipeline(b, window_s=0.0, supervisor=sup, metrics=m,
                           olp=olp)
        await p.start()
        b.fanout = p
        olp.report(queue_depth=100)         # overload signal
        assert olp.overloaded()
        assert p.offer(make_message("pub", "t", b"q0"))         # shed
        assert m.get("broker.olp.shed_qos0") == 1
        retained = make_message("pub", "t", b"ret", retain=True)
        assert p.offer(retained)                                 # deferred
        assert m.get("broker.olp.deferred") == 1
        assert len(p._deferred) == 1
        assert p.offer(make_message("pub", "t", b"q1", qos=1))  # flows
        assert await until(lambda: b"q1" in got)
        assert b"q0" not in got
        # overload clears → the deferred retained publish is delivered
        await asyncio.sleep(0.06)           # past cooloff
        olp.report(queue_depth=0)
        assert not olp.overloaded()
        p.offer(make_message("pub", "t", b"after", qos=1))  # wake drain
        assert await until(lambda: b"ret" in got and b"after" in got)
        await p.stop()
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# 2. cluster replication loop killed mid-traffic
# ---------------------------------------------------------------------------

async def _start_cluster_node(name, seeds=""):
    from emqx_tpu.config import Config
    from emqx_tpu.node import BrokerNode

    cfg = Config(file_text=(
        f'node.name = "{name}"\n'
        'listeners.tcp.default.bind = "127.0.0.1:0"\n'
        'cluster.enable = true\n'
        'cluster.listen = "127.0.0.1:0"\n'
        f'cluster.seeds = "{seeds}"\n'
        'cluster.heartbeat_interval = 200ms\n'
        'cluster.node_timeout = 1500ms\n'
    ))
    cfg.put("tpu.enable", False)
    node = BrokerNode(cfg)
    await node.start()
    node.cluster.SYNC_INTERVAL = 0.02
    node.cluster.RECONNECT_INTERVAL = 0.3
    return node


def test_chaos_cluster_sync_loop_kill_recovers():
    from emqx_tpu.client import Client

    async def main():
        n1 = await _start_cluster_node("c1@chaos")
        n2 = await _start_cluster_node(
            "c2@chaos", seeds=f"127.0.0.1:{n1.cluster.listen_port}")
        try:
            assert await until(
                lambda: n2.cluster.name in n1.cluster.peers
                and n1.cluster.peers[n2.cluster.name].up
                and n1.cluster.name in n2.cluster.peers
                and n2.cluster.peers[n1.cluster.name].up)
            # wound n1's route-replication loop mid-operation
            child = n1.supervisor.lookup("cluster.sync")
            assert child is not None and child.kill()
            # a subscription taken on n1 AFTER the kill must still
            # replicate (the restarted loop re-broadcasts the delta)
            sub = Client(clientid="s1",
                         port=n1.listeners.all()[0].port)
            await sub.connect()
            await sub.subscribe("chaos/+/x", qos=1)
            assert await until(
                lambda: n2.broker.router.match_routes("chaos/a/x"))
            # and forwarding works end to end: publish on n2 → n1 sub
            pub = Client(clientid="p1",
                         port=n2.listeners.all()[0].port)
            await pub.connect()
            await pub.publish("chaos/a/x", b"hello", qos=1)
            got = await sub.recv(timeout=5)
            assert got.payload == b"hello"
            assert n1.observed.metrics.get(
                "broker.supervisor.restarts") >= 1
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())


# ---------------------------------------------------------------------------
# 3. bridge sink killed + wounded mid-traffic
# ---------------------------------------------------------------------------

def test_chaos_bridge_sink_kill_and_fault_at_least_once():
    from emqx_tpu.bridge.resource import BufferedWorker, Connector

    class SinkConnector(Connector):
        def __init__(self):
            self.got = []

        async def send(self, items):
            self.got.extend(items)

    async def main():
        m = Metrics()
        sup = fast_sup(metrics=m)
        conn = SinkConnector()
        w = BufferedWorker(conn, name="chaos", batch_size=4,
                           retry_base=0.001, retry_max=0.01)
        w.supervisor = sup
        await w.start()
        inj = faultinject.install(FaultInjector([
            {"point": "bridge.sink", "action": "raise",
             "skip": 3, "times": 2},
        ]))
        try:
            items = [f"item-{i}" for i in range(40)]
            for i, it in enumerate(items):
                w.enqueue(it)
                if i == 20:
                    w._tasks[0].kill()      # wound the worker loop
                    await asyncio.sleep(0.002)
                await asyncio.sleep(0)
            assert await until(lambda: set(conn.got) >= set(items))
            # at-least-once into the remote; the injected SendErrors
            # rode the normal retry/backoff path
            assert inj.fired.get("bridge.sink") == 2
            assert w.metrics["retried"] >= 1
            assert m.get("broker.supervisor.restarts") >= 1
            assert w.status == "connected"
        finally:
            faultinject.uninstall()
        await w.stop()
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# 4. exhook channel killed mid-stream
# ---------------------------------------------------------------------------

def test_chaos_exhook_sender_kill_recovers():
    grpc = pytest.importorskip("grpc")  # noqa: F841  (manager imports it)
    import types

    from emqx_tpu.exhook.manager import (
        ExHookManager, ServerSpec, _ServerState,
    )

    class FakeStub:
        def __init__(self):
            self.calls = []

        def OnClientConnected(self, req):
            async def go():
                self.calls.append(req)
            return go()

    async def main():
        b = Broker()
        m = Metrics()
        sup = fast_sup(metrics=m)
        node = types.SimpleNamespace(broker=b, supervisor=sup,
                                     started_at=0.0)
        mgr = ExHookManager(node, [])
        st = _ServerState(spec=ServerSpec(name="s1", url="inproc"))
        st.stub = FakeStub()
        st.hooks = ["client.connected"]
        mgr.servers = [st]
        st.sender = sup.start_child("exhook.sender.s1",
                                    lambda: mgr._sender_loop(st))
        for i in range(3):
            st.queue.put_nowait(("OnClientConnected", i))
        assert await until(lambda: len(st.stub.calls) == 3)
        # wound the notification channel mid-stream
        assert st.sender.kill()
        for i in range(3, 6):
            st.queue.put_nowait(("OnClientConnected", i))
        assert await until(lambda: len(st.stub.calls) == 6)
        assert st.stub.calls == list(range(6))
        assert m.get("broker.supervisor.restarts") >= 1
        st.sender.cancel()
        await sup.stop()

    run(main())


# ---------------------------------------------------------------------------
# 5. transport write faults heal through the retry machinery
# ---------------------------------------------------------------------------

def test_chaos_injected_cluster_frame_drops_heal():
    """Dropped cluster frames (the cast seam) must not wedge
    replication: the seq-gap detection re-bootstraps."""
    from emqx_tpu.client import Client

    async def main():
        n1 = await _start_cluster_node("d1@chaos")
        n2 = await _start_cluster_node(
            "d2@chaos", seeds=f"127.0.0.1:{n1.cluster.listen_port}")
        try:
            assert await until(
                lambda: n2.cluster.name in n1.cluster.peers
                and n1.cluster.peers[n2.cluster.name].up)
            inj = faultinject.install(FaultInjector([
                # drop a few cluster frames, then run clean
                {"point": "cluster.rpc", "action": "drop", "times": 3},
            ]))
            try:
                sub = Client(clientid="s1",
                             port=n1.listeners.all()[0].port)
                await sub.connect()
                await sub.subscribe("heal/#", qos=1)
                # keep mutating the route table: once the drops exhaust,
                # the next delta batch exposes the seq gap and the
                # receiver re-bootstraps (snapshot covers heal/#)
                for i in range(20):
                    await sub.subscribe(f"heal{i}/#", qos=0)
                    await asyncio.sleep(0.1)
                    if n2.broker.router.match_routes("heal/x"):
                        break
                assert n2.broker.router.match_routes("heal/x")
                assert inj.fired.get("cluster.rpc", 0) >= 1
                await sub.disconnect()
            finally:
                faultinject.uninstall()
        finally:
            await n2.stop()
            await n1.stop()

    run(main())
