"""Broker core behavioral tests: mqueue/inflight/session QoS flows,
shared-sub strategies, hooks, end-to-end pub/sub dispatch — mirroring
emqx_broker_SUITE / emqx_session_SUITE / emqx_shared_sub_SUITE coverage
(SURVEY.md §4)."""

import pytest

from emqx_tpu.broker import (
    Broker, Hooks, Inflight, InflightFullError, MQueue, Message, Publish,
    Session, SharedSub, SubOpts, make_message, OK, STOP,
)


def msg(topic="t", qos=0, payload=b"x", sender="pub", **kw):
    return make_message(sender, topic, payload, qos=qos, **kw)


# ---------------------------------------------------------------------------
# MQueue
# ---------------------------------------------------------------------------

def test_mqueue_fifo_and_bound():
    q = MQueue(max_len=3)
    for i in range(3):
        assert q.insert(msg(payload=str(i).encode(), qos=1)) is None
    victim = q.insert(msg(payload=b"3", qos=1))
    assert victim is not None and victim.payload == b"0"  # oldest dropped
    assert q.dropped == 1
    assert [m.payload for m in q.to_list()] == [b"1", b"2", b"3"]
    assert q.pop().payload == b"1"


def test_mqueue_priorities():
    q = MQueue(max_len=10, priorities={"hi": 2, "lo": 0})
    q.insert(msg(topic="lo", qos=1, payload=b"a"))
    q.insert(msg(topic="hi", qos=1, payload=b"b"))
    q.insert(msg(topic="lo", qos=1, payload=b"c"))
    assert q.pop().payload == b"b"  # higher priority first
    assert q.pop().payload == b"a"


def test_mqueue_priority_eviction():
    q = MQueue(max_len=2, priorities={"hi": 1})
    q.insert(msg(topic="lo", qos=1, payload=b"a"))
    q.insert(msg(topic="lo", qos=1, payload=b"b"))
    v = q.insert(msg(topic="hi", qos=1, payload=b"c"))
    assert v.payload == b"a"  # low-prio oldest evicted for high-prio
    # incoming low-prio with queue full of high-prio is itself dropped
    q2 = MQueue(max_len=1, priorities={"hi": 1})
    q2.insert(msg(topic="hi", qos=1, payload=b"h"))
    v2 = q2.insert(msg(topic="lo", qos=1, payload=b"l"))
    assert v2.payload == b"l"


def test_mqueue_store_qos0():
    q = MQueue(max_len=5, store_qos0=False)
    v = q.insert(msg(qos=0))
    assert v is not None and len(q) == 0
    assert q.insert(msg(qos=1)) is None


# ---------------------------------------------------------------------------
# Inflight
# ---------------------------------------------------------------------------

def test_inflight_window():
    f = Inflight(max_size=2)
    f.insert(1, "a")
    with pytest.raises(KeyError):
        f.insert(1, "dup")
    f.insert(2, "b")
    assert f.is_full()
    with pytest.raises(InflightFullError):
        f.insert(3, "c")
    assert f.delete(1) == "a"
    assert f.lookup(2) == "b"
    assert not f.is_full()


# ---------------------------------------------------------------------------
# Session QoS flows
# ---------------------------------------------------------------------------

def test_session_qos0_passthrough():
    s = Session("c1")
    out, dropped = s.deliver([msg(qos=0)])
    assert len(out) == 1 and out[0].pid is None
    assert not dropped and s.inflight.is_empty()


def test_session_qos1_flow():
    s = Session("c1", max_inflight=2)
    out, _ = s.deliver([msg(qos=1), msg(qos=1), msg(qos=1)])
    assert len(out) == 2           # window=2, third queued
    assert len(s.mqueue) == 1
    acked, more = s.puback(out[0].pid)
    assert acked is not None
    assert len(more) == 1          # queued message flushed into window
    assert s.puback(9999) == (None, [])  # unknown pid ignored


def test_session_qos2_outbound_flow():
    s = Session("c1")
    out, _ = s.deliver([msg(qos=2)])
    pid = out[0].pid
    assert s.pubrec(pid) is True
    assert s.pubrec(pid) is False      # second PUBREC: already released
    known, more = s.pubcomp(pid)
    assert known and s.inflight.is_empty()
    assert s.pubcomp(pid) == (False, [])


def test_session_qos2_inbound_exactly_once():
    s = Session("c1", max_awaiting_rel=2)
    assert s.publish_qos2(10, msg(qos=2)) == "ok"
    assert s.publish_qos2(10, msg(qos=2)) == "dup"   # dedup by packet id
    assert s.publish_qos2(11, msg(qos=2)) == "ok"
    assert s.publish_qos2(12, msg(qos=2)) == "full"  # quota exceeded
    assert s.pubrel_received(10) is True
    assert s.pubrel_received(10) is False
    assert s.publish_qos2(10, msg(qos=2)) == "ok"    # pid reusable after rel


def test_session_retry_sets_dup():
    s = Session("c1", retry_interval=0.0)
    out, _ = s.deliver([msg(qos=1)])
    retries = s.retry()
    assert len(retries) == 1
    pid, kind, m = retries[0]
    assert kind == "publish" and m.dup is True and pid == out[0].pid


def test_session_packet_id_wraps_and_skips_inflight():
    s = Session("c1", max_inflight=10)
    s._next_pid = 65534
    a = s.next_packet_id()
    s.inflight.insert(a, ("publish", None))
    assert a == 65535
    b = s.next_packet_id()
    assert b == 1  # wrapped past 65535
    s.inflight.insert(b, ("publish", None))
    s._next_pid = 65534
    assert s.next_packet_id() == 2  # skips 65535 (inflight) and 1 (inflight)


def test_session_resume_redelivers_dup():
    s = Session("c1", max_inflight=1)
    s.deliver([msg(qos=1, payload=b"a"), msg(qos=1, payload=b"b")])
    pubs = s.resume_publishes()
    assert pubs[0].msg.payload == b"a" and pubs[0].msg.dup is True
    assert len(pubs) == 1  # window still full, 'b' stays queued


# ---------------------------------------------------------------------------
# SharedSub strategies
# ---------------------------------------------------------------------------

def _members(ss):
    ss.subscribe("g", "t/#", "c1")
    ss.subscribe("g", "t/#", "c2")
    ss.subscribe("g", "t/#", "c3")


def test_shared_round_robin():
    ss = SharedSub("round_robin")
    _members(ss)
    picks = [ss.pick("g", "t/#", "t/x")[0] for _ in range(6)]
    assert picks == ["c1", "c2", "c3", "c1", "c2", "c3"]


def test_shared_sticky():
    ss = SharedSub("sticky", seed=1)
    _members(ss)
    first = ss.pick("g", "t/#", "t/x")
    assert all(ss.pick("g", "t/#", "t/y") == first for _ in range(5))
    ss.unsubscribe("g", "t/#", first[0])
    second = ss.pick("g", "t/#", "t/z")
    assert second != first


def test_shared_hash_strategies_deterministic():
    for strat, key in [("hash_clientid", "sender"), ("hash_topic", "topic")]:
        ss = SharedSub(strat)
        _members(ss)
        a = ss.pick("g", "t/#", "t/x", sender="s1")
        assert all(
            ss.pick("g", "t/#", "t/x", sender="s1") == a for _ in range(5)
        )


def test_shared_redispatch_on_nack():
    ss = SharedSub("round_robin")
    _members(ss)
    accepted = []

    def try_deliver(m):
        accepted.append(m[0])
        return m[0] == "c3"  # others nack

    got = ss.dispatch_with_ack("g", "t/#", "t/x", try_deliver)
    # redispatch never retries a nacked member and ends on the acceptor
    assert got[0] == "c3"
    assert accepted[-1] == "c3" and len(accepted) == len(set(accepted))

    got = ss.dispatch_with_ack("g", "t/#", "t/x", lambda m: False)
    assert got is None  # every member nacked


def test_shared_local_strategy():
    ss = SharedSub("local", seed=3)
    ss.subscribe("g", "t", "c1", node="n1")
    ss.subscribe("g", "t", "c2", node="n2")
    for _ in range(5):
        assert ss.pick("g", "t", "t", local_node="n2")[0] == "c2"


# ---------------------------------------------------------------------------
# Hooks
# ---------------------------------------------------------------------------

def test_hooks_priority_and_stop():
    h = Hooks()
    calls = []
    h.add("p", lambda: calls.append("low"), priority=0)
    h.add("p", lambda: calls.append("hi"), priority=10)
    assert h.run("p") == OK
    assert calls == ["hi", "low"]

    h2 = Hooks()
    h2.add("p", lambda: STOP, priority=5)
    h2.add("p", lambda: calls.append("never"), priority=0)
    assert h2.run("p") == STOP
    assert "never" not in calls


def test_hooks_run_fold():
    h = Hooks()
    h.add("m", lambda acc: (OK, acc + 1))
    h.add("m", lambda acc: (STOP, acc * 10))
    h.add("m", lambda acc: (OK, acc + 999))  # after STOP: not run
    assert h.run_fold("m", (), 1) == 20


def test_hooks_delete():
    h = Hooks()
    fn = lambda: None
    h.add("p", fn, name="x")
    assert h.delete("p", "x") is True
    assert h.callbacks("p") == []


# ---------------------------------------------------------------------------
# Broker end-to-end
# ---------------------------------------------------------------------------

def test_broker_pubsub_roundtrip():
    b = Broker()
    b.open_session("sub1")
    b.open_session("sub2")
    b.subscribe("sub1", "sensors/+/temp", SubOpts(qos=1))
    b.subscribe("sub2", "sensors/#", SubOpts(qos=0))
    res = b.publish(msg(topic="sensors/kitchen/temp", qos=1))
    assert res.matched == 2
    assert res.publishes["sub1"][0].pid is not None       # QoS1 capped at 1
    assert res.publishes["sub2"][0].pid is None           # QoS capped to 0
    res2 = b.publish(msg(topic="other/x"))
    assert res2.no_subscribers


def test_broker_qos_cap_is_min():
    b = Broker()
    b.open_session("s")
    b.subscribe("s", "t", SubOpts(qos=2))
    res = b.publish(msg(topic="t", qos=1))
    assert res.publishes["s"][0].msg.qos == 1  # min(pub 1, sub 2)


def test_broker_no_local():
    b = Broker()
    b.open_session("c1")
    b.subscribe("c1", "t", SubOpts(nl=True))
    res = b.publish(msg(topic="t", sender="c1"))
    assert "c1" not in res.publishes
    res2 = b.publish(msg(topic="t", sender="other"))
    assert "c1" in res2.publishes


def test_broker_shared_group_single_delivery():
    b = Broker(shared_strategy="round_robin")
    for c in ("c1", "c2"):
        b.open_session(c)
        b.subscribe(c, "$share/g/t/#", SubOpts(qos=1))
    res1 = b.publish(msg(topic="t/x"))
    res2 = b.publish(msg(topic="t/x"))
    got = [list(r.publishes) for r in (res1, res2)]
    assert got == [["c1"], ["c2"]]  # one member per publish, round robin


def test_broker_shared_and_plain_coexist():
    b = Broker()
    b.open_session("plain")
    b.open_session("shared")
    b.subscribe("plain", "t/#", SubOpts())
    b.subscribe("shared", "$share/g/t/#", SubOpts())
    res = b.publish(msg(topic="t/1"))
    assert set(res.publishes) == {"plain", "shared"}


def test_broker_unsubscribe_cleans_routes():
    b = Broker()
    b.open_session("c")
    b.subscribe("c", "a/+", SubOpts())
    assert b.router.route_count() == 1
    b.unsubscribe("c", "a/+")
    assert b.router.route_count() == 0
    assert b.publish(msg(topic="a/b")).no_subscribers


def test_broker_session_takeover_discard():
    b = Broker()
    s1, present = b.open_session("c", clean_start=True)
    assert not present
    b.subscribe("c", "t", SubOpts())
    s2, present = b.open_session("c", clean_start=False)
    assert present and s2 is s1                      # resumed
    s3, present = b.open_session("c", clean_start=True)
    assert not present and s3 is not s1              # discarded
    assert b.router.route_count() == 0               # old subs dropped


def test_broker_mqtt5_publish_hook_veto():
    b = Broker()
    b.open_session("c")
    b.subscribe("c", "t", SubOpts())

    def deny(m):
        m.headers["allow_publish"] = False
        return (STOP, m)

    b.hooks.add("message.publish", deny)
    res = b.publish(msg(topic="t"))
    assert res.publishes == {} and res.no_subscribers


def test_broker_sys_topic_protection_end_to_end():
    b = Broker()
    b.open_session("c")
    b.subscribe("c", "#", SubOpts())
    res = b.publish(msg(topic="$SYS/broker/uptime"))
    assert res.no_subscribers


def test_broker_stats():
    b = Broker()
    b.open_session("c")
    b.subscribe("c", "a", SubOpts())
    b.subscribe("c", "$share/g/b", SubOpts())
    st = b.stats()
    assert st["sessions.count"] == 1
    assert st["subscriptions.count"] == 2
    assert st["routes.count"] == 2
    assert st["shared_groups.count"] == 1


def test_queue_legacy_shared_sub_delivers():
    b = Broker()
    b.open_session("c1")
    b.subscribe("c1", "$queue/jobs", SubOpts(qos=1))
    res = b.publish(msg(topic="jobs", qos=1))
    assert "c1" in res.publishes


def test_outbox_overflow_counted_and_logged_once(caplog):
    import logging

    from emqx_tpu.observe.metrics import Metrics

    b = Broker()
    b.metrics = Metrics()
    b.open_session("c")
    b.subscribe("c", "t", SubOpts())
    with caplog.at_level(logging.WARNING, logger="emqx_tpu.broker.broker"):
        for i in range(b.OUTBOX_MAX + 25):
            b.publish(msg(topic="t", payload=str(i).encode()))
    assert len(b.outbox["c"]) == b.OUTBOX_MAX
    # oldest dropped, newest kept
    assert int(b.outbox["c"][0].msg.payload) == 25
    assert b.metrics.get("broker.outbox.dropped") == 25
    warnings = [r for r in caplog.records if "outbox overflow" in r.message]
    assert len(warnings) == 1  # logged once per client, not per drop


def test_effective_message_shared_when_no_transform():
    b = Broker()
    m = msg(topic="t", qos=0)
    assert b._effective(m, SubOpts(qos=0)) is m        # zero-copy
    eff = b._effective(msg(topic="t", qos=2), SubOpts(qos=1))
    assert eff.qos == 1                                # capped
    eff = b._effective(msg(topic="t", retain=True), SubOpts(rap=False))
    assert eff.retain is False                         # RAP off clears
    eff = b._effective(m, SubOpts(subid=7))
    assert eff.properties["Subscription-Identifier"] == 7


def test_clone_does_not_inherit_qos0_publish_cache():
    # Session.deliver's bulk QoS0 path caches a shared Publish on the
    # message (_pub0); a clone (subid / rap transform) must not inherit
    # it or the transformed subscriber gets the ORIGINAL message back
    m = msg(topic="t", qos=0)
    s = Session("plain")
    s.subscribe("t", SubOpts(qos=0))
    sends, _ = s.deliver([m])
    assert sends[0].msg is m              # cache primed on the original
    eff = m.clone(properties={"Subscription-Identifier": 7})
    s2 = Session("tagged")
    s2.subscribe("t", SubOpts(qos=0, subid=7))
    sends2, _ = s2.deliver([eff])
    assert sends2[0].msg is eff           # NOT the stale cached Publish
    assert sends2[0].msg.properties["Subscription-Identifier"] == 7


def test_qos0_fanout_subid_and_rap_survive_publish_order():
    # end-to-end shape of the same bug: a no-transform subscriber primes
    # the cache on the ORIGINAL message, then subid/rap subscribers
    # (whose view is a clone) must still see their transformed view
    b = Broker()
    got = {}
    b.on_deliver = lambda cid, pubs: got.setdefault(cid, []).extend(pubs)
    b.open_session("plain")
    b.subscribe("plain", "t", SubOpts(qos=0))          # eff IS the original
    b.open_session("tagged")
    b.subscribe("tagged", "t", SubOpts(qos=0, subid=9))
    b.publish(msg(topic="t", qos=0))
    assert "Subscription-Identifier" not in got["plain"][0].msg.properties
    assert got["tagged"][0].msg.properties["Subscription-Identifier"] == 9
    # retain-as-published variant: the rap=True leg primes the cache on
    # the original, the rap=False leg's clone must see retain cleared
    b.open_session("keep")
    b.subscribe("keep", "r", SubOpts(qos=0, rap=True))
    b.open_session("clear")
    b.subscribe("clear", "r", SubOpts(qos=0))
    b.publish(msg(topic="r", qos=0, retain=True))
    assert got["keep"][0].msg.retain is True
    assert got["clear"][0].msg.retain is False


def test_expired_queued_messages_accounted():
    b = Broker()
    s, _ = b.open_session("c", max_inflight=1)
    b.subscribe("c", "t", SubOpts(qos=1))
    b.publish(msg(topic="t", qos=1))  # fills window
    b.publish(msg(topic="t", qos=1,
                  properties={"Message-Expiry-Interval": 0}))  # queued, expires
    import time
    time.sleep(0.01)
    dropped_before = s.mqueue.dropped
    _, more = s.puback(1)
    assert more == []                     # expired message not delivered
    assert s.mqueue.dropped == dropped_before + 1
