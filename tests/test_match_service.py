"""In-process TPU match service: the broker's own publish path rides the
device kernel (VERDICT.md round-1 weak item 4 / next-round item 5).

Covers: router-delta mirror sync, hint production/consumption, fail-open
on staleness, rule co-batching, and an e2e TCP publish storm where
dispatch demonstrably used the kernel (tpu.* metrics) with parity.
"""

import asyncio

import pytest

from emqx_tpu import topic as T
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=30.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def make_node(**extra):
    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    cfg.put("tpu.enable", True)  # env layer disables it for other tests
    cfg.put("tpu.mirror_refresh_interval", 0.01)
    cfg.put("tpu.bypass_rate", 0.0)  # pin the device path on for tests
    for k, v in extra.items():
        cfg.put(k, v)
    return BrokerNode(cfg)


def sub(b, cid, flt):
    if cid not in b.sessions:
        b.open_session(cid)
    b.subscribe(cid, flt)


def ms_synced(node):
    ms = node.match_service
    return (
        ms is not None and ms.ready
        and ms._seen_epoch == node.broker.router.epoch
        and ms.dev.epoch == ms.inc.epoch
    )


def test_publish_storm_uses_kernel_with_parity():
    async def main():
        node = make_node()
        await node.start()
        port = node.listeners.all()[0].port
        try:
            subs = []
            filters = []
            for i in range(6):
                c = Client(clientid=f"s{i}", port=port)
                await c.connect()
                flt = f"room/+/kind{i % 3}"
                await c.subscribe(flt, qos=0)
                subs.append(c)
                filters.append(flt)
            assert await settle(lambda: ms_synced(node))

            pub = Client(clientid="p", port=port)
            await pub.connect()
            topics = [f"room/{i}/kind{i % 3}" for i in range(30)]
            for t in topics:
                await pub.publish(t, b"x", qos=0)

            # every subscriber with a matching filter got every message
            async def got_all():
                want = sum(
                    1 for t in topics for f in filters if T.match(t, f)
                )
                have = sum(s.messages.qsize() for s in subs)
                return have >= want

            ok = False
            for _ in range(100):
                if await got_all():
                    ok = True
                    break
                await asyncio.sleep(0.05)
            assert ok, "deliveries missing"

            m = node.observed.metrics
            assert m.get("tpu.match.batches") >= 1
            assert m.get("tpu.match.topics") >= len(topics)
            assert m.get("tpu.mirror.refresh") >= 1
            for s in subs:
                await s.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_scoped_hint_invalidation():
    """Round-3 churn semantics: a router mutation only kills the hints it
    can actually make wrong.  Exact adds and any deletes resolve live via
    routes_with_wild; only a NEW wildcard filter matching the topic
    invalidates (VERDICT.md round-2 item 3)."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/x")
            assert ms.hint_routes("a/x") is not None

            # exact-filter add: the hint SURVIVES and already includes
            # the new route (exact map is read live)
            sub(b, "c2", "a/x")
            hint = ms.hint_routes("a/x")
            assert hint is not None
            assert sorted(map(tuple, hint)) == sorted(
                map(tuple, b.router.match_routes("a/x"))
            )

            # non-matching wildcard add: hint survives too
            sub(b, "c3", "zzz/+")
            assert ms.hint_routes("a/x") is not None

            # unsubscribe (delete): hint survives, route drops out live
            b.unsubscribe("c2", "a/x")
            hint = ms.hint_routes("a/x")
            assert hint is not None
            assert sorted(map(tuple, hint)) == sorted(
                map(tuple, b.router.match_routes("a/x"))
            )

            # a MATCHING wildcard add is the one poison case
            sub(b, "c4", "a/#")
            assert ms.hint_routes("a/x") is None
            assert node.observed.metrics.get("tpu.match.hint_stale") >= 1

            # after resync + re-prefetch the device path serves again
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/x")
            hint = ms.hint_routes("a/x")
            assert hint is not None
            assert sorted(map(tuple, hint)) == sorted(
                map(tuple, b.router.match_routes("a/x"))
            )
        finally:
            await node.stop()

    run(main())


def test_churn_keeps_device_duty_cycle():
    """Continuous subscribe/unsubscribe churn elsewhere in the topic
    space must not collapse the device path to host serving: duty cycle
    (hints served / publishes) stays >50% with full parity."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            for i in range(8):
                sub(b, f"s{i}", f"room/+/k{i}")
            assert await settle(lambda: ms_synced(node))

            m = node.observed.metrics
            topics = [f"room/{i}/k{i % 8}" for i in range(16)]
            served = 0
            total = 0
            for round_ in range(12):
                # churn: unrelated wildcard subs come and go every round
                sub(b, "churn", f"churnspace/{round_}/+")
                if round_ > 0:
                    b.unsubscribe("churn", f"churnspace/{round_ - 1}/+")
                for t in topics:
                    await ms.prefetch(t)
                    total += 1
                    hint = ms.hint_routes(t)
                    if hint is not None:
                        served += 1
                        want = b.router.match_routes(t)
                        assert sorted(map(tuple, hint)) == sorted(
                            map(tuple, want)
                        ), t
                await asyncio.sleep(0.005)
            duty = served / total
            assert duty > 0.5, f"device duty cycle {duty:.2f} under churn"
            assert m.get("tpu.match.hint_served") >= served
        finally:
            await node.stop()

    run(main())


def test_adaptive_bypass_low_concurrency():
    """With bypass enabled and a trickle of publishes, prefetch skips
    the device batching window entirely (host trie is faster at one-
    client load) and delivery still works via the host path."""

    async def main():
        node = make_node(**{"tpu.bypass_rate": 1e9})
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/x")
            assert node.observed.metrics.get("tpu.match.bypass") >= 1
            assert ms.hint_routes("a/x") is None  # no hint minted
            # broker delivery falls back to the host trie transparently
            from emqx_tpu.broker.message import make_message

            res = b.publish(make_message("p", "a/x", b"!"))
            assert res.matched >= 1
        finally:
            await node.stop()

    run(main())


def test_hint_routes_match_host_routes():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            flts = ["s/+/t", "s/#", "exact/topic", "+/b", "deep/a/b/c/d/e/f/+/x"]
            for i, f in enumerate(flts):
                sub(b, f"c{i}", f)
            assert await settle(lambda: ms_synced(node))
            for topic in ["s/1/t", "s/9", "exact/topic", "q/b", "none",
                          "deep/a/b/c/d/e/f/q/x"]:
                await ms.prefetch(topic)
                hint = ms.hint_routes(topic)
                assert hint is not None, topic
                want = b.router.match_routes(topic)
                assert sorted(map(tuple, hint)) == sorted(map(tuple, want)), topic
        finally:
            await node.stop()

    run(main())


def test_rule_cobatch_selected_by_hint():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            hits = []
            node.rule_engine.create_rule(
                "r1", 'SELECT topic FROM "evt/+/fire"',
                actions=[lambda out, cols: hits.append(out["topic"])],
            )
            node.rule_engine.create_rule(
                "r2", 'SELECT topic FROM "other/#"', actions=[],
            )
            sub(b, "c1", "evt/#")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("evt/z1/fire")
            assert ms.hint_rules("evt/z1/fire") == ["r1"]
            from emqx_tpu.broker.message import make_message

            b.publish(make_message("c9", "evt/z1/fire", b"!"))
            assert hits == ["evt/z1/fire"]
            # unregister: a stale hint may still NAME the dead rule (the
            # safe direction — the engine skips unknown ids), but the
            # rule must never fire again
            node.rule_engine.delete_rule("r1")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("evt/z1/fire")
            b.publish(make_message("c9", "evt/z1/fire", b"!"))
            assert hits == ["evt/z1/fire"]  # unchanged: r1 never refired
        finally:
            await node.stop()

    run(main())


def test_bootstrap_refcounts_multiple_dests():
    """ADVICE r2 high 1: a filter bootstrapped with several live routes
    must survive the deletion of all but one of them."""

    async def main():
        node = make_node()
        b = node.broker
        sub(b, "c1", "m/+")
        sub(b, "c2", "m/+")
        await node.start()  # bootstrap sees 2 routes for m/+
        try:
            ms = node.match_service
            assert await settle(lambda: ms_synced(node))
            b.unsubscribe("c1", "m/+")
            assert await settle(lambda: ms_synced(node))
            assert ms.inc.n_filters == 1, "filter dropped while still routed"
            await ms.prefetch("m/1")
            hint = ms.hint_routes("m/1")
            assert hint is not None and len(hint) == 1
        finally:
            await node.stop()

    run(main())


def test_rule_registration_invalidates_hints():
    """ADVICE r2 medium: rule changes don't bump the router epoch; a
    hint minted before a rule registration must not claim 'no rules'."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "evt/#")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("evt/x")
            assert ms.hint_rules("evt/x") == []
            hits = []
            node.rule_engine.create_rule(
                "r1", 'SELECT topic FROM "evt/+"',
                actions=[lambda out, cols: hits.append(out["topic"])],
            )
            # stale in the rules dimension now → engine host-matches
            assert ms.hint_rules("evt/x") is None
            from emqx_tpu.broker.message import make_message

            b.publish(make_message("p", "evt/x", b"!"))
            assert hits == ["evt/x"]
        finally:
            await node.stop()

    run(main())


def test_unsubscribe_prunes_mirror():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "x/+")
            assert await settle(lambda: ms_synced(node))
            assert ms.inc.n_filters == 1
            b.unsubscribe("c1", "x/+")
            assert await settle(
                lambda: ms_synced(node) and ms.inc.n_filters == 0
            )
        finally:
            await node.stop()

    run(main())


def test_table_kind_selection_and_python_parity():
    """tpu.table=auto picks the native C++ table when buildable; the
    python twin passes the same storm (both serve identical hints)."""
    async def main():
        node_native = make_node()
        await node_native.start()
        try:
            ms = node_native.match_service
            assert ms is not None
            # this environment has the toolchain: auto => native
            assert ms.table_kind == "native"
        finally:
            await node_native.stop()

        node_py = make_node(**{"tpu.table": "python"})
        await node_py.start()
        try:
            ms = node_py.match_service
            assert ms is not None and ms.table_kind == "python"
            port = node_py.listeners.all()[0].port
            sub = Client(clientid="s", port=port)
            await sub.connect()
            await sub.subscribe("k/+/x")
            await settle(lambda: ms.dev.epoch == ms.inc.epoch)
            pub = Client(clientid="p", port=port)
            await pub.connect()
            await pub.publish("k/1/x", b"v")
            got = await sub.recv(timeout=5)
            assert (got.topic, got.payload) == ("k/1/x", b"v")
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await node_py.stop()

    run(main())


def test_depth_bucketed_batch_parity():
    """A mixed-depth batch split across the shallow and full kernels
    produces the same hints as the host trie (split_min=1 pins the
    split on)."""
    async def main():
        node = make_node(**{"tpu.split_min": 1, "tpu.batch_size": 512})
        await node.start()
        try:
            ms = node.match_service
            assert ms is not None
            port = node.listeners.all()[0].port
            sub = Client(clientid="s", port=port)
            await sub.connect()
            for flt in ("a/+", "a/+/c/+/e", "deep/+/x/+/z/+/q", "#"):
                await sub.subscribe(flt)
            await settle(lambda: ms.dev.epoch == ms.inc.epoch)

            assert await settle(lambda: ms.ready, timeout=120)
            topics = ["a/b", "a/b/c/d/e", "deep/1/x/2/z/3/q", "nah",
                      "a/q", "deep/only"]
            # push one batch through the device loop directly
            futs = []
            loop = asyncio.get_running_loop()
            for t in topics:
                f = loop.create_future()
                futs.append(f)
                ms._pending.append((t, f))
            ms._batch_wake.set()
            # first compiles of BOTH kernel shapes can take a while on CPU
            assert await settle(
                lambda: all(f.done() for f in futs), timeout=180)
            from emqx_tpu import topic as T

            missing = 0
            for t in topics:
                hint = ms._hints.get(t)
                want = sorted(
                    f for f in ("a/+", "a/+/c/+/e", "deep/+/x/+/z/+/q", "#")
                    if T.match(t, f)
                )
                if hint is None:
                    missing += 1
                    continue
                assert sorted(hint[2]) == want, (t, hint[2], want)
            assert missing == 0, f"{missing} topics got no hint"
            # the split actually happened: 2 kernel batches for 1 wake
            assert node.observed.metrics.all().get(
                "tpu.match.batches", 0) >= 2
        finally:
            await node.stop()

    run(main())


def test_hint_cache_lru_eviction_no_thrash():
    """VERDICT r3 weak 9: a working set just over hint_cap must not
    flip the cache between full and empty.  Eviction takes only the
    least-recently-served entries, so the hot head of a Zipf working
    set keeps its hints (and its device duty cycle) while the cold
    tail cycles through."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            ms.hint_cap = 24  # scaled-down 64k working-set scenario
            sub(b, "s", "room/+/k")
            assert await settle(lambda: ms_synced(node))

            hot = [f"room/h{i}/k" for i in range(8)]
            # warm the hot set and mark it served (moves to LRU tail)
            for t in hot:
                await ms.prefetch(t)
            for t in hot:
                assert ms.hint_routes(t) is not None

            served_hot = 0
            total_hot = 0
            for round_ in range(6):
                # a cold tail larger than the remaining capacity arrives,
                # interleaved with hot serves (Zipf: the hot head is hit
                # far more often than any one cold topic)
                cold = [f"room/c{round_}_{i}/k" for i in range(20)]
                for ci, t in enumerate(cold):
                    await ms.prefetch(t)
                    if ci % 4 == 3:
                        for h in hot:
                            total_hot += 1
                            if ms.hint_routes(h) is not None:
                                served_hot += 1
                # the cache never exceeds cap and never empties
                assert len(ms._hints) <= ms.hint_cap
                assert len(ms._hints) >= 8
            duty = served_hot / total_hot
            assert duty > 0.9, f"hot-set duty cycle {duty:.2f} thrashed"
            m = node.observed.metrics
            assert m.get("tpu.match.hint_evicted") >= 1
        finally:
            await node.stop()

    run(main())

def test_rules_only_hot_set_survives_lru_eviction():
    """VERDICT r4 weak 8: `hint_rules` hits must refresh LRU recency
    exactly like `hint_routes` does — a rules-only working set (topics
    matched by rule FROM-filters but with no subscribers) is hot, and
    must not age out of the cache under a cold tail."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            ms.hint_cap = 24
            node.rule_engine.create_rule(
                "r1", 'SELECT topic FROM "room/+/k"', actions=[],
            )
            # a subscription on an unrelated branch keeps the table
            # non-empty without routing the hot topics
            sub(b, "s", "other/+")
            assert await settle(lambda: ms_synced(node))

            hot = [f"room/h{i}/k" for i in range(8)]
            for t in hot:
                await ms.prefetch(t)
            for t in hot:
                assert ms.hint_rules(t) == ["r1"]

            served_hot = 0
            total_hot = 0
            for round_ in range(6):
                cold = [f"room/c{round_}_{i}/k" for i in range(20)]
                for ci, t in enumerate(cold):
                    await ms.prefetch(t)
                    if ci % 4 == 3:
                        for h in hot:
                            total_hot += 1
                            if ms.hint_rules(h) is not None:
                                served_hot += 1
                assert len(ms._hints) <= ms.hint_cap
            duty = served_hot / total_hot
            assert duty > 0.9, \
                f"rules-only hot set duty cycle {duty:.2f} thrashed"
        finally:
            await node.stop()

    run(main())
