"""In-process TPU match service: the broker's own publish path rides the
device kernel (VERDICT.md round-1 weak item 4 / next-round item 5).

Covers: router-delta mirror sync, hint production/consumption, fail-open
on staleness, rule co-batching, and an e2e TCP publish storm where
dispatch demonstrably used the kernel (tpu.* metrics) with parity.
"""

import asyncio

import pytest

from emqx_tpu import topic as T
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=8.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def make_node(**extra):
    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    cfg.put("tpu.enable", True)  # env layer disables it for other tests
    cfg.put("tpu.mirror_refresh_interval", 0.01)
    for k, v in extra.items():
        cfg.put(k, v)
    return BrokerNode(cfg)


def sub(b, cid, flt):
    if cid not in b.sessions:
        b.open_session(cid)
    b.subscribe(cid, flt)


def ms_synced(node):
    ms = node.match_service
    return (
        ms is not None and ms.ready
        and ms._seen_epoch == node.broker.router.epoch
        and ms.dev.epoch == ms.inc.epoch
    )


def test_publish_storm_uses_kernel_with_parity():
    async def main():
        node = make_node()
        await node.start()
        port = node.listeners.all()[0].port
        try:
            subs = []
            filters = []
            for i in range(6):
                c = Client(clientid=f"s{i}", port=port)
                await c.connect()
                flt = f"room/+/kind{i % 3}"
                await c.subscribe(flt, qos=0)
                subs.append(c)
                filters.append(flt)
            assert await settle(lambda: ms_synced(node))

            pub = Client(clientid="p", port=port)
            await pub.connect()
            topics = [f"room/{i}/kind{i % 3}" for i in range(30)]
            for t in topics:
                await pub.publish(t, b"x", qos=0)

            # every subscriber with a matching filter got every message
            async def got_all():
                want = sum(
                    1 for t in topics for f in filters if T.match(t, f)
                )
                have = sum(s.messages.qsize() for s in subs)
                return have >= want

            ok = False
            for _ in range(100):
                if await got_all():
                    ok = True
                    break
                await asyncio.sleep(0.05)
            assert ok, "deliveries missing"

            m = node.observed.metrics
            assert m.get("tpu.match.batches") >= 1
            assert m.get("tpu.match.topics") >= len(topics)
            assert m.get("tpu.mirror.refresh") >= 1
            for s in subs:
                await s.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_stale_hint_falls_back_to_host():
    """A hint minted before a router mutation must not be consumed."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/x")
            assert ms.hint_routes("a/x") is not None
            # mutate the router: the hint is now poison and must die
            sub(b, "c2", "a/x")
            assert ms.hint_routes("a/x") is None
        finally:
            await node.stop()

    run(main())


def test_hint_routes_match_host_routes():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            flts = ["s/+/t", "s/#", "exact/topic", "+/b", "deep/a/b/c/d/e/f/+/x"]
            for i, f in enumerate(flts):
                sub(b, f"c{i}", f)
            assert await settle(lambda: ms_synced(node))
            for topic in ["s/1/t", "s/9", "exact/topic", "q/b", "none",
                          "deep/a/b/c/d/e/f/q/x"]:
                await ms.prefetch(topic)
                hint = ms.hint_routes(topic)
                assert hint is not None, topic
                want = b.router.match_routes(topic)
                assert sorted(map(tuple, hint)) == sorted(map(tuple, want)), topic
        finally:
            await node.stop()

    run(main())


def test_rule_cobatch_selected_by_hint():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            hits = []
            node.rule_engine.create_rule(
                "r1", 'SELECT topic FROM "evt/+/fire"',
                actions=[lambda out, cols: hits.append(out["topic"])],
            )
            node.rule_engine.create_rule(
                "r2", 'SELECT topic FROM "other/#"', actions=[],
            )
            sub(b, "c1", "evt/#")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("evt/z1/fire")
            assert ms.hint_rules("evt/z1/fire") == ["r1"]
            from emqx_tpu.broker.message import make_message

            b.publish(make_message("c9", "evt/z1/fire", b"!"))
            assert hits == ["evt/z1/fire"]
            # unregister drops it from the co-batch
            node.rule_engine.delete_rule("r1")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("evt/z1/fire")
            assert ms.hint_rules("evt/z1/fire") == []
        finally:
            await node.stop()

    run(main())


def test_unsubscribe_prunes_mirror():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "x/+")
            assert await settle(lambda: ms_synced(node))
            assert ms.inc.n_filters == 1
            b.unsubscribe("c1", "x/+")
            assert await settle(
                lambda: ms_synced(node) and ms.inc.n_filters == 0
            )
        finally:
            await node.stop()

    run(main())
