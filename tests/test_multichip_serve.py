"""Multichip serve backend (ISSUE 15): the match table sharded by
topic-prefix over the virtual 8-device CPU mesh, serving real publish
traffic through MatchService.

Covers: compact-contract parity against the host tables and the
single-chip flat path (bit-for-bit), per-shard truncation psum
fail-open, delta churn + growth restacks, per-shard segment
persistence with the epoch/checksum guards, kernel-cache mesh keys
(CompileMiss + prewarm), shard-kill / ``match.shard`` fault chaos with
delivery held at 1.0 via CPU failover, and the flag-off spy (the
single-chip path is byte-identical — no matcher is even constructed).
"""

import asyncio
import json
import os

import numpy as np
import pytest

from emqx_tpu import faultinject
from emqx_tpu import topic as T
from emqx_tpu.client import Client
from emqx_tpu.config import Config
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.node import BrokerNode
from emqx_tpu.observe.metrics import Metrics
from emqx_tpu.ops.incremental import IncrementalNfa
from emqx_tpu.parallel import multichip_serve as mcs_mod
from emqx_tpu.parallel.multichip_serve import (
    MultichipMatcher, ShardDead, is_micro_filter, serve_mesh_shape,
    shard_of_filter,
)

FILTERS = ["a/+", "a/#", "+/b", "#", "x/y/z", "x/+/z", "$SYS/#",
           "rooms/+/temp", "rooms/1/#", "b/c", "deep/+/q/+", "m/n"]


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=60.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def make_node(**extra):
    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    cfg.put("tpu.enable", True)
    cfg.put("tpu.mirror_refresh_interval", 0.01)
    cfg.put("tpu.bypass_rate", 0.0)
    cfg.put("match.multichip.enable", True)
    for k, v in extra.items():
        cfg.put(k, v)
    return BrokerNode(cfg)


def build_pair(filters=FILTERS, depth=8, **mc_kw):
    """(service table, matcher with the same aid space, pairs)."""
    inc = IncrementalNfa(depth=depth)
    pairs = []
    for f in filters:
        inc.add(f)
        pairs.append((f, inc.aid_of(f)))
    mc = MultichipMatcher(depth=depth, **mc_kw)
    mc.rebuild(pairs)
    assert mc.apply_pending()
    return inc, mc, pairs


def topics_for(n, seed=5):
    rng = np.random.default_rng(seed)
    words = ["a", "b", "x", "y", "z", "rooms", "1", "temp", "m", "n",
             "deep", "q"]
    return ["/".join(rng.choice(words, size=rng.integers(1, 5)))
            for _ in range(n)]


def mesh_rows(mc, topics, batch=64, depth=None):
    enc = mc.encode(topics, batch=batch, depth=depth)
    return mc.readback(mc.dispatch(enc), len(topics))


# ---------------------------------------------------------------------------
# partition + parity (CPU mesh)
# ---------------------------------------------------------------------------

def test_mesh_shape_and_partition_determinism():
    assert serve_mesh_shape(8) == {"dp": 2, "tp": 4}
    assert serve_mesh_shape(8, tp=2) == {"dp": 4, "tp": 2}
    assert serve_mesh_shape(1) == {"dp": 1, "tp": 1}
    for f in FILTERS:
        t = shard_of_filter(f, 4)
        assert 0 <= t < 4
        assert t == shard_of_filter(f, 4)  # deterministic
    # the partition spreads the whole table over the shards; the
    # wildcard-root filters live in the replicated micro-table instead
    # of crc32-hashing to one arbitrary shard (ISSUE 16)
    _inc, mc, _pairs = build_pair()
    per_shard = [sub.n_filters for sub in mc._subs]
    n_micro = sum(1 for f in FILTERS if is_micro_filter(f))
    assert n_micro >= 2            # corpus keeps the micro path honest
    assert len(mc._micro_filters) == n_micro
    assert sum(per_shard) == len(FILTERS) - n_micro
    assert mc.dp * mc.tp == 8


def test_compact_rows_bit_for_bit_vs_host_and_single_chip():
    """The dense compact contract off the mesh must reproduce the
    single-chip serve path's rows bit-for-bit (same service accept
    ids) and agree with the host walk on every topic."""
    from emqx_tpu.broker.match_service import MatchService
    from emqx_tpu.ops import encode_batch
    from emqx_tpu.ops.device_table import DeviceNfa

    inc, mc, _pairs = build_pair()
    dev = DeviceNfa(inc, active_slots=8, max_matches=16)
    topics = topics_for(64)
    rows8, sp8, nbytes = mesh_rows(mc, topics)
    assert nbytes > 0
    enc = encode_batch(inc, topics, batch=64)
    res = dev.match(*enc, flat_cap=8 * 64)
    rows1, sp1 = MatchService._readback_rows(res, len(topics), 16)
    assert not sp8 and not sp1
    for t, r8, r1 in zip(topics, rows8, rows1):
        assert sorted(r8) == sorted(r1) == sorted(inc.match_host(t)), t


def test_delta_churn_and_growth_restack_parity():
    """note_add/note_del ride the drain/apply cycle; enough adds to
    cross a pow2 boundary force a restack (gen bump) and parity must
    hold through both regimes."""
    inc, mc, _pairs = build_pair()
    gen0 = mc.gen
    # small delta: scatters, no restack
    for f in ("live/+/one", "live/two"):
        inc.add(f)
        mc.note_add(f, inc.aid_of(f))
    inc.remove("a/+")
    mc.note_del("a/+")
    assert mc.apply_pending()
    topics = topics_for(32) + ["live/x/one", "live/two", "a/q"]
    rows, sp, _ = mesh_rows(mc, topics)
    for t, r in zip(topics, rows):
        if topics.index(t) in sp:
            continue
        assert sorted(r) == sorted(inc.match_host(t)), t
    # bulk growth: resized deltas restack the stacked tables
    for i in range(400):
        f = f"grow/{i}/+"
        inc.add(f)
        mc.note_add(f, inc.aid_of(f))
    assert mc.apply_pending()
    assert mc.gen > gen0
    rows, sp, _ = mesh_rows(mc, ["grow/7/z", "grow/399/z", "m/n"])
    assert not sp
    for t, r in zip(["grow/7/z", "grow/399/z", "m/n"], rows):
        assert sorted(r) == sorted(inc.match_host(t)), t


def test_truncation_psum_fail_open():
    """Per-shard truncation: every row the psum'd overflow did NOT
    flag must be COMPLETE (the flag may over-approximate — the host
    re-runs flagged rows — but never under-approximate)."""
    inc, mc, _pairs = build_pair(max_matches=1, ep_micro_matches=1)
    # shard segments truncate ("x/y/z" matches x/y/z + x/+/z on the
    # "x" shard) AND the micro segment truncates ("a/b" matches the
    # wildcard-root "+/b" + "#" past the 1-slot micro cap)
    topics = ["a/b", "a/b/c", "x/y/z", "m/n", "b/c"]
    rows, sp, _ = mesh_rows(mc, topics)
    spset = set(sp)
    assert spset, "expected at least one truncated row"
    for i, t in enumerate(topics):
        if i not in spset:
            assert sorted(rows[i]) == sorted(inc.match_host(t)), t


# ---------------------------------------------------------------------------
# chaos: dead shards + the match.shard seam (matcher level)
# ---------------------------------------------------------------------------

def test_shard_kill_raises_and_counts_failover():
    inc, mc, _pairs = build_pair()
    enc = mc.encode(["a/b"], batch=64)
    mc.dispatch(enc)
    mc.kill_shard(2)
    with pytest.raises(ShardDead):
        mc.dispatch(enc)
    assert mc.failovers == 1
    mc.revive_shard(2)
    rows, _, _ = mesh_rows(mc, ["a/b"])
    assert sorted(rows[0]) == sorted(inc.match_host("a/b"))


def test_match_shard_fault_injection_point():
    inc, mc, _pairs = build_pair()
    enc = mc.encode(["a/b"], batch=64)
    faultinject.install(FaultInjector([
        {"point": "match.shard", "action": "raise", "times": 1},
    ]))
    try:
        with pytest.raises(faultinject.InjectedFault):
            mc.dispatch(enc)
        assert mc.failovers == 1
        mc.dispatch(enc)   # rule exhausted: healthy again
    finally:
        faultinject.uninstall()


# ---------------------------------------------------------------------------
# per-shard segment persistence
# ---------------------------------------------------------------------------

def test_segments_roundtrip_epoch_and_checksum_guards(tmp_path):
    inc, mc, _pairs = build_pair()
    d = str(tmp_path)
    mc.save_segments(d, epoch=inc.epoch)
    topics = topics_for(16)
    want, _, _ = mesh_rows(mc, topics)

    # epoch mismatch -> repartition serves
    mc2 = MultichipMatcher(depth=8)
    assert not mc2.load_segments(d, expect_epoch=inc.epoch + 1)
    # matching epoch -> seeded, restacked at the next apply, parity
    mc3 = MultichipMatcher(depth=8)
    assert mc3.load_segments(d, expect_epoch=inc.epoch)
    assert mc3.dirty and not mc3.ready
    assert mc3.apply_pending()
    got, _, _ = mesh_rows(mc3, topics)
    assert [sorted(r) for r in got] == [sorted(r) for r in want]
    assert mc3.seeded_from_segments

    # tampered aid maps -> checksum reject
    mpath = os.path.join(d, "multichip", "aid_maps.npz")
    maps = dict(np.load(mpath))
    maps["m0"] = np.asarray(maps["m0"], np.int32) + 1
    np.savez(mpath, **maps)
    mc4 = MultichipMatcher(depth=8)
    assert not mc4.load_segments(d, expect_epoch=inc.epoch)

    # wrong tp layout -> rejected before any array is trusted
    mc5 = MultichipMatcher(depth=8, tp=2)
    assert not mc5.load_segments(d, expect_epoch=inc.epoch)


# ---------------------------------------------------------------------------
# kernel-cache mesh dimension
# ---------------------------------------------------------------------------

def test_kernel_cache_mesh_keys_compile_miss_and_prewarm():
    from emqx_tpu.ops.kernel_cache import CompileMiss, MatchKernelCache

    kc = MatchKernelCache()
    inc, mc, _pairs = build_pair(kernel_cache=kc)
    enc = mc.encode(["a/b"], batch=64)
    # non-blocking cold shape: the serving contract (CPU answers NOW)
    with pytest.raises(CompileMiss):
        mc.dispatch(enc, block_compile=False)
    # blocking compile, then a hit
    rows, _, _ = mc.readback(mc.dispatch(enc, block_compile=True), 1)
    assert sorted(rows[0]) == sorted(inc.match_host("a/b"))
    h0 = kc.hits
    mc.dispatch(enc)
    assert kc.hits > h0
    # prewarm replays the MESH combo against the next pow2 table shape
    smax, hbmax = mc._stacked_shape[0], mc._stacked_shape[1]
    assert not kc.shape_covered(2 * smax, hbmax)
    n = kc.prewarm_shape(2 * smax, hbmax)
    assert n >= 1
    assert kc.shape_covered(2 * smax, hbmax)


# ---------------------------------------------------------------------------
# MatchService integration (full node on the CPU mesh)
# ---------------------------------------------------------------------------

def test_node_multichip_serves_then_shard_kill_holds_delivery():
    """Real traffic through the sharded table: hints ride the mesh
    with parity; a killed shard degrades like any device failure —
    the CPU trie answers and delivery stays 1.0 (tier-1 chaos)."""

    async def main():
        node = make_node()
        await node.start()
        ms = node.match_service
        assert ms is not None and ms.mc is not None
        assert ms.mc.n_devices == 8
        port = node.listeners.all()[0].port
        try:
            subs, filters = [], []
            for i in range(4):
                c = Client(clientid=f"s{i}", port=port)
                await c.connect()
                flt = f"room/+/kind{i % 2}"
                await c.subscribe(flt, qos=0)
                subs.append(c)
                filters.append(flt)
            assert await settle(lambda: ms.ready and ms.mc.ready)
            d0 = ms.mc.dispatches
            pub = Client(clientid="p", port=port)
            await pub.connect()
            topics = [f"room/{i}/kind{i % 2}" for i in range(20)]
            for t in topics:
                await pub.publish(t, b"x", qos=0)
            want = sum(1 for t in topics for f in filters
                       if T.match(t, f))
            assert await settle(
                lambda: sum(s.messages.qsize() for s in subs) >= want)
            m = node.observed.metrics
            assert ms.mc.dispatches > d0, "batches did not ride the mesh"
            assert m.get("tpu.match.shard_dispatches") >= 1
            assert m.get("tpu.match.shard_devices") == 8
            assert m.get("tpu.match.batches") >= 1

            # chaos: dead shard -> CPU failover, delivery_ratio 1.0
            ms.mc.kill_shard(1)
            topics2 = [f"room/{100 + i}/kind{i % 2}" for i in range(20)]
            for t in topics2:
                await pub.publish(t, b"y", qos=0)
            want2 = want + sum(1 for t in topics2 for f in filters
                               if T.match(t, f))
            assert await settle(
                lambda: sum(s.messages.qsize() for s in subs) >= want2)
            assert m.get("tpu.match.shard_failover") >= 1
            info = ms.info()["multichip"]
            assert info["dead_shards"] == [1]
            for s in subs:
                await s.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


def test_node_match_shard_fault_failover_and_recovery():
    """An injected ``match.shard`` raise behaves like a device
    failure: the batch falls to the CPU trie (hints still answer),
    and once the rule is exhausted the mesh serves again."""

    async def main():
        node = make_node()
        await node.start()
        ms = node.match_service
        assert ms is not None and ms.mc is not None
        try:
            b = node.broker
            if "c1" not in b.sessions:
                b.open_session("c1")
            b.subscribe("c1", "f/+")
            assert await settle(lambda: ms.ready and ms.mc.ready)
            faultinject.install(FaultInjector([
                {"point": "match.shard", "action": "raise", "times": 2},
            ]))
            await ms.prefetch("f/one")
            # device path refused; the publish path still answers via
            # the host trie (no fresh hint was minted)
            inj = faultinject.get()
            assert inj is not None
            assert inj.fired.get("match.shard", 0) >= 1
            faultinject.uninstall()
            d0 = ms.mc.dispatches
            await ms.prefetch("f/two")
            assert await settle(lambda: ms.mc.dispatches > d0)
            assert ms.hint_routes("f/two") is not None
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


def test_compaction_swap_repartitions_and_serves():
    """A compacted-table swap reassigns EVERY aid: the shard
    partition rebuilds from the fresh space (mc.gen bumps) and serving
    parity holds on the new table."""

    async def main():
        import tempfile

        seg = tempfile.mkdtemp()
        node = make_node(**{
            "match.segments.enable": True,
            "match.segments.dir": seg,
            "match.segments.compact_interval": 0.2,
            "match.segments.compact_min_mutations": 1,
        })
        await node.start()
        ms = node.match_service
        assert ms is not None and ms.mc is not None
        try:
            b = node.broker
            if "c1" not in b.sessions:
                b.open_session("c1")
            for i in range(8):
                b.subscribe("c1", f"swap/{i}/+")
            assert await settle(lambda: ms.ready and ms.mc.ready)
            gen0 = ms.mc.gen
            assert await settle(lambda: ms._table_gen >= 1, timeout=30)
            # the repartition lands on the next sync pass
            assert await settle(
                lambda: ms.mc.ready and ms.mc.gen > gen0, timeout=30)
            await ms.prefetch("swap/3/x")
            routes = ms.hint_routes("swap/3/x")
            assert routes is not None
            # per-shard segments persisted next to the main segment
            assert os.path.exists(
                os.path.join(seg, "multichip", "manifest.json"))
            with open(os.path.join(seg, "multichip",
                                   "manifest.json")) as f:
                assert json.load(f)["tp"] == ms.mc.tp
        finally:
            await node.stop()

    run(main())


def test_flag_off_is_byte_identical_single_chip_path(monkeypatch):
    """match.multichip.enable off: no matcher is constructed (spy),
    the serve plane dispatches through the single-chip DeviceNfa, and
    the shard metrics stay zero."""
    calls = []
    real = mcs_mod.MultichipMatcher

    class Spy(real):
        def __init__(self, *a, **kw):
            calls.append(1)
            super().__init__(*a, **kw)

    monkeypatch.setattr(mcs_mod, "MultichipMatcher", Spy)

    async def main():
        cfg = Config(
            file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", True)
        cfg.put("tpu.mirror_refresh_interval", 0.01)
        cfg.put("tpu.bypass_rate", 0.0)
        node = BrokerNode(cfg)
        await node.start()
        ms = node.match_service
        try:
            assert ms is not None
            assert ms.mc is None
            b = node.broker
            if "c1" not in b.sessions:
                b.open_session("c1")
            b.subscribe("c1", "off/+")
            assert await settle(lambda: ms.ready)
            await ms.prefetch("off/x")
            assert ms.hint_routes("off/x") is not None
            m = node.observed.metrics
            assert m.get("tpu.match.batches") >= 1
            assert m.get("tpu.match.shard_dispatches") == 0
            assert m.get("tpu.match.shard_devices") == 0
            assert not calls, "flag off must not construct a matcher"
            assert ms.info()["multichip"] is None
        finally:
            await node.stop()

    run(main())

# ---------------------------------------------------------------------------
# prefix-EP routed front end (ISSUE 16)
# ---------------------------------------------------------------------------

def build_ep_pair(filters=FILTERS, depth=8, **mc_kw):
    inc = IncrementalNfa(depth=depth)
    pairs = []
    for f in filters:
        inc.add(f)
        pairs.append((f, inc.aid_of(f)))
    mc = MultichipMatcher(depth=depth, ep=True, **mc_kw)
    mc.rebuild(pairs)
    assert mc.apply_pending()
    return inc, mc, pairs


def test_ep_routed_parity_vs_replicated_mixed_roots():
    """Routed bit-parity: a mixed literal/wildcard-root corpus served
    through the EP front end must reproduce the replicated-batch
    backend's rows (and the host walk) exactly — the owner's merged
    own+micro segment covers everything the fanned batch saw."""
    inc, mc_rep, pairs = build_pair()
    mc_ep = MultichipMatcher(depth=8, ep=True, ep_slack=4.0)
    mc_ep.rebuild(pairs)
    assert mc_ep.apply_pending()
    topics = topics_for(48)
    rows_r, sp_r, _ = mesh_rows(mc_rep, topics)
    rows_e, sp_e, _ = mesh_rows(mc_ep, topics)
    assert mc_ep.ep_dispatches == 1 and mc_rep.ep_dispatches == 0
    assert not sp_r and not sp_e
    for t, rr, re_ in zip(topics, rows_r, rows_e):
        assert sorted(re_) == sorted(rr) == sorted(inc.match_host(t)), t


def test_ep_bucket_overflow_fails_open():
    """A hot root skewing every row of a source slice to ONE owner
    overflows the (source, owner) bucket: overflowed rows are flagged
    for the CPU trie (never silently dropped), unflagged rows stay
    complete."""
    inc, mc, _pairs = build_ep_pair(ep_slack=1.0)
    # every topic under x/: all 8 rows of each source slice route to
    # the "x" owner, capacity ceil(1.0*8/4) = 2 -> 6 overflow/source
    topics = [f"x/{i}/z" for i in range(24)] + ["x/y/z"] * 8
    rows, sp, _ = mc.readback(
        mc.dispatch(mc.encode(topics, batch=64)), len(topics))
    assert sp, "expected bucket overflow on the skewed corpus"
    spset = set(sp)
    assert len(spset) < len(topics), "slack must keep some rows routed"
    for i, t in enumerate(topics):
        if i not in spset:
            assert sorted(rows[i]) == sorted(inc.match_host(t)), t


def test_ep_micro_table_completeness_unknown_roots():
    """Wildcard-root filters live in the replicated micro-table: a
    topic whose root was NEVER interned (word id 0, owner shard 0)
    still collects its full wildcard answer set on the routed path."""
    inc, mc, _pairs = build_ep_pair(ep_slack=4.0)
    topics = ["zzz/b", "unknown/word/here", "qqq"]
    rows, sp, _ = mc.readback(
        mc.dispatch(mc.encode(topics, batch=64)), len(topics))
    assert not sp
    for t, r in zip(topics, rows):
        want = sorted(inc.match_host(t))
        assert sorted(r) == want, (t, r, want)
        assert want, f"corpus must exercise the micro path for {t}"


def test_ep_micro_table_tracks_churn():
    """note_add/note_del of wildcard-root filters mutate the micro
    partition (not a crc32 shard) and serve on the next apply."""
    inc, mc, _pairs = build_ep_pair(ep_slack=4.0)
    inc.add("+/added")
    mc.note_add("+/added", inc.aid_of("+/added"))
    inc.remove("#")
    mc.note_del("#")
    assert mc.apply_pending()
    assert "+/added" in mc._micro_filters
    assert "#" not in mc._micro_filters
    topics = ["q/added", "zz/yy"]
    rows, sp, _ = mc.readback(
        mc.dispatch(mc.encode(topics, batch=64)), len(topics))
    assert not sp
    for t, r in zip(topics, rows):
        assert sorted(r) == sorted(inc.match_host(t)), t


def test_ep_route_fault_injection_point():
    """The routed front end's own seam: an injected ep.route raise
    refuses the dispatch (failover counted) without touching the
    replicated path."""
    inc, mc, _pairs = build_ep_pair(ep_slack=4.0)
    enc = mc.encode(["a/b"], batch=64)
    faultinject.install(FaultInjector([
        {"point": "ep.route", "action": "raise", "times": 1},
    ]))
    try:
        with pytest.raises(faultinject.InjectedFault):
            mc.dispatch(enc)
        assert mc.failovers == 1
        rows, _, _ = mc.readback(mc.dispatch(enc), 1)
        assert sorted(rows[0]) == sorted(inc.match_host("a/b"))
    finally:
        faultinject.uninstall()


def test_ep_shard_kill_raises_before_routing():
    inc, mc, _pairs = build_ep_pair(ep_slack=4.0)
    enc = mc.encode(["a/b"], batch=64)
    mc.dispatch(enc)
    mc.kill_shard(3)
    with pytest.raises(ShardDead):
        mc.dispatch(enc)
    assert mc.failovers == 1


def test_ep_metrics_width_gate_and_odd_batches_fall_back():
    """Routed dispatches publish the per-shard width tp*C (the
    gate_shard_width_le_batch_over_tp numerator) and the analytic ICI
    bill; batch shapes that don't split into tp source slices fall
    back to the replicated step for that dispatch."""
    from emqx_tpu.observe.metrics import Metrics

    m = Metrics()
    inc, mc, pairs = build_ep_pair(metrics=m)
    b = 64
    rows, _, _ = mc.readback(
        mc.dispatch(mc.encode(["a/b"], batch=b)), 1)
    assert sorted(rows[0]) == sorted(inc.match_host("a/b"))
    assert m.get("tpu.match.ep_dispatches") == 1
    width = m.get("tpu.match.ep_shard_width")
    assert width == mc.tp * mc.ep_capacity(b)
    import math
    assert width <= math.ceil(mc.ep_slack * (b // mc.dp) / mc.tp)
    assert m.get("tpu.match.ep_ici_bytes") > 0
    # 4-row batch: 4 % (dp*tp) != 0 -> replicated fallback, parity holds
    rows2, _, _ = mc.readback(
        mc.dispatch(mc.encode(["a/b"], batch=4)), 1)
    assert sorted(rows2[0]) == sorted(inc.match_host("a/b"))
    assert m.get("tpu.match.ep_dispatches") == 1  # unchanged


def test_node_ep_routed_serves_and_shard_kill_holds_delivery():
    """The full node with match.multichip.ep.enable: real publishes
    ride the routed step (ep metrics move), and a killed shard on the
    ROUTED path still degrades to the CPU trie at delivery 1.0."""

    async def main():
        node = make_node(**{"match.multichip.ep.enable": True})
        await node.start()
        ms = node.match_service
        assert ms is not None and ms.mc is not None and ms.mc.ep
        port = node.listeners.all()[0].port
        try:
            subs, filters = [], []
            for i in range(4):
                c = Client(clientid=f"s{i}", port=port)
                await c.connect()
                flt = f"room/+/kind{i % 2}"
                await c.subscribe(flt, qos=0)
                subs.append(c)
                filters.append(flt)
            assert await settle(lambda: ms.ready and ms.mc.ready)
            pub = Client(clientid="p", port=port)
            await pub.connect()
            topics = [f"room/{i}/kind{i % 2}" for i in range(20)]
            for t in topics:
                await pub.publish(t, b"x", qos=0)
            want = sum(1 for t in topics for f in filters
                       if T.match(t, f))
            assert await settle(
                lambda: sum(s.messages.qsize() for s in subs) >= want)
            m = node.observed.metrics
            assert await settle(
                lambda: m.get("tpu.match.ep_dispatches") >= 1)
            assert m.get("tpu.match.ep_shard_width") >= 1

            ms.mc.kill_shard(2)
            topics2 = [f"room/{100 + i}/kind{i % 2}" for i in range(20)]
            for t in topics2:
                await pub.publish(t, b"y", qos=0)
            want2 = want + sum(1 for t in topics2 for f in filters
                               if T.match(t, f))
            assert await settle(
                lambda: sum(s.messages.qsize() for s in subs) >= want2)
            assert m.get("tpu.match.shard_failover") >= 1
            for s in subs:
                await s.disconnect()
            await pub.disconnect()
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# count-compacted routed readback (ISSUE 17)
# ---------------------------------------------------------------------------

def test_ep_compact_parity_and_bytes_reduction():
    """The routed step's tp·K-wide segment answers collapse to one
    K-wide segment per row under ``ep_compact``: rows stay bit-equal
    to the routed AND replicated contracts (and the host walk) while
    the routed d2h bytes drop ~tp× — exactly one owner wrote each
    row, so the psum-merge loses nothing."""
    inc, mc_rep, pairs = build_pair()
    mc_ep = MultichipMatcher(depth=8, ep=True, ep_slack=4.0)
    mc_ep.rebuild(pairs)
    assert mc_ep.apply_pending()
    mc_c = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                            ep_compact=True)
    mc_c.rebuild(pairs)
    assert mc_c.apply_pending()
    assert mc_c.info()["ep_compact"] is True
    assert mc_ep.info()["ep_compact"] is False
    topics = topics_for(48)
    rows_r, sp_r, nb_r = mesh_rows(mc_rep, topics)
    rows_e, sp_e, nb_e = mesh_rows(mc_ep, topics)
    rows_c, sp_c, nb_c = mesh_rows(mc_c, topics)
    assert mc_c.ep_dispatches == 1 and mc_ep.ep_dispatches == 1
    assert not sp_r and not sp_e and not sp_c
    for t, rr, re_, rc in zip(topics, rows_r, rows_e, rows_c):
        assert sorted(rc) == sorted(re_) == sorted(rr) \
            == sorted(inc.match_host(t)), t
    # the compact contract ships (B, K) ids instead of (B, tp·K)
    assert nb_c < nb_e, (nb_c, nb_e)
    assert nb_c <= nb_e // 2, (nb_c, nb_e)


def test_ep_compact_overflow_fails_open():
    """Bucket overflow under the compact contract keeps the fail-open
    discipline: psum carries every shard's overflow flag into the
    collapsed row, so skewed rows are flagged for the CPU trie and
    unflagged rows stay complete."""
    inc, mc, _pairs = build_ep_pair(ep_slack=1.0, ep_compact=True)
    topics = [f"x/{i}/z" for i in range(24)] + ["x/y/z"] * 8
    rows, sp, _ = mc.readback(
        mc.dispatch(mc.encode(topics, batch=64)), len(topics))
    assert sp, "expected bucket overflow on the skewed corpus"
    spset = set(sp)
    assert len(spset) < len(topics), "slack must keep some rows routed"
    for i, t in enumerate(topics):
        if i not in spset:
            assert sorted(rows[i]) == sorted(inc.match_host(t)), t


def test_ep_compact_odd_batch_falls_back_replicated():
    """Batch shapes that can't split into tp source slices fall back
    to the replicated step under ep_compact too — same fallback gate,
    parity holds."""
    inc, mc, _pairs = build_ep_pair(ep_slack=4.0, ep_compact=True)
    rows, _, _ = mc.readback(
        mc.dispatch(mc.encode(["a/b"], batch=4)), 1)
    assert sorted(rows[0]) == sorted(inc.match_host("a/b"))
    assert mc.ep_dispatches == 0   # replicated fallback served


# ---------------------------------------------------------------------------
# degraded mesh: scoped failover, health ladder, online rebuild (ISSUE 18)
# ---------------------------------------------------------------------------

def fill_parity(inc, mc, topics, rows, sp, fill=None):
    """The scoped-failover delivery contract: every non-spilled row,
    credited with the CPU fill of the dead shards' aids, reproduces
    the host walk exactly."""
    fill = mc.dead_aids() if fill is None else fill
    spset = set(sp)
    for i, t in enumerate(topics):
        if i in spset:
            continue
        host = set(inc.match_host(t))
        assert set(rows[i]) | (host & fill) == host, t


def test_degraded_flag_off_whole_plane_failover_unchanged():
    """Flag OFF: a dead shard refuses every dispatch (the PR 17
    whole-plane CPU failover, byte-identical) and the step cache keys
    carry no micro_owner extension."""
    inc, mc, _pairs = build_pair()
    assert mc.degraded is False
    mc.kill_shard(0)
    assert not mc.degraded_serving
    assert mc.mesh_state() == 2
    with pytest.raises(ShardDead):
        mc.dispatch(mc.encode(["a/b"], batch=64))
    assert mc.failovers == 1 and mc.degraded_batches == 0
    mc.revive_shard(0)
    assert mc.mesh_state() == 0
    rows, _, _ = mesh_rows(mc, ["a/b"])
    assert sorted(rows[0]) == sorted(inc.match_host("a/b"))
    # PR 17 key shape verbatim: (batch, depth, kind) — no owner element
    assert all(len(k) == 3 for k in mc._steps)


def test_degraded_replicated_mask_and_micro_owner_migration():
    """Replicated scoped failover: the dead shard's answer segment is
    masked (rows decode exactly the LIVE shards' answers — the service
    CPU-fills the rest), micro filters never enter the fill set, and
    killing shard 0 migrates the micro merge point to the lowest live
    shard so wildcard-root answers stay on-device."""
    met = Metrics()
    inc, mc, _pairs = build_pair(degraded=True, metrics=met)
    topics = topics_for(24) + ["m/n", "q/b"]
    want = [set(inc.match_host(t)) for t in topics]
    mc.kill_shard(0)     # owns "m/n" AND the default micro merge point
    assert mc.degraded_serving and mc.mesh_state() == 1
    dead = mc.dead_aids()
    assert dead, "victim shard must own part of the corpus"
    micro_aids = set(mc._micro_filters.values())
    assert micro_aids and not (micro_aids & dead)
    rows, sp, _ = mesh_rows(mc, topics)
    assert not sp
    for t, r, w in zip(topics, rows, want):
        assert set(r) == w - dead, t
    # "q/b" matches only wildcard-root (micro) filters: fully on-device
    # through the MIGRATED merge owner
    i = topics.index("q/b")
    assert set(rows[i]) == want[i]
    assert mc.degraded_batches >= 1
    assert met.get("tpu.mesh.degraded_batches") >= 1
    assert met.get("tpu.mesh.state") == 1
    mc.revive_shard(0)
    rows2, _, _ = mesh_rows(mc, topics)
    assert [set(r) for r in rows2] == want


def test_degraded_ep_scoped_failover_row_accounting():
    """EP-routed degraded serving: EXACTLY the rows whose crc32-root
    owner is dead divert to the CPU trie; the other (tp-1)/tp of an
    owner-balanced batch stays on-device with bit-exact host parity
    (the dead shard's literal filters share no root with a live-owned
    row), and the divert set is counted on ``cpu_filled_rows``."""
    met = Metrics()
    tp = serve_mesh_shape(8)["tp"]
    roots: dict = {t: [] for t in range(tp)}
    i = 0
    while any(len(v) < 2 for v in roots.values()):
        r = f"r{i}"
        i += 1
        roots[shard_of_filter(f"{r}/a/+", tp)].append(r)
    inc = IncrementalNfa(depth=8)
    pairs = []
    for t in range(tp):
        for r in roots[t][:2]:
            for f in (f"{r}/a/+", f"{r}/b/#"):
                inc.add(f)
                pairs.append((f, inc.aid_of(f)))
    inc.add("+/m/#")
    pairs.append(("+/m/#", inc.aid_of("+/m/#")))
    mc = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                          degraded=True, metrics=met)
    mc.rebuild(pairs)
    assert mc.apply_pending()
    batch = 64
    topics = [f"{roots[k % tp][(k // tp) % 2]}/a/x" for k in range(batch)]
    rows0, sp0, _ = mesh_rows(mc, topics, batch=batch)
    assert not sp0 and mc.ep_dispatches == 1
    victim = 1
    mc.kill_shard(victim)
    assert mc.degraded_serving
    rows, sp, _ = mesh_rows(mc, topics, batch=batch)
    dead_rows = {k for k, t in enumerate(topics)
                 if shard_of_filter(t, tp) == victim}
    assert set(sp) == dead_rows
    assert len(sp) == batch // tp          # owner-balanced: exactly 1/tp
    for k, t in enumerate(topics):
        if k not in dead_rows:
            assert sorted(rows[k]) == sorted(inc.match_host(t)), t
    assert mc.cpu_filled_rows == len(dead_rows)
    assert met.get("tpu.mesh.cpu_filled_rows") == len(dead_rows)
    assert met.get("tpu.mesh.degraded_batches") >= 1


def test_degraded_double_kill_cpu_only_then_staged_readmit():
    """The double-kill rung: two dead shards drop the plane to
    cpu-only (every dispatch refused), and the staged re-admit climbs
    back — lowest shard rebuilt + canaried first (serving resumes
    degraded around the remaining dead shard), then the second, back
    to healthy with bit parity."""
    inc, mc, pairs = build_pair(degraded=True)
    topics = topics_for(24) + ["m/n", "b/c"]
    mc.kill_shard(0)
    assert mc.degraded_serving and mc.mesh_state() == 1
    mc.kill_shard(1)
    assert not mc.degraded_serving and mc.mesh_state() == 2
    with pytest.raises(ShardDead):
        mc.dispatch(mc.encode(topics, batch=64))
    for t in (0, 1):
        assert mc.rebuild_shard(t, pairs) >= 0.0
        ctop = mc.canary_topics(t)
        assert ctop, "victim shards own filters in this corpus"
        crows, csp = mc.canary_rows(ctop, 64, t)
        fill_parity(inc, mc, ctop, crows, csp,
                    fill=mc.dead_aids(exclude=t))
        mc.revive_shard(t)
        assert mc.mesh_state() == (1 if t == 0 else 0)
        if t == 0:
            # middle rung: degraded(S) serving around shard 1
            rows, sp, _ = mesh_rows(mc, topics)
            assert not sp
            fill_parity(inc, mc, topics, rows, sp)
    rows, sp, _ = mesh_rows(mc, topics)
    assert not sp
    for t_, r in zip(topics, rows):
        assert sorted(r) == sorted(inc.match_host(t_)), t_
    assert mc.rebuilds == 2


def test_rebuild_shard_delta_tail_replay_and_readmit_zero_stale():
    """Online rebuild converges on the LIVE filter state: a filter
    added while its owner shard was dead is replayed from the service
    pairs into the fresh subtable, the canary proves bit parity, and
    after re-admission the delta filter serves on-device (zero-stale
    re-admission)."""
    inc, mc, pairs = build_pair(degraded=True)
    f = "delta/x/+"
    t = shard_of_filter(f, mc.tp)
    mc.kill_shard(t)
    inc.add(f)
    pairs.append((f, inc.aid_of(f)))      # the delta lands while dead
    assert mc.rebuild_shard(t, pairs) >= 0.0
    ctop = mc.canary_topics(t)
    assert any(c.startswith("delta/") for c in ctop)
    crows, csp = mc.canary_rows(ctop, 64, t)
    csps = set(csp)
    for i, topic in enumerate(ctop):
        if i in csps:
            continue
        assert sorted(crows[i]) == sorted(inc.match_host(topic)), topic
    mc.revive_shard(t)
    assert mc.mesh_state() == 0
    rows, sp, _ = mesh_rows(mc, ["delta/x/y"])
    assert not sp
    assert inc.aid_of(f) in rows[0]
    assert sorted(rows[0]) == sorted(inc.match_host("delta/x/y"))


def test_shard_kill_races_apply_pending_restack():
    """Satellite chaos: a shard dies WHILE ``apply_pending`` is
    mid-restack (inside the maintenance lock).  The swap completes on
    the full grid, degraded serving picks the death up afterwards with
    the fill contract intact, and the online rebuild re-admits it with
    parity — maintenance and the health ladder never tear the table."""
    inc, mc, pairs = build_pair(degraded=True)
    victim = 1
    real = mc._restack

    def racy():
        mc.kill_shard(victim)     # death lands mid-maintenance
        real()

    mc._restack = racy
    try:
        for f in ("race/a/+", "race/b/#"):
            inc.add(f)
            pairs.append((f, inc.aid_of(f)))
        mc.rebuild(pairs)          # the full-restack (swap) path
        assert mc.apply_pending()
    finally:
        mc._restack = real
    assert mc.dead_shards == [victim] and mc.degraded_serving
    topics = topics_for(16) + ["race/a/x", "b/c"]
    rows, sp, _ = mesh_rows(mc, topics)
    assert not sp
    fill_parity(inc, mc, topics, rows, sp)
    assert mc.rebuild_shard(victim, pairs) >= 0.0
    mc.revive_shard(victim)
    rows2, sp2, _ = mesh_rows(mc, topics)
    assert not sp2
    for t, r in zip(topics, rows2):
        assert sorted(r) == sorted(inc.match_host(t)), t


def test_node_shard_kill_races_compaction_swap_then_readmits():
    """Satellite chaos at node level: kill a shard in the compaction
    swap window (the service just bumped ``_table_gen``; the mesh
    repartition hasn't landed).  The swap completes, the health ladder
    raises the degraded alarm, and the supervised rebuild re-admits
    the shard through the canary — serving never stops."""

    async def main():
        import tempfile

        seg = tempfile.mkdtemp()
        node = make_node(**{
            "match.segments.enable": True,
            "match.segments.dir": seg,
            "match.segments.compact_interval": 0.2,
            "match.segments.compact_min_mutations": 1,
            "match.multichip.degraded.enable": True,
            "supervisor.backoff_base": 0.005,
            "supervisor.backoff_max": 0.05,
        })
        await node.start()
        ms = node.match_service
        assert ms is not None and ms.mc is not None and ms.mc.degraded
        try:
            b = node.broker
            if "c1" not in b.sessions:
                b.open_session("c1")
            for i in range(8):
                b.subscribe("c1", f"swap/{i}/+")
            assert await settle(lambda: ms.ready and ms.mc.ready)
            gen0 = ms.mc.gen
            assert await settle(lambda: ms._table_gen >= 1, timeout=30)
            ms.mc.kill_shard(1)            # mid-swap-window death
            assert await settle(
                lambda: ms.mc.ready and ms.mc.gen > gen0, timeout=30)
            # the supervised rebuild re-admits it (canary-gated)
            assert await settle(lambda: not ms.mc.dead_shards,
                                timeout=60)
            assert ms.mc.rebuilds >= 1
            assert await settle(
                lambda: not node.observed.alarms.is_active(
                    "mesh_degraded"), timeout=30)
            await ms.prefetch("swap/3/x")
            assert ms.hint_routes("swap/3/x") is not None
            assert node.observed.metrics.get("tpu.mesh.state") == 0
        finally:
            await node.stop()

    run(main())


def test_node_canary_failure_blocks_readmit_until_parity():
    """A failing bit-parity canary keeps the rebuilt shard OUT:
    ``tpu.mesh.readmit_canary_fails`` counts the refusals, the
    degraded alarm stays up, and the moment parity is restored the
    shard re-admits and the alarm clears."""

    async def main():
        node = make_node(**{
            "match.multichip.degraded.enable": True,
            "supervisor.backoff_base": 0.005,
            "supervisor.backoff_max": 0.05,
        })
        await node.start()
        ms = node.match_service
        assert ms is not None and ms.mc is not None
        try:
            b = node.broker
            if "c1" not in b.sessions:
                b.open_session("c1")
            for i in range(6):
                b.subscribe("c1", f"cn/{i}/+")
            assert await settle(lambda: ms.ready and ms.mc.ready)

            async def failing(t):
                return False

            ms._mesh_canary = failing     # parity probe refuses
            ms.mc.kill_shard(0)
            await ms.prefetch("cn/0/x")   # serve pass trips the watch
            m = node.observed.metrics
            assert await settle(
                lambda: m.get("tpu.mesh.readmit_canary_fails") >= 2,
                timeout=30)
            assert ms.mc.dead_shards == [0]      # stays OUT
            assert node.observed.alarms.is_active("mesh_degraded")
            info = ms.mesh_info()
            assert info["alarmed"] and info["rebuilding"]
            del ms._mesh_canary           # parity restored
            assert await settle(lambda: not ms.mc.dead_shards,
                                timeout=60)
            assert await settle(
                lambda: not node.observed.alarms.is_active(
                    "mesh_degraded"), timeout=30)
            assert ms.mc.readmit_canary_fails >= 2
            assert ms.mc.rebuilds >= 1
        finally:
            await node.stop()

    run(main())

# ---------------------------------------------------------------------------
# load-adaptive plane (ISSUE 20): capacity auto-resize + popularity
# placement
# ---------------------------------------------------------------------------

def _colliding_roots(tp, n, prefix="h"):
    """First ``n`` synthetic roots that crc32-hash to ONE shard — the
    skew every popularity test needs."""
    out, i = [], 0
    while len(out) < n:
        r = f"{prefix}{i}"
        if shard_of_filter(r, tp) == shard_of_filter(f"{prefix}0", tp):
            out.append(r)
        i += 1
    return out


def test_greedy_balance_pure_strict_improvement():
    """The pure core: every move is strictly improving (hottest root
    whose load fits inside the hi-lo gap), the worst shard's load
    drops, budget 0 is a no-op, and a balanced input stays put."""
    from emqx_tpu.parallel.prefix_ep import greedy_balance

    loads = {"h0": 100.0, "h1": 90.0, "h2": 80.0, "h3": 70.0,
             "c0": 1.0}
    owners = {"h0": 0, "h1": 0, "h2": 0, "h3": 0, "c0": 1}

    def worst(o):
        per = [0.0] * 4
        for w, t in o.items():
            per[t] += loads[w]
        return max(per)

    new, moved = greedy_balance(loads, owners, 4, 64)
    assert moved >= 3
    assert worst(new) < worst(owners)
    assert worst(new) <= 100.0          # no shard above the hottest root
    assert set(new) == set(owners)      # no root invented or dropped
    assert all(0 <= t < 4 for t in new.values())
    # budget 0: identity
    same, n0 = greedy_balance(loads, owners, 4, 0)
    assert n0 == 0 and same == owners
    # already balanced: strict improvement finds nothing to move
    flat = {f"r{i}": 10.0 for i in range(4)}
    fown = {f"r{i}": i for i in range(4)}
    kept, nk = greedy_balance(flat, fown, 4, 64)
    assert nk == 0 and kept == fown


def test_autotune_flag_off_byte_identical():
    """Flag off (the default ctor): no load is noted, no resize ever
    triggers, the placement map stays empty, ``shard_of`` is the pure
    crc32 hash, and rows are bit-identical to an autotune-on matcher
    that never crossed a threshold."""
    inc, mc_off, pairs = build_ep_pair(ep_slack=4.0)
    mc_on = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                             ep_autotune=True)
    mc_on.rebuild(pairs)
    assert mc_on.apply_pending()
    topics = topics_for(48)
    rows_off, sp_off, _ = mesh_rows(mc_off, topics)
    rows_on, sp_on, _ = mesh_rows(mc_on, topics)
    assert sp_off == sp_on
    assert [sorted(r) for r in rows_off] == [sorted(r) for r in rows_on]
    assert not mc_off.ep_autotune
    assert mc_off._cap_class == 0 and mc_off._placement == {}
    assert mc_off.ep_resizes == 0 and not mc_off._root_load.any()
    assert mc_off.plan_rebalance() == 0     # flag off: a no-op
    assert mc_off._placement_next is None
    for f in FILTERS:
        assert mc_off.shard_of(f) == shard_of_filter(f, mc_off.tp)
    assert mc_off.ep_capacity(64) == mc_on.ep_capacity(64)
    # autotune on but idle: still byte-identical state
    assert mc_on._cap_class == 0 and mc_on._placement == {}


def test_overflow_ewma_grow_rearms_warn_latch_rows_complete(caplog):
    """EWMA-triggered grow: a hot root overflowing every source slice
    crosses the grow threshold, the grid grows on a background thread
    while EVERY row of every batch stays complete (fail-open, zero
    failover strikes), the grow zeroes the EWMA and re-arms the
    warn-once latch, and the SECOND regression at the grown class
    warns again (satellite: the latch must reset on grow)."""
    import logging
    import time

    # grow_threshold ABOVE the warn threshold so each grow happens
    # after the warn fired: warn/grow at class 0, re-warn/grow at 1
    inc, mc, _pairs = build_ep_pair(
        ep_slack=0.5, ep_autotune=True, ep_grow_threshold=0.6)
    assert mc.ep_autotune and mc._cap_class == 0
    topics = [f"x/{i}/z" for i in range(56)] + ["x/y/z"] * 8
    cap0 = mc.ep_capacity(64)
    with caplog.at_level(logging.WARNING,
                         logger="emqx_tpu.parallel.multichip_serve"):
        deadline = time.monotonic() + 120.0
        complete = True
        while mc.ep_resizes < 2 and time.monotonic() < deadline:
            rows, sp, _ = mc.readback(
                mc.dispatch(mc.encode(topics, batch=64)), len(topics))
            spset = set(sp)
            complete = complete and all(
                (sorted(inc.match_host(t)) if k in spset
                 else sorted(rows[k])) == sorted(inc.match_host(t))
                for k, t in enumerate(topics))
        while mc._resize_busy and time.monotonic() < deadline:
            time.sleep(0.01)
    assert mc.ep_resizes >= 2, "EWMA never triggered the grow"
    assert mc._cap_class >= 2
    assert complete, "rows dropped during the compile window"
    assert mc.failovers == 0            # zero breaker strikes
    assert mc.ep_capacity(64) > cap0
    warns = [r for r in caplog.records if "overflow EWMA" in r.message]
    assert len(warns) >= 2, "grow must re-arm the warn-once latch"
    # the flip reset the measurement state for the new grid
    grows = [r for r in caplog.records if "grew to capacity" in r.message]
    assert len(grows) >= 2
    # post-grow serve on the wider grid still bit-complete
    rows, sp, _ = mc.readback(
        mc.dispatch(mc.encode(topics, batch=64)), len(topics))
    spset = set(sp)
    for k, t in enumerate(topics):
        if k not in spset:
            assert sorted(rows[k]) == sorted(inc.match_host(t)), t
    # the last readback may have kicked one more grow: drain it so the
    # compile thread can't leak CPU into the rest of the suite
    assert mc.drain_resize(120.0)


def test_kernel_cache_grow_compiles_ahead_no_dispatch_parks():
    """With a kernel cache attached the resize worker compiles the
    grown grid THROUGH the cache before flipping: a post-flip
    dispatch with ``block_compile=False`` hits — never a CompileMiss,
    so no serve dispatch ever parks behind XLA."""
    import time

    from emqx_tpu.ops.kernel_cache import MatchKernelCache

    kc = MatchKernelCache()
    inc, mc, _pairs = build_ep_pair(
        ep_slack=0.5, ep_autotune=True, ep_grow_threshold=0.05,
        kernel_cache=kc)
    topics = [f"x/{i}/z" for i in range(64)]
    enc = mc.encode(topics, batch=64)
    mc.readback(mc.dispatch(enc, block_compile=True), len(topics))
    deadline = time.monotonic() + 120.0
    while mc.ep_resizes < 1 and time.monotonic() < deadline:
        mc.readback(mc.dispatch(enc, block_compile=True), len(topics))
    while mc._resize_busy and time.monotonic() < deadline:
        time.sleep(0.01)
    assert mc.ep_resizes >= 1 and mc._cap_class >= 1
    # the serving contract: the grown-grid step is already cached
    rows, sp, _ = mc.readback(
        mc.dispatch(enc, block_compile=False), len(topics))
    spset = set(sp)
    for k, t in enumerate(topics):
        if k not in spset:
            assert sorted(rows[k]) == sorted(inc.match_host(t)), t
    assert mc.drain_resize(120.0)


def test_plan_rebalance_stages_and_rebuild_applies_with_parity():
    """The popularity pass STAGES; only the next rebuild applies: the
    override map is invisible to serving until the repartition swap,
    then the moved hot roots spread across shards, ``_word_owner``
    routes to the new owners, and rows stay bit-parity with the host
    oracle and the replicated backend."""
    mc = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                          ep_autotune=True, balance_budget=64)
    hot = _colliding_roots(mc.tp, 4)
    home = shard_of_filter(f"{hot[0]}/a/+", mc.tp)
    inc = IncrementalNfa(depth=8)
    pairs = []
    for r in hot:
        for f in (f"{r}/a/+", f"{r}/b/#"):
            inc.add(f)
            pairs.append((f, inc.aid_of(f)))
    mc.rebuild(pairs)
    assert mc.apply_pending()
    assert all(mc.shard_of(f"{r}/a/x") == home for r in hot)
    topics = [f"{hot[k % 4]}/a/x" for k in range(64)]
    for _ in range(3):                      # accumulate the load slab
        mesh_rows(mc, topics)
    assert mc._root_load.any()
    moved = mc.plan_rebalance()
    assert moved >= 1
    # staged, not applied: serving still routes to the crc32 home
    assert mc._placement == {} and mc._placement_next
    assert all(mc.shard_of(f"{r}/a/x") == home for r in hot)
    rows0, sp0, _ = mesh_rows(mc, topics)
    assert not sp0
    # the next rebuild (the compaction-swap cadence) applies the map
    mc.rebuild(pairs)
    assert mc.apply_pending()
    assert mc._placement and mc._placement_next is None
    owners = {mc.shard_of(f"{r}/a/x") for r in hot}
    assert len(owners) > 1, "hot roots must spread after the remap"
    for r in hot:                            # device routing agrees
        wid = mc.vocab[r]
        assert int(mc._word_owner[wid]) == mc.shard_of(f"{r}/a/x")
    mc_rep = MultichipMatcher(depth=8)
    mc_rep.rebuild(pairs)
    assert mc_rep.apply_pending()
    rows_e, sp_e, _ = mesh_rows(mc, topics)
    rows_r, sp_r, _ = mesh_rows(mc_rep, topics)
    assert not sp_e and not sp_r
    for t, re_, rr, r0 in zip(topics, rows_e, rows_r, rows0):
        want = sorted(inc.match_host(t))
        assert sorted(re_) == sorted(rr) == sorted(r0) == want, t
    assert mc.ep_rebalances == 1 and mc.moved_roots == moved


def test_placement_segments_roundtrip_v3_and_skew_rejection(tmp_path):
    """The override map rides the v3 segment set: a cold start
    restores placement bit-identical BEFORE the restack (the restored
    partition and its shard_of agree); a placement tampered after the
    save fails the per-segment placement_crc guard even with a
    recomputed manifest checksum (torn-save mixed generations); a v2
    manifest is rejected outright."""
    mc = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                          ep_autotune=True, balance_budget=64)
    hot = _colliding_roots(mc.tp, 4)
    inc = IncrementalNfa(depth=8)
    pairs = []
    for r in hot:
        for f in (f"{r}/a/+", f"{r}/b/#"):
            inc.add(f)
            pairs.append((f, inc.aid_of(f)))
    mc.rebuild(pairs)
    assert mc.apply_pending()
    topics = [f"{hot[k % 4]}/a/x" for k in range(64)]
    for _ in range(3):
        mesh_rows(mc, topics)
    assert mc.plan_rebalance() >= 1
    mc.rebuild(pairs)
    assert mc.apply_pending()
    assert mc._placement
    d = str(tmp_path)
    mc.save_segments(d, epoch=inc.epoch)
    want, _, _ = mesh_rows(mc, topics)

    mc2 = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                           ep_autotune=True)
    assert mc2.load_segments(d, expect_epoch=inc.epoch)
    assert mc2._placement == mc._placement
    assert mc2.apply_pending()
    assert all(mc2.shard_of(f"{r}/a/x") == mc.shard_of(f"{r}/a/x")
               for r in hot)
    got, sp, _ = mesh_rows(mc2, topics)
    assert not sp
    assert [sorted(r) for r in got] == [sorted(r) for r in want]

    # tamper the persisted owners + recompute the manifest checksum:
    # the per-segment placement_crc (cut under the ORIGINAL map) must
    # reject the mixed generation
    mpath = os.path.join(d, "multichip", "aid_maps.npz")
    maps = dict(np.load(mpath))
    assert len(maps["ps"]), "round trip must persist real overrides"
    ps = np.asarray(maps["ps"], np.int32)
    ps[0] = (ps[0] + 1) % mc.tp
    maps["ps"] = ps
    np.savez(mpath, **maps)
    manp = os.path.join(d, "multichip", "manifest.json")
    with open(manp) as f:
        meta = json.load(f)
    core = {k: meta[k] for k in
            ("version", "epoch", "tp", "depth", "native")}
    meta["checksum"] = MultichipMatcher._manifest_checksum(core, maps)
    with open(manp, "w") as f:
        json.dump(meta, f, sort_keys=True)
    mc3 = MultichipMatcher(depth=8, ep=True, ep_autotune=True)
    assert not mc3.load_segments(d, expect_epoch=inc.epoch)

    # a v2 manifest (pre-placement format) is rejected by version
    meta["version"] = 2
    with open(manp, "w") as f:
        json.dump(meta, f, sort_keys=True)
    mc4 = MultichipMatcher(depth=8, ep=True, ep_autotune=True)
    assert not mc4.load_segments(d, expect_epoch=inc.epoch)


def test_rebalance_defers_while_degraded_then_readmit_post_remap():
    """Rebalance racing the degraded mesh: while ANY shard is dead the
    balance pass stages NOTHING (roots never remap onto a dead owner);
    after re-admission the pass stages and applies, and a shard killed
    POST-remap rebuilds + canaries against the remapped placement (the
    canary judges the placement the rebuild was built against)."""
    mc = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                          ep_autotune=True, balance_budget=64,
                          degraded=True)
    hot = _colliding_roots(mc.tp, 4)
    home = shard_of_filter(f"{hot[0]}/a/+", mc.tp)
    inc = IncrementalNfa(depth=8)
    pairs = []
    for r in hot:
        for f in (f"{r}/a/+", f"{r}/b/#"):
            inc.add(f)
            pairs.append((f, inc.aid_of(f)))
    mc.rebuild(pairs)
    assert mc.apply_pending()
    topics = [f"{hot[k % 4]}/a/x" for k in range(64)]
    for _ in range(3):
        mesh_rows(mc, topics)
    # dead shard: the pass defers outright
    mc.kill_shard(home)
    assert mc.plan_rebalance() == 0
    assert mc._placement_next is None and mc.ep_rebalances == 0
    rows_d, sp_d, _ = mesh_rows(mc, topics)   # scoped failover serves
    spset = set(sp_d)
    assert spset, "hot rows owned by the dead shard must divert"
    for k, t in enumerate(topics):
        if k not in spset:
            assert sorted(rows_d[k]) == sorted(inc.match_host(t)), t
    # readmit, then the pass stages and the rebuild applies
    assert mc.rebuild_shard(home, pairs) >= 0.0
    mc.revive_shard(home)
    assert mc.plan_rebalance() >= 1
    mc.rebuild(pairs)
    assert mc.apply_pending()
    moved = [r for r in hot
             if mc.shard_of(f"{r}/a/x") != home]
    assert moved, "the remap must have moved a hot root off home"
    # post-remap kill of a MOVED root's new owner: the online rebuild
    # partitions by the live (overridden) placement and the canary
    # proves parity against exactly that placement
    t2 = mc.shard_of(f"{moved[0]}/a/x")
    mc.kill_shard(t2)
    assert mc.plan_rebalance() == 0           # still defers while dead
    assert mc.rebuild_shard(t2, pairs) >= 0.0
    ctop = mc.canary_topics(t2)
    assert any(c.startswith(f"{moved[0]}/") for c in ctop)
    crows, csp = mc.canary_rows(ctop, 64, t2)
    csps = set(csp)
    for i, topic in enumerate(ctop):
        if i not in csps:
            assert sorted(crows[i]) == sorted(inc.match_host(topic)), \
                topic
    mc.revive_shard(t2)
    rows_p, sp_p, _ = mesh_rows(mc, topics)
    assert not sp_p
    for t, r in zip(topics, rows_p):
        assert sorted(r) == sorted(inc.match_host(t)), t


def test_ep_rebalance_fault_injection_noop():
    """An injected ``ep.rebalance`` fault raises BEFORE anything is
    staged (kill mid-rebalance = no-op): placement unchanged, nothing
    pending, and the next un-faulted pass stages normally."""
    mc = MultichipMatcher(depth=8, ep=True, ep_slack=4.0,
                          ep_autotune=True, balance_budget=64)
    hot = _colliding_roots(mc.tp, 4)
    inc = IncrementalNfa(depth=8)
    pairs = []
    for r in hot:
        inc.add(f"{r}/a/+")
        pairs.append((f"{r}/a/+", inc.aid_of(f"{r}/a/+")))
    mc.rebuild(pairs)
    assert mc.apply_pending()
    topics = [f"{hot[k % 4]}/a/x" for k in range(64)]
    for _ in range(3):
        mesh_rows(mc, topics)
    faultinject.install(FaultInjector([
        {"point": "ep.rebalance", "action": "raise", "times": 1},
    ]))
    try:
        with pytest.raises(faultinject.InjectedFault):
            mc.plan_rebalance()
        assert mc._placement == {} and mc._placement_next is None
        assert mc.ep_rebalances == 0
        rows, sp, _ = mesh_rows(mc, topics)   # delivery holds
        assert not sp
        for t, r in zip(topics, rows):
            assert sorted(r) == sorted(inc.match_host(t)), t
        assert mc.plan_rebalance() >= 1       # un-faulted: stages
        assert mc._placement_next
    finally:
        faultinject.uninstall()
