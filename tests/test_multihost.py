"""Multi-host (DCN) runtime: hybrid mesh construction + collective
routing on the virtual 8-device CPU mesh, with host count simulated —
the laptop-to-fleet passthrough contract of parallel/multihost.py."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from emqx_tpu.parallel import (
    MultihostRuntime, dcn_env, hybrid_mesh_from,
)
from jax.sharding import NamedSharding, PartitionSpec as P


def test_single_process_passthrough():
    rt = MultihostRuntime.from_env()
    assert rt.num_processes == 1 and not rt.initialized
    assert rt.is_coordinator()
    mesh = rt.hybrid_mesh({"tp": 2}, dcn_axis="dp")
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == len(jax.devices()) // 2


def test_env_contract():
    os.environ["EMQX_TPU_NUM_PROCESSES"] = "1"
    try:
        env = dcn_env()
        assert env["num_processes"] == "1"
        rt = MultihostRuntime.from_env()
        assert not rt.initialized      # 1 process -> passthrough
    finally:
        del os.environ["EMQX_TPU_NUM_PROCESSES"]


def test_hybrid_mesh_groups_hosts_on_outer_axis():
    """Simulate 2 hosts x 4 devices: inner axes must only span devices
    of one simulated host (ICI); the outer axis crosses hosts (DCN)."""
    devs = jax.devices()
    mesh = hybrid_mesh_from({"tp": 2}, dcn_axis="dp", devices=devs,
                            num_hosts=2)
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}
    arr = mesh.devices
    # each dp row holds devices from ONE simulated host (ids 0-3 | 4-7)
    for row in range(4):
        host_ids = {d.id // 4 for d in arr[row]}
        assert len(host_ids) == 1, arr


def test_hybrid_mesh_collectives_route_correctly():
    """psum over the inner axis + all_gather over the outer axis give
    the same numbers as a flat computation."""
    from jax.experimental.shard_map import shard_map

    mesh = hybrid_mesh_from({"tp": 4}, dcn_axis="dp", num_hosts=2)
    assert dict(mesh.shape) == {"dp": 2, "tp": 4}
    x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)

    def f(block):
        # block: (4, 1) per device — reduce over tp, keep dp shards
        return jax.lax.psum(block, "tp")

    g = shard_map(f, mesh=mesh, in_specs=P("dp", "tp"),
                  out_specs=P("dp", "tp"))
    got = np.asarray(g(x))
    # psum over tp sums the 4 column shards within each dp row group
    assert np.allclose(got, np.broadcast_to(
        np.asarray(x).sum(axis=1, keepdims=True), (8, 4)))


def test_hybrid_mesh_rejects_bad_factorizations():
    with pytest.raises(ValueError):
        hybrid_mesh_from({"tp": 3}, num_hosts=2)     # 4 % 3 != 0
    with pytest.raises(ValueError):
        hybrid_mesh_from({"dp": 2}, dcn_axis="dp", num_hosts=2)
    with pytest.raises(ValueError):
        hybrid_mesh_from({"tp": 2}, num_hosts=3)     # 8 % 3 != 0


def test_leftover_devices_fold_into_dcn_axis():
    # 2 hosts x 4 devices, ici uses only 2 -> outer = hosts x leftover
    mesh = hybrid_mesh_from({"tp": 2}, dcn_axis="dp", num_hosts=2)
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}
    assert mesh.devices.size == 8


def test_two_process_jax_distributed_collectives():
    """VERDICT r4 item 4: REAL two-process ``jax.distributed`` — spawn 2
    OS processes, bootstrap the coordination service on localhost, build
    the hybrid ICI x DCN mesh, and run psum / global-sum / ppermute
    collectives ACROSS processes.  All numeric assertions run inside the
    workers (tests/_multihost_worker.py); this parent checks the
    bootstrap + both OK markers."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    env = dict(os.environ)
    # the worker pins its own JAX env; scrub the parent's 8-device flag
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("aren't implemented on the CPU backend" in o for o in outs):
        pytest.skip("jax CPU backend lacks multiprocess collectives")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"MULTIHOST_OK rank={rank}" in out, out
