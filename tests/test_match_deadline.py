"""Deadline-aware serve plane (ISSUE 7): latency budgets, partial
dispatch, adaptive per-lane batch caps, per-dispatch timeout with
CPU-trie fallback, circuit breaker with supervised recovery probe, and
the staged olp brownout ladder.

The flag-off path (match.deadline.enable = false, the default) is the
pre-deadline fixed-window loop and is covered by the pre-existing
tests/test_match_service.py suite — which this PR keeps passing
unchanged.
"""

import asyncio
import time

import pytest

from emqx_tpu import faultinject
from emqx_tpu import topic as T
from emqx_tpu.broker.olp import Olp
from emqx_tpu.config import Config
from emqx_tpu.faultinject import FaultInjector
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def settle(pred, timeout=60.0, interval=0.01):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return True
        await asyncio.sleep(interval)
    return pred()


def make_node(**extra):
    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    cfg.put("tpu.enable", True)  # env layer disables it for other tests
    cfg.put("tpu.mirror_refresh_interval", 0.01)
    cfg.put("tpu.bypass_rate", 0.0)  # pin the device path on
    cfg.put("match.deadline.enable", True)
    cfg.put("match.deadline_ms", 60.0)
    cfg.put("match.breaker.threshold", 3)
    cfg.put("match.breaker.probe_interval", 0.05)
    cfg.put("supervisor.backoff_base", 0.005)
    cfg.put("supervisor.backoff_max", 0.05)
    for k, v in extra.items():
        cfg.put(k, v)
    return BrokerNode(cfg)


def sub(b, cid, flt):
    if cid not in b.sessions:
        b.open_session(cid)
    b.subscribe(cid, flt)


def ms_synced(node):
    ms = node.match_service
    return (
        ms is not None and ms.ready
        and ms._seen_epoch == node.broker.router.epoch
        and ms.dev.epoch == ms.inc.epoch
    )


# ---------------------------------------------------------------------------
# olp brownout ladder (pure unit, injected clock)
# ---------------------------------------------------------------------------

def test_olp_brownout_ladder_escalates_and_recovers():
    o = Olp(max_queue_depth=10, cooloff=1.0)
    assert o.brownout_level(now=0.0) == 0
    o.report(queue_depth=100, now=0.0)
    assert o.brownout_level(now=0.0) == 1          # entry: stage 1
    o.report(queue_depth=100, now=0.9)
    assert o.brownout_level(now=1.1) == 2          # sustained: stage 2
    o.report(queue_depth=100, now=1.9)
    assert o.brownout_level(now=2.1) == 3          # stage 3 (capped)
    o.report(queue_depth=100, now=2.9)
    assert o.brownout_level(now=3.5) == 3          # still within cooloff
    o.report(queue_depth=0, now=4.5)               # cool report past cooloff
    assert o.brownout_level(now=4.5) == 0          # straight back to 0


def test_olp_brownout_new_episode_resets_escalation():
    o = Olp(max_queue_depth=10, cooloff=1.0)
    o.report(queue_depth=100, now=0.0)
    o.report(queue_depth=100, now=0.9)
    assert o.brownout_level(now=1.0) == 2
    # silent gap > cooloff: overload cleared on its own; the next hot
    # report starts a NEW episode at stage 1, not stage 3
    o.report(queue_depth=100, now=5.0)
    assert o.brownout_level(now=5.0) == 1


# ---------------------------------------------------------------------------
# deadline loop: parity + partial dispatch + adaptive caps
# ---------------------------------------------------------------------------

def test_deadline_loop_serves_with_parity():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            assert ms is not None and ms.deadline
            for i in range(4):
                sub(b, f"s{i}", f"room/+/k{i}")
            assert await settle(lambda: ms_synced(node))
            topics = [f"room/{i}/k{i % 4}" for i in range(24)]
            await asyncio.gather(*[ms.prefetch(t) for t in topics])
            for t in topics:
                hint = ms.hint_routes(t)
                assert hint is not None, t
                want = b.router.match_routes(t)
                assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
            m = node.observed.metrics
            assert m.get("tpu.match.batches") >= 1
            assert ms.info()["breaker"] == "closed"
        finally:
            await node.stop()

    run(main())


def test_partial_dispatch_on_budget_expiry():
    """With the adaptive bound far above the queued count, the loop must
    flush a PARTIAL batch once the oldest waiter's budget (minus the
    dispatch estimate) runs out — within the budget, not at batch-full."""

    async def main():
        node = make_node(**{"match.deadline_ms": 80.0})
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/warm")   # pay the first-dispatch costs
            # pin the arrival-rate estimate high so bound == max_batch,
            # far above the 3 waiters below — only the budget can flush
            ms._rate_ewma = 1e9
            t0 = time.perf_counter()
            await asyncio.gather(*[ms.prefetch(f"a/p{i}") for i in range(3)])
            el = time.perf_counter() - t0
            # resolved by the deadline (plus dispatch + margin), far
            # below the prefetch timeout the old loop would burn
            assert el < 0.4, el
            for i in range(3):
                assert ms.hint_routes(f"a/p{i}") is not None
            assert node.observed.metrics.get(
                "broker.match.deadline_dispatch") >= 1
        finally:
            await node.stop()

    run(main())


def test_adaptive_bound_and_lane_caps():
    async def main():
        node = make_node()
        await node.start()
        try:
            ms = node.match_service
            # bound follows the EWMA arrival rate under the budget
            ms._est_dispatch_s = 0.01
            ms._rate_ewma = 1000.0   # 1k/s, 60 ms budget - 10 ms est
            b1 = ms._deadline_bound()
            assert 1 <= b1 <= ms.max_batch
            assert b1 == int(1000.0 * (ms.deadline_s - 0.01))
            ms._rate_ewma = 1e9
            assert ms._deadline_bound() == ms.max_batch

            # brownout stage 1/2 shrinks the cap (half, quarter)
            class FakeOlp:
                lvl = 0

                def brownout_level(self, now=None):
                    return self.lvl

            ms.olp = FakeOlp()
            ms.olp.lvl = 1
            assert ms._deadline_bound() == ms.max_batch >> 1
            ms.olp.lvl = 2
            assert ms._deadline_bound() == ms.max_batch >> 2
            ms.olp = None

            # per-lane caps: a deep-topic flood cannot starve the short
            # lane; skipped waiters stay queued in order
            ms._short_frac = 0.5
            short_cap, long_cap = ms._lane_caps(8)
            assert 1 <= short_cap <= 8 and 1 <= long_cap <= 8
            loop = asyncio.get_running_loop()
            mk = lambda t: (t, loop.create_future(), loop.time() + 1.0)
            deep = [mk(f"a/b/c/d/e/f{i}") for i in range(10)]
            shallow = [mk(f"s{i}") for i in range(4)]
            ms._pending = deep + shallow
            batch = ms._pop_batch(8)
            lanes = [t.count("/") < ms.short_depth for t, _f, _d in batch]
            assert any(lanes), "short lane starved by the deep flood"
            # order preserved within what stayed queued
            left = [t for t, _f, _d in ms._pending]
            assert left == sorted(left, key=lambda t: (
                [p[0] for p in deep + shallow].index(t)))
            for p in ms._pending:   # clean up the fabricated waiters
                p[1].cancel()
            ms._pending = []
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# robustness: dispatch timeout, breaker, brownout shed, loop death
# ---------------------------------------------------------------------------

def test_dispatch_hang_times_out_to_cpu_fallback():
    """A hung device dispatch must cost ONE dispatch timeout — the batch
    is answered from the CPU tables (hints minted, host parity), never
    the full prefetch timeout per waiter."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/warm")
            faultinject.install(FaultInjector([
                {"point": "match.dispatch", "action": "hang", "times": 1},
            ]))
            t0 = time.perf_counter()
            await ms.prefetch("a/hung")
            el = time.perf_counter() - t0
            assert el < ms.prefetch_timeout_s * 0.9, el
            hint = ms.hint_routes("a/hung")
            assert hint is not None, "CPU fallback minted no hint"
            want = b.router.match_routes("a/hung")
            assert sorted(map(tuple, hint)) == sorted(map(tuple, want))
            m = node.observed.metrics
            assert m.get("broker.match.cpu_fallback") >= 1
            assert ms._breaker_failures >= 1   # counted toward the breaker
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


def test_breaker_trips_probes_and_recovers():
    """threshold consecutive dispatch failures → breaker OPEN: CPU-serve
    mode, match_degraded alarm, breaker_state metric; the supervised
    probe child closes it (and clears the alarm) once the device answers
    again — here, once the injected faults exhaust."""

    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            m = node.observed.metrics
            alarms = node.observed.alarms
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/warm")
            inj = faultinject.install(FaultInjector([
                {"point": "match.dispatch", "action": "raise", "times": 3},
            ]))
            for i in range(3):
                await ms.prefetch(f"a/f{i}")
                # failed dispatches still answer from CPU immediately
                assert ms.hint_routes(f"a/f{i}") is not None
            assert ms._breaker_open
            assert alarms.is_active("match_degraded")
            assert m.get("broker.match.breaker_state") >= 1
            # the probe registered as a supervised child
            assert node.supervisor.lookup("match.probe") is not None
            # while open: prefetch short-circuits (no waiter, no budget)
            t0 = time.perf_counter()
            await ms.prefetch("a/open")
            assert time.perf_counter() - t0 < 0.05
            assert m.get("broker.match.cpu_fallback") >= 4
            # faults exhausted → the next probe closes the breaker
            assert await settle(lambda: not ms._breaker_open, timeout=15)
            assert not alarms.is_active("match_degraded")
            assert m.get("broker.match.breaker_state") == 0
            assert inj.fired.get("match.dispatch") == 3
            # device serves again
            await ms.prefetch("a/back")
            assert ms.hint_routes("a/back") is not None
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


def test_brownout_sheds_qos0_then_everything():
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            m = node.observed.metrics
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))

            class FakeOlp:
                lvl = 0

                def brownout_level(self, now=None):
                    return self.lvl

            ms.olp = FakeOlp()
            # stage 2: QoS0 prefetches shed to CPU, QoS1+ still device
            ms.olp.lvl = 2
            before = m.get("broker.match.cpu_fallback")
            await ms.prefetch("a/q0", qos=0)
            assert m.get("broker.match.cpu_fallback") == before + 1
            assert ms.hint_routes("a/q0") is None   # host trie serves it
            await ms.prefetch("a/q1", qos=1)
            assert ms.hint_routes("a/q1") is not None  # device served
            assert m.get("broker.match.brownout_level") == 2
            # stage 3: full CPU serve regardless of QoS
            ms.olp.lvl = 3
            t0 = time.perf_counter()
            await ms.prefetch("a/q2", qos=2)
            assert time.perf_counter() - t0 < 0.05
            assert ms.hint_routes("a/q2") is None
            # recovery: back to device serving
            ms.olp.lvl = 0
            await ms.prefetch("a/rec", qos=0)
            assert ms.hint_routes("a/rec") is not None
        finally:
            await node.stop()

    run(main())


def _kill_failover_body(deadline: bool):
    async def main():
        extra = {} if deadline else {"match.deadline.enable": False}
        node = make_node(**extra)
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            assert ms.deadline is deadline
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/warm")
            child = node.supervisor.lookup("match.batch")
            assert child is not None
            # park a waiter, then kill the serve loop under it
            task = asyncio.ensure_future(ms.prefetch("a/kill"))
            await asyncio.sleep(0)          # waiter enqueued
            t0 = time.perf_counter()
            assert child.kill()
            await task
            el = time.perf_counter() - t0
            # the bugfix: resolved on loop DEATH, not after the full
            # prefetch_timeout_s (0.5 s) stall the old code burned
            assert el < 0.2, el
            assert node.observed.metrics.get(
                "broker.match.cpu_fallback") >= 1
            # restart re-arms: the next prefetch is served by the device
            assert await settle(lambda: child.alive(), timeout=10)
            assert await settle(lambda: ms_synced(node))
            await ms.prefetch("a/again")
            assert ms.hint_routes("a/again") is not None
            assert node.observed.metrics.get(
                "broker.supervisor.restarts") >= 1
        finally:
            await node.stop()

    run(main())


def test_deadline_loop_death_fails_waiters_over_immediately():
    _kill_failover_body(deadline=True)


def test_legacy_loop_death_fails_waiters_over_immediately():
    """The satellite bugfix applies to the default fixed-window loop
    too: kill → waiters resolve now; restart → re-armed wake."""
    _kill_failover_body(deadline=False)


def test_match_compile_fault_host_serves_then_recovers():
    """An injected fault at the match.compile seam (the warm/compile
    step) rides the sync loop's failure path: the node still starts,
    the host path serves, and the retry heals the mirror."""

    async def main():
        faultinject.install(FaultInjector([
            {"point": "match.compile", "action": "raise", "times": 1},
        ]))
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            assert ms is not None
            sub(b, "c1", "a/+")
            # the first warm raised; the 1 s retry re-syncs and serves
            assert await settle(lambda: ms_synced(node), timeout=60)
            inj = faultinject.get()
            assert inj is not None and inj.fired.get("match.compile") == 1
            await ms.prefetch("a/x")
            assert ms.hint_routes("a/x") is not None
        finally:
            faultinject.uninstall()
            await node.stop()

    run(main())


def test_deadline_default_off_keeps_legacy_loop():
    async def main():
        cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
        cfg.put("tpu.enable", True)
        cfg.put("tpu.mirror_refresh_interval", 0.01)
        node = BrokerNode(cfg)
        await node.start()
        try:
            ms = node.match_service
            assert ms is not None
            assert ms.deadline is False          # opt-in stays off
            assert ms.info()["deadline"] is False
        finally:
            await node.stop()

    run(main())


# ---------------------------------------------------------------------------
# split dispatch-vs-readback estimate (ISSUE 12, ROADMAP dispatch-tax (c))
# ---------------------------------------------------------------------------

def test_split_estimate_warm_fallback_and_feed():
    """Cold: the combined EWMA serves the partial-flush trigger.  Warm
    (>= SPLIT_WARM component samples): the trigger subtracts the
    queue-wait-free dispatch + readback component sum instead."""
    async def main():
        node = make_node()
        await node.start()
        try:
            ms = node.match_service
            # cold: no component samples yet → combined fallback
            ms._est_split_samples = 0
            ms._est_dispatch_s = 0.033
            ms._est_disp_s = 0.004
            ms._est_rb_s = 0.002
            assert ms._dispatch_est() == 0.033
            # feed the stage timers to warmth
            for _ in range(ms.SPLIT_WARM):
                ms._note_split(0.010, 0.005)
            assert ms._est_split_samples >= ms.SPLIT_WARM
            est = ms._dispatch_est()
            assert est == ms._est_disp_s + ms._est_rb_s
            assert 0.003 < ms._est_disp_s < 0.011
            assert 0.001 < ms._est_rb_s < 0.006
            # the bound uses the split estimate once warm
            ms._rate_ewma = 1000.0
            want = int(1000.0 * (ms.deadline_s - est))
            assert ms._deadline_bound() == want
            info = ms.info()
            assert info["est_split_warm"] is True
            assert info["est_disp_ms"] > 0
            assert info["est_readback_ms"] > 0
        finally:
            await node.stop()

    run(main())


def test_split_estimate_fed_by_real_dispatches():
    """A real serve path feeds the split components: after live
    prefetches the component estimates carry measured stage times."""
    async def main():
        node = make_node()
        await node.start()
        try:
            b = node.broker
            ms = node.match_service
            sub(b, "c1", "a/+")
            assert await settle(lambda: ms_synced(node))
            before = ms._est_split_samples
            for i in range(3):
                await ms.prefetch(f"a/real{i}")
            assert ms._est_split_samples > before
            assert ms._est_disp_s > 0 and ms._est_rb_s > 0
        finally:
            await node.stop()

    run(main())
