"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's multi-node-without-a-cluster CT pattern
(SURVEY.md §4): correctness/sharding tests run on
``--xla_force_host_platform_device_count=8`` CPU devices; real-TPU perf is
exercised only by ``bench.py``.

Must run before any test module imports jax, hence env mutation at
conftest import time.
"""

import os

import re

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
).strip()
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_ENABLE_X64"] = "0"

# The in-process TPU match service defaults ON for production nodes; in
# the unit suite it would add a kernel jit compile to every node start.
# Tests that exercise it opt in with an explicit `tpu.enable = true`.
os.environ.setdefault("EMQX_TPU__ENABLE", "false")

def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running (bench smoke, multihost) — excluded from "
        "tier-1 via -m 'not slow'",
    )
    # Donated-operand kernels (the serve pipeline's nfa_match_donated)
    # warn once per compile when a donated buffer can't be aliased —
    # best-effort donation by design (match_kernel.py filters this in
    # production; pytest's per-test filter reset needs the ini form).
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Some donated buffers were not usable")

# This box's sitecustomize force-registers the TPU PJRT plugin and rewrites
# jax_platforms to "axon,cpu" for every interpreter; env vars alone don't
# win.  Re-pin to CPU before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", jax.devices()
assert len(jax.devices()) == 8, jax.devices()
