"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's multi-node-without-a-cluster CT pattern
(SURVEY.md §4): correctness/sharding tests run on
``--xla_force_host_platform_device_count=8`` CPU devices; real-TPU perf is
exercised only by ``bench.py``.

Must run before any test module imports jax, hence env mutation at
conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_ENABLE_X64"] = "0"
