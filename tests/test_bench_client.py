"""Load-generator smoke tests: the emqtt_bench analog driving a live
in-process broker node end-to-end (SURVEY.md §2.3 / §6: emqtt_bench is the
reference's baseline driver)."""

import asyncio

from emqx_tpu.bench_client import run_scenario
from emqx_tpu.config import Config
from emqx_tpu.node import BrokerNode


def run(coro):
    return asyncio.run(coro)


async def with_node():
    cfg = Config(file_text='listeners.tcp.default.bind = "127.0.0.1:0"\n')
    node = BrokerNode(cfg)
    await node.start()
    return node, node.listeners.all()[0].port


def test_conn_storm():
    async def main():
        node, port = await with_node()
        try:
            out = await run_scenario("conn", port=port, count=25)
            assert out["connected"] == 25
            assert out["connect_failures"] == 0
        finally:
            await node.stop()

    run(main())


def test_pub_with_e2e_latency():
    async def main():
        node, port = await with_node()
        try:
            out = await run_scenario(
                "pub", port=port, count=4, messages=20, qos=1,
                subscribers=4, duration=3.0, payload_size=64,
            )
            assert out["sent"] == 80
            assert out["received"] == 80  # each sub matches its own topic
            assert out["latency_us"]["p99"] is not None
            assert out["latency_us"]["p50"] > 0
        finally:
            await node.stop()

    run(main())


def test_paced_publish_rate():
    async def main():
        node, port = await with_node()
        try:
            out = await run_scenario(
                "pub", port=port, count=2, rate=50.0, duration=1.0,
            )
            # 2 clients x 50 msg/s x 1 s, generous tolerance for CI noise
            assert 60 <= out["sent"] <= 140
        finally:
            await node.stop()

    run(main())
