"""Typed layered config — emqx_config/emqx_schema/hocon parity
(SURVEY.md §5.6)."""

import pytest

from emqx_tpu.config import Config, parse_hocon, duration, bytesize


def test_value_parsers():
    assert duration("15s") == 15.0
    assert duration("2m") == 120.0
    assert duration("100ms") == 0.1
    assert bytesize("1MB") == 1 << 20
    assert bytesize("64KB") == 64 << 10
    with pytest.raises(ValueError):
        duration("abc")


def test_hocon_subset():
    text = """
    # comment
    node.name = "n1@host"     // trailing comment
    mqtt {
      max_packet_size = 2MB
      max_inflight = 64
      retain_available = false
    }
    broker.shared_subscription_strategy = round_robin
    listeners.tcp.default { bind = "127.0.0.1:1883" }
    tags = [a, "b c", 3]
    """
    d = parse_hocon(text)
    assert d["node"]["name"] == "n1@host"
    assert d["mqtt"]["max_packet_size"] == "2MB"
    assert d["mqtt"]["max_inflight"] == 64
    assert d["mqtt"]["retain_available"] is False
    assert d["broker"]["shared_subscription_strategy"] == "round_robin"
    assert d["listeners"]["tcp"]["default"]["bind"] == "127.0.0.1:1883"
    assert d["tags"] == ["a", "b c", 3]


def test_layering_defaults_file_env():
    cfg = Config(
        file_text="mqtt.max_inflight = 64\nmqtt.session_expiry_interval = 1h",
        env={"EMQX_MQTT__MAX_INFLIGHT": "128", "UNRELATED": "x"},
    )
    assert cfg.get("mqtt.max_inflight") == 128            # env wins
    assert cfg.get("mqtt.session_expiry_interval") == 3600.0  # file
    assert cfg.get("mqtt.max_qos_allowed") == 2           # default


def test_schema_rejects_unknown_and_invalid():
    with pytest.raises(ValueError):
        Config(file_text="mqtt.not_a_key = 1", env={})
    with pytest.raises(ValueError):
        Config(file_text="mqtt.max_qos_allowed = 7", env={})
    cfg = Config(env={})
    with pytest.raises(ValueError):
        cfg.put("broker.shared_subscription_strategy", "bogus")


def test_zone_overrides():
    cfg = Config(
        file_text="""
        mqtt.max_inflight = 32
        zones.external.mqtt.max_inflight = 8
        """,
        env={},
    )
    assert cfg.zone(None).get("mqtt.max_inflight") == 32
    assert cfg.zone("external").get("mqtt.max_inflight") == 8
    assert cfg.zone("external").get("mqtt.max_qos_allowed") == 2


def test_hot_update_handler_two_phase():
    cfg = Config(env={})
    seen = []
    cfg.on_update("tpu.", lambda p, old, new: seen.append((p, old, new)))
    cfg.put("tpu.batch_size", 8192)
    assert seen == [("tpu.batch_size", 2048, 8192)]
    assert cfg.get("tpu.batch_size") == 8192

    def boom(p, old, new):
        raise RuntimeError("refuse")

    cfg.on_update("tpu.", boom)
    with pytest.raises(RuntimeError):
        cfg.put("tpu.batch_size", 1024)
    assert cfg.get("tpu.batch_size") == 8192  # rolled back


def test_duration_and_size_coercion_via_env():
    cfg = Config(env={"EMQX_MQTT__MAX_PACKET_SIZE": "2MB",
                      "EMQX_TPU__BATCH_DEADLINE": "500ms"})
    assert cfg.get("mqtt.max_packet_size") == 2 << 20
    assert cfg.get("tpu.batch_deadline") == 0.5


def test_schema_clamps_multichip_autotune_keys():
    """ISSUE 20 registry hygiene: the autotune keys validate their
    documented ranges, and ``match.readback.auto_slack`` is a
    FRACTION — values outside [0, 1] are config errors, not silent
    extrapolation."""
    cfg = Config(env={})
    assert cfg.get("match.multichip.ep.autotune.enable") is False
    cfg.put("match.multichip.ep.autotune.enable", True)
    cfg.put("match.multichip.ep.autotune.grow_threshold", 0.1)
    cfg.put("match.multichip.ep.autotune.shrink_threshold", 0.0)
    cfg.put("match.multichip.ep.autotune.max_cap_class", 8)
    cfg.put("match.multichip.ep.autotune.max_moved_roots", 0)
    with pytest.raises(ValueError):
        cfg.put("match.multichip.ep.autotune.grow_threshold", 0.0)
    with pytest.raises(ValueError):
        cfg.put("match.multichip.ep.autotune.grow_threshold", 1.5)
    with pytest.raises(ValueError):
        cfg.put("match.multichip.ep.autotune.shrink_threshold", -0.1)
    with pytest.raises(ValueError):
        cfg.put("match.multichip.ep.autotune.max_cap_class", 9)
    with pytest.raises(ValueError):
        cfg.put("match.multichip.ep.autotune.max_cap_class", -1)
    with pytest.raises(ValueError):
        cfg.put("match.multichip.ep.autotune.max_moved_roots", 5000)
    cfg.put("match.readback.auto_slack", 0.0)
    cfg.put("match.readback.auto_slack", 1.0)
    with pytest.raises(ValueError):
        cfg.put("match.readback.auto_slack", 1.5)
    with pytest.raises(ValueError):
        cfg.put("match.readback.auto_slack", -0.1)
