"""jq evaluator (`rule_engine/jq.py`) — expected outputs hand-checked
against real jq 1.7 behavior (generator semantics, total order,
operator table)."""

import pytest

from emqx_tpu.rule_engine.jq import JqError, jq_eval

DOC = {
    "user": {"name": "ada", "tags": ["ops", "dev"], "age": 36},
    "xs": [1, 2, 3],
    "pairs": [{"k": "a", "v": 1}, {"k": "b", "v": 2}],
    "weird key": 7,
    "n": None,
}


CASES = [
    # paths
    (".", DOC, [DOC]),
    (".user.name", DOC, ["ada"]),
    ('.["weird key"]', DOC, [7]),
    (".xs[0]", DOC, [1]),
    (".xs[-1]", DOC, [3]),
    (".xs[7]", DOC, [None]),
    (".missing", DOC, [None]),
    (".missing.deeper", DOC, [None]),       # null propagates
    (".xs[]", DOC, [1, 2, 3]),
    (".user.tags[]", DOC, ["ops", "dev"]),
    (".xs[1:]", DOC, [[2, 3]]),
    (".xs[:2]", DOC, [[1, 2]]),
    (".xs[1:2]", DOC, [[2]]),
    # optional
    (".user.name?", DOC, ["ada"]),
    (".n[]?", DOC, []),
    ('.user | .name', DOC, ["ada"]),
    # comma + pipe
    (".user.name, .xs[0]", DOC, ["ada", 1]),
    (".xs[] | . + 10", DOC, [11, 12, 13]),
    # literals + arithmetic
    ("1 + 2", None, [3]),
    ('"a" + "b"', None, ["ab"]),
    ("[1,2] + [3]", None, [[1, 2, 3]]),
    ('{"a":1} + {"b":2}', None, [{"a": 1, "b": 2}]),
    ("null + 5", None, [5]),
    ("10 - 3", None, [7]),
    ("[1,2,3] - [2]", None, [[1, 3]]),
    ("3 * 2.5", None, [7.5]),
    ("10 / 4", None, [2.5]),
    ("10 / 5", None, [2]),
    ('"a,b,c" / ","', None, [["a", "b", "c"]]),
    ("7 % 3", None, [1]),
    ("-7 % 3", None, [-1]),                 # jq: sign of the dividend
    ("- .xs[0]", DOC, [-1]),
    # comparisons + jq total order
    ("1 < 2", None, [True]),
    ('"abc" == "abc"', None, [True]),
    ("null < false", None, [True]),
    ("[1,2] < [1,3]", None, [True]),
    (".xs[0] != 2", DOC, [True]),
    # and/or/not/alternative
    ("true and false", None, [False]),
    ("false or true", None, [True]),
    ("null // 5", None, [5]),
    ("false // 5", None, [5]),
    (".user.name // 5", DOC, ["ada"]),
    (".missing.x? // 0", DOC, [0]),
    ("true | not", None, [False]),
    ("null | not", None, [True]),
    # constructions (cartesian fan-out)
    ("[.xs[] * 2]", DOC, [[2, 4, 6]]),
    ("[]", None, [[]]),
    ('{"a": 1}', None, [{"a": 1}]),
    ("{name: .user.name}", DOC, [{"name": "ada"}]),
    ("{v: .xs[]}", DOC, [{"v": 1}, {"v": 2}, {"v": 3}]),
    ('{(.user.name): 1}', DOC, [{"ada": 1}]),
    ("{user} | .user.age", DOC, [36]),      # shorthand key
    # if/elif/else (generator condition; else defaults to .)
    ("if .xs[0] == 1 then \"one\" else \"other\" end", DOC, ["one"]),
    ("if false then 1 elif true then 2 else 3 end", None, [2]),
    ("if false then 1 elif false then 2 else 3 end", None, [3]),
    ("5 | if . > 3 then . end", None, [5]),
    # builtins
    (".xs | length", DOC, [3]),
    ('"abcd" | length', None, [4]),
    ("null | length", None, [0]),
    (".user | keys", DOC, [["age", "name", "tags"]]),
    (".xs | keys", DOC, [[0, 1, 2]]),
    (".n | values", DOC, []),
    (".xs[0] | type", DOC, ["number"]),
    (".user | type", DOC, ["object"]),
    (".xs | add", DOC, [6]),
    ("[] | add", None, [None]),
    ('["a","b"] | add', None, ["ab"]),
    ("3.7 | floor", None, [3]),
    ("3.2 | ceil", None, [4]),
    ("9 | sqrt", None, [3.0]),
    ("-4 | abs", None, [4]),
    ("42 | tostring", None, ["42"]),
    ('[1,2] | tostring', None, ["[1,2]"]),
    ('"42" | tonumber', None, [42]),
    ('"4.5" | tonumber', None, [4.5]),
    ('"AbC" | ascii_downcase', None, ["abc"]),
    ('"AbC" | ascii_upcase', None, ["ABC"]),
    (".xs | reverse", DOC, [[3, 2, 1]]),
    ('"abc" | reverse', None, ["cba"]),
    ("[3,1,2] | sort", None, [[1, 2, 3]]),
    ('[2, "a", null, true] | sort', None, [[None, True, 2, "a"]]),
    (".pairs | sort_by(.v) | .[0].k", DOC, ["a"]),
    ("[3,1,3,2,1] | unique", None, [[1, 2, 3]]),
    ('.user.tags | join("+")', DOC, ["ops+dev"]),
    ('"a b c" | split(" ")', None, [["a", "b", "c"]]),
    (".xs | map(. * 10)", DOC, [[10, 20, 30]]),
    (".xs[] | select(. > 1)", DOC, [2, 3]),
    (".pairs | map(select(.v == 2)) | .[0].k", DOC, ["b"]),
    ('.user | has("name")', DOC, [True]),
    ('.user | has("zz")', DOC, [False]),
    (".xs | has(1)", DOC, [True]),
    ('"hello" | contains("ell")', None, [True]),
    ('["a","b"] | contains(["a"])', None, [True]),
    ('"topic/x" | startswith("topic")', None, [True]),
    ('"topic/x" | endswith("x")', None, [True]),
    ('"pre-body" | ltrimstr("pre-")', None, ["body"]),
    ('"body.json" | rtrimstr(".json")', None, ["body"]),
    ('"dev42" | test("^dev[0-9]+$")', None, [True]),
    (".xs | first", DOC, [1]),
    (".xs | last", DOC, [3]),
    (".xs | min", DOC, [1]),
    (".xs | max", DOC, [3]),
    ("[] | min", None, [None]),
    ("range(3)", None, [0, 1, 2]),
    ("range(1; 4)", None, [1, 2, 3]),
    ("empty", None, []),
    (".xs[] | empty", DOC, []),
    ('{"a":1} | to_entries', None, [[{"key": "a", "value": 1}]]),
    ('[{"key":"a","value":1}] | from_entries', None, [{"a": 1}]),
    # nesting / precedence
    ("(1 + 2) * 3", None, [9]),
    (".pairs[] | {(.k): .v} ", DOC, [{"a": 1}, {"b": 2}]),
    ("[.pairs[].v] | add", DOC, [3]),
    (".xs[] + .xs[0]", DOC, [2, 3, 4]),     # cartesian over streams
]


@pytest.mark.parametrize("prog,doc,want", CASES,
                         ids=[c[0] for c in CASES])
def test_jq_case(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_errors():
    for prog, doc in [
        (".xs | .[] | .[]", {"xs": [1]}),   # iterate a number
        ("1 + \"a\"", None),                # number + string
        ("1 / 0", None),
        ("error(\"boom\")", None),
        ("nosuchfn", None),
        ("$undefined", None),               # unbound variable
        (". ..", None),
        ("if true then 1", None),           # missing end
        ('{"k" 1}', None),                  # bad object syntax
    ]:
        with pytest.raises(JqError):
            jq_eval(prog, doc)


def test_jq_error_suppression_forms():
    assert jq_eval(".[]?", 42) == []
    assert jq_eval('.["k"]?', 42) == []
    assert jq_eval(".k? // \"d\"", 42) == ["d"]
    # alternative swallows left-side errors too (jq semantics)
    assert jq_eval(".[] // \"d\"", 42) == ["d"]


def test_rule_engine_jq_func_still_parses_json_input():
    from emqx_tpu.rule_engine.funcs import call_func

    out = call_func("jq", ['.a[] | . * 2', '{"a": [1, 2]}'])
    assert out == [2, 4]
    out = call_func("jq", ['{sum: (.a | add)}', b'{"a": [3, 4]}'])
    assert out == [{"sum": 7}]
    with pytest.raises(ValueError):
        call_func("jq", [".a", "{not json"])


def test_jq_dot_bracket_forms():
    """Real jq (and the replaced subset) accept a dot before brackets:
    .a.["k"], .a.[], .a.[0] (review finding, round 5)."""
    doc = {"a": {"k": 1, "xs": [5, 6]}}
    assert jq_eval('.a.["k"]', doc) == [1]
    assert jq_eval(".a.xs.[0]", doc) == [5]
    assert jq_eval(".a.xs.[]", doc) == [5, 6]
    assert jq_eval('.["a"].["xs"].[1]', doc) == [6]


JQ2_CASES = [
    ("[1,[2,[3]]] | flatten", None, [[1, 2, 3]]),
    ("[1,[2,[3]]] | flatten(1)", None, [[1, 2, [3]]]),
    ("[true, false] | any", None, [True]),
    ("[true, false] | all", None, [False]),
    ("[] | any", None, [False]),
    ("[] | all", None, [True]),
    ("[1,2,3] | any(. > 2)", None, [True]),
    ("[1,2,3] | all(. > 0)", None, [True]),
    ('[{"k":"a","v":1},{"k":"b","v":2},{"k":"a","v":3}] '
     '| group_by(.k) | map(length)', None, [[2, 1]]),
    ('[{"v":3},{"v":1},{"v":2}] | min_by(.v)', None, [{"v": 1}]),
    ('[{"v":3},{"v":1},{"v":2}] | max_by(.v)', None, [{"v": 3}]),
    ("[] | min_by(.v)", None, [None]),
    ('[{"k":1,"x":"a"},{"k":1,"x":"b"},{"k":2,"x":"c"}] '
     '| unique_by(.k) | length', None, [2]),
    ('{"a":[1]} | tojson', None, ['{"a":[1]}']),
    ('"[1,2]" | fromjson', None, [[1, 2]]),
    ('"ab" | explode', None, [[97, 98]]),
    ("[97,98] | implode", None, ["ab"]),
    # recursive descent
    ('{"a":{"b":1},"c":[2]} | [..]', None,
     [[{"a": {"b": 1}, "c": [2]}, {"b": 1}, 1, [2], 2]]),
    ("[..] | length", {"x": {"y": {"z": 0}}}, [4]),
    ('{"a":1,"b":{"c":2}} | [.. | select(type == "number")]',
     None, [[1, 2]]),
]


@pytest.mark.parametrize("prog,doc,want", JQ2_CASES,
                         ids=[c[0][:40] for c in JQ2_CASES])
def test_jq_round5b_builtins(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_fromjson_and_implode_errors():
    with pytest.raises(JqError):
        jq_eval('"{bad" | fromjson', None)
    with pytest.raises(JqError):
        jq_eval("[-1] | implode", None)
    with pytest.raises(JqError):
        jq_eval('"x" | flatten', None)


JQ_RECURSE_CASES = [
    # builtin.jq: def recurse(f): def r: ., (f | r); r;
    ("[recurse(if . < 3 then . + 1 else empty end)]", 0, [[0, 1, 2, 3]]),
    ("[recurse(.c?[]?)]", {"c": [{"c": [1]}, 2]},
     [[{"c": [{"c": [1]}, 2]}, {"c": [1]}, 1, 2]]),
    # recurse(f; cond): descend only while cond holds on f's output
    ("[recurse(. * 2; . < 100)]", 1, [[1, 2, 4, 8, 16, 32, 64]]),
    ("[recurse(.a; . != null)]", {"a": {"a": None}},
     [[{"a": {"a": None}}, {"a": None}]]),
]


@pytest.mark.parametrize("prog,doc,want", JQ_RECURSE_CASES,
                         ids=[c[0][:40] for c in JQ_RECURSE_CASES])
def test_jq_recurse_with_filter(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_recurse_runaway_capped():
    with pytest.raises(JqError, match="cap"):
        jq_eval("[recurse(.)]", 1)


JQ_LANG_CASES = [
    # variable bindings: `.` stays the original input in BODY
    (".a as $x | .b + $x", {"a": 1, "b": 10}, [11]),
    # one binding per output of the source (generator semantics)
    (".[] as $x | $x + 100", [1, 2, 3], [101, 102, 103]),
    # $var with postfix chain
    (".u as $u | $u.name", {"u": {"name": "ann"}}, ["ann"]),
    # nested bindings shadow
    ("1 as $x | 2 as $x | $x", None, [2]),
    # reduce: classic sum
    ("reduce .[] as $x (0; . + $x)", [1, 2, 3, 4], [10]),
    # reduce folds with the LAST output of update:
    # 0 -> last(1,100)=100 -> last(102,200)=200
    ("reduce (1,2) as $x (0; . + $x, . + 100)", None, [200]),
    # foreach: running sums
    ("[foreach .[] as $x (0; . + $x)]", [1, 2, 3], [[1, 3, 6]]),
    # foreach with extract
    ("[foreach .[] as $x (0; . + $x; . * 10)]", [1, 2], [[10, 30]]),
    # try/catch
    ("try error(\"boom\") catch .", None, ["boom"]),
    ("try (1/0) catch \"div\"", None, ["div"]),
    ("[.[] | try tonumber]", ["1", "x", "3"], [[1, 3]]),
    # string interpolation
    ('"a=\\(.a), b=\\(.b)"', {"a": 1, "b": [2]}, ["a=1, b=[2]"]),
    ('"\\(1,2)-\\(3)"', None, ["1-3", "2-3"]),
    # interpolation containing a string literal with parens
    ('"v=\\(.k // "(none)")"', {}, ["v=(none)"]),
    # new builtins
    ("[limit(2; .[])]", [1, 2, 3, 4], [[1, 2]]),
    ("first(.[] | select(. > 1))", [1, 2, 3], [2]),
    ("last(.[])", [1, 2, 3], [3]),
    ("nth(1; .[])", [4, 5, 6], [5]),
    ("[.[] | until(. >= 10; . * 2)]", [1, 3], [[16, 12]]),
    ("[while(. < 20; . * 2)]", 1, [[1, 2, 4, 8, 16]]),
    ("getpath([\"a\", \"b\"])", {"a": {"b": 7}}, [7]),
    ("getpath([\"a\", \"x\"])", {"a": {"b": 7}}, [None]),
    ("setpath([\"a\", \"b\"]; 9)", {"a": {"b": 7}, "c": 1},
     [{"a": {"b": 9}, "c": 1}]),
    ("setpath([\"n\", 1]; 5)", {}, [{"n": [None, 5]}]),
    ("[paths]", {"a": {"b": 1}}, [[["a"], ["a", "b"]]]),
    ("[leaf_paths]", {"a": {"b": 1}, "c": [2]},
     [[["a", "b"], ["c", 0]]]),
    ('[splits("[,;]")]', "a,b;c", [["a", "b", "c"]]),
    ("1 | isnan", None, [False]),
    ("infinite | isinfinite", None, [True]),
    ("utf8bytelength", "héllo", [6]),
    # reduce over an object stream via variables
    ("reduce to_entries[] as $e ({}; . + {($e.value): $e.key})",
     {"a": "x", "b": "y"}, [{"x": "a", "y": "b"}]),
]


@pytest.mark.parametrize("prog,doc,want", JQ_LANG_CASES,
                         ids=[c[0][:44] for c in JQ_LANG_CASES])
def test_jq_language_features(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_until_runaway_capped():
    with pytest.raises(JqError, match="cap"):
        jq_eval("until(. < 0; . + 1)", 1)


def test_jq_bare_dot_as_binding():
    """`. as $x | BODY` — the canonical binding form; `as` is a
    reserved word, never a `.as` field read (review finding)."""
    assert jq_eval(". as $x | .b + $x.a", {"a": 5, "b": 2}) == [7]
    assert jq_eval(".[] | . as $n | $n * 2", [1, 2]) == [2, 4]
    assert jq_eval("reduce . as $x (10; . + $x)", 5) == [15]
    # a field literally named "as" needs the quoted form, like jq
    assert jq_eval('.["as"]', {"as": 9}) == [9]


def test_jq_setpath_index_capped():
    with pytest.raises(JqError, match="cap"):
        jq_eval("setpath([200000000]; 1)", None)


def test_jq_nth_bad_count_is_jqerror():
    with pytest.raises(JqError):
        jq_eval("nth(null; .[])", [1, 2, 3])
    with pytest.raises(JqError):
        jq_eval('nth("a"; .[])', [1, 2, 3])
    with pytest.raises(JqError):
        jq_eval('limit("a"; .[])', [1, 2, 3])


# ---------------------------------------------------------------------------
# round-5 close-out: paths/assignment, regex capture family, dates
# ---------------------------------------------------------------------------

ASSIGN_CASES = [
    ('.a = 1', {"b": 2}, [{"b": 2, "a": 1}]),
    ('.a.b = 5', {}, [{"a": {"b": 5}}]),
    ('.a[0] = "x"', {"a": [1, 2]}, [{"a": ["x", 2]}]),
    ('.[] = 0', [1, 2, 3], [[0, 0, 0]]),
    ('.a |= . + 1', {"a": 4}, [{"a": 5}]),
    ('.a += 2', {"a": 1}, [{"a": 3}]),
    ('.a -= 2', {"a": 1}, [{"a": -1}]),
    ('.a *= 3', {"a": 2}, [{"a": 6}]),
    ('.a /= 2', {"a": 7}, [{"a": 3.5}]),
    ('.a //= 9', {"a": None}, [{"a": 9}]),
    ('.a //= 9', {"a": 5}, [{"a": 5}]),
    ('(.a, .b) = 7', {}, [{"a": 7, "b": 7}]),
    # rhs sees the ORIGINAL input, one output per rhs value
    ('.a = (.b, .c)', {"b": 1, "c": 2},
     [{"b": 1, "c": 2, "a": 1}, {"b": 1, "c": 2, "a": 2}]),
    ('.users[].age += 1', {"users": [{"age": 1}, {"age": 2}]},
     [{"users": [{"age": 2}, {"age": 3}]}]),
    # select() narrows the path set, jq-style
    ('(.a[] | select(. > 1)) = 0', {"a": [1, 2, 3]}, [{"a": [1, 0, 0]}]),
    # |= with empty rhs deletes the path (jq 1.7 semantics)
    ('.a |= empty', {"a": 1, "b": 2}, [{"b": 2}]),
    ('del(.a)', {"a": 1, "b": 2}, [{"b": 2}]),
    ('del(.a[1])', {"a": [1, 2, 3]}, [{"a": [1, 3]}]),
    # multiple indices delete deepest-first: no index shifting
    ('del(.a[0, 1])', {"a": [1, 2, 3]}, [{"a": [3]}]),
    ('del(.missing)', {"b": 2}, [{"b": 2}]),
    ('path(.a.b)', None, [["a", "b"]]),
    ('path(.a[])', {"a": [1, 2]}, [["a", 0], ["a", 1]]),
    ('delpaths([["a", "b"], ["c"]])',
     {"a": {"b": 1, "z": 2}, "c": 3}, [{"a": {"z": 2}}]),
    # assignment precedence: `//` is looser, `=` family over or-level
    ('.a = 1 // 2', {}, [{"a": 1}]),
    # optional path forms skip mistyped bases instead of erroring
    ('.a.b? = 1', {"a": 5}, [{"a": 5}]),
    ('(.xs[]? | .k) = 1', {"xs": 3}, [{"xs": 3}]),
]


@pytest.mark.parametrize("prog,doc,want", ASSIGN_CASES,
                         ids=[c[0] for c in ASSIGN_CASES])
def test_jq_assignment_family(prog, doc, want):
    assert jq_eval(prog, doc) == want


REGEX_CASES = [
    ('match("a+")', "baaad",
     [{"offset": 1, "length": 3, "string": "aaa", "captures": []}]),
    ('[match("a"; "g") | .offset]', "banana", [[1, 3, 5]]),
    ('capture("(?<x>[0-9]+)-(?<y>[a-z]+)")', "17-abc",
     [{"x": "17", "y": "abc"}]),
    ('sub("a"; "o")', "banana", ["bonana"]),
    ('gsub("a"; "o")', "banana", ["bonono"]),
    # the replacement expression sees named captures as `.`
    ('gsub("(?<c>[aeiou])"; "<\\(.c)>")', "hid", ["h<i>d"]),
    ('test("HI"; "i")', "hi there", [True]),
    ('test("nope")', "hi there", [False]),
    ('[splits("[, ]+")]', "a, b,c", [["a", "b", "c"]]),
    # multi-output replacements fan out cartesian-style over matches
    # (real-jq parity; earlier matches vary slowest)
    ('[sub("a"; "x", "y")]', "banana", [["bxnana", "bynana"]]),
    ('[gsub("n"; "1", "2")]', "banana",
     [["ba1a1a", "ba1a2a", "ba2a1a", "ba2a2a"]]),
    ('sub("zzz"; "x", "y")', "banana", ["banana"]),   # no match: input
    ('[gsub("(?<c>[aeiou])"; .c, "_")]', "ox",
     [["ox", "_x"]]),
]


@pytest.mark.parametrize("prog,doc,want", REGEX_CASES,
                         ids=[c[0] for c in REGEX_CASES])
def test_jq_regex_family(prog, doc, want):
    assert jq_eval(prog, doc) == want


DATE_CASES = [
    # 1660000000 == 2022-08-08T23:06:40Z (a Monday; yday 0-based)
    ('gmtime', 1660000000, [[2022, 7, 8, 23, 6, 40, 1, 219]]),
    ('gmtime | mktime', 1660000000, [1660000000]),
    ('todate', 1660000000, ["2022-08-08T23:06:40Z"]),
    ('fromdate', "2022-08-08T23:06:40Z", [1660000000]),
    ('strftime("%Y/%m/%d")', 1660000000, ["2022/08/08"]),
    ('strptime("%Y-%m-%d") | mktime', "2022-08-08", [1659916800]),
    ('fromdate | todate', "2000-01-01T00:00:00Z",
     ["2000-01-01T00:00:00Z"]),
]


@pytest.mark.parametrize("prog,doc,want", DATE_CASES,
                         ids=[c[0] for c in DATE_CASES])
def test_jq_date_family(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_now_is_wall_clock():
    import time
    (t,) = jq_eval("now", None)
    assert abs(t - time.time()) < 5


def test_jq_assignment_error_forms():
    with pytest.raises(JqError, match="path"):
        jq_eval("(1 + 2) = 3", {})            # not a path expression
    with pytest.raises(JqError):
        jq_eval('.a = .b = 1', {})            # nonassoc, like jq
    with pytest.raises(JqError, match="regex"):
        jq_eval('test("a"; "q")', "x")        # unknown flag
    with pytest.raises(JqError):
        jq_eval('gsub("(?<c>a)"; 42)', "a")   # non-string replacement


def test_jq_date_errors_are_catchable():
    """Platform time_t overflows must surface as JqError (catchable by
    jq-level try/catch), not raw OverflowError (review finding)."""
    assert jq_eval('try todate catch "bad"', 1e30) == ["bad"]
    assert jq_eval('try gmtime catch "bad"', 1e30) == ["bad"]
    assert jq_eval('try mktime catch "bad"',
                   [10**15, 0, 1, 0, 0, 0]) == ["bad"]


def test_jq_first_as_path_is_dot_zero():
    """jq defines first as .[0]: as a path it must index position 0
    (arrays/null), not 'first object key' (review finding)."""
    assert jq_eval('(.a | first) = 5', {"a": []}) == [{"a": [5]}]
    assert jq_eval('path(first)', [7, 8]) == [[0]]
    with pytest.raises(JqError):
        jq_eval('path(first)', {"b": 1})      # like jq: number index


DEF_CASES = [
    ('def f: . + 1; f', 4, [5]),
    ('def f: . * 2; f | f', 3, [12]),
    ('def twice(g): g | g; twice(. + 3)', 0, [6]),
    # $-value params fan the call out over their output stream
    ('def f($x): $x * 10; f(1, 2)', None, [10, 20]),
    # recursion
    ('def fact: if . <= 1 then 1 else . * (. - 1 | fact) end; fact',
     5, [120]),
    # filter params are closures over the call site
    ('def m(g): [.[] | g]; m(. + 1)', [1, 2], [[2, 3]]),
    ('def f: 1; def g: f + 1; g', None, [2]),
    # defs are legal mid-pipeline, jq-style
    ('.a | def f: . + 1; f', {"a": 9}, [10]),
    # a user def shadows the builtin of the same name/arity
    ('def first: 99; first', [1, 2], [99]),
    # lexical scoping: the body sees the def-site environment
    ('5 as $n | def f: $n; f', None, [5]),
    ('def f(g): def h: g; h; f(42)', None, [42]),
]


@pytest.mark.parametrize("prog,doc,want", DEF_CASES,
                         ids=[c[0] for c in DEF_CASES])
def test_jq_def_functions(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_def_runaway_recursion_is_jqerror():
    with pytest.raises(JqError, match="recursion"):
        jq_eval("def f: f; f", None)


def test_jq_def_parse_errors():
    for prog in ("def : 1; .", "def f: 1", "def f(1): 2; f(3)"):
        with pytest.raises(JqError):
            jq_eval(prog, None)


def test_jq_value_param_also_binds_filter_name():
    """jq desugars def f($a): B to def f(a): a as $a | B, so the bare
    name stays callable (review finding)."""
    assert jq_eval('def f($x): x; f(7)', None) == [7]
    assert jq_eval('def f($x): $x + x; f(3)', None) == [6]


DESTRUCTURE_CASES = [
    ('. as [$a, $b] | $a + $b', [3, 4], [7]),
    ('. as [$a, $b, $c] | $c', [1, 2], [None]),    # short array -> null
    ('. as {a: $x} | $x', {"a": 9}, [9]),
    ('. as {$a, $b} | [$a, $b]', {"a": 1, "b": 2}, [[1, 2]]),
    ('. as {a: [$x, $y]} | $x * $y', {"a": [3, 5]}, [15]),
    ('. as {"weird key": $w} | $w', {"weird key": 8}, [8]),
    ('null as [$a] | $a', None, [None]),           # null binds nulls
    ('reduce .[] as [$k, $n] (0; . + $n)', [["a", 1], ["b", 2]], [3]),
    ('foreach .[] as {n: $n} (0; . + $n)', [{"n": 1}, {"n": 2}], [1, 3]),
    ('.[] as [$a] | $a', [[1], [2]], [1, 2]),
    ('. as {(.k): $v} | $v', {"k": "x", "x": 42}, [42]),
]


@pytest.mark.parametrize("prog,doc,want", DESTRUCTURE_CASES,
                         ids=[c[0] for c in DESTRUCTURE_CASES])
def test_jq_destructuring(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_destructuring_mismatch_errors():
    with pytest.raises(JqError, match="destructure"):
        jq_eval('. as [$a] | $a', {"x": 1})
    with pytest.raises(JqError, match="destructure"):
        jq_eval('. as {a: $x} | $x', [1, 2])


def test_jq_computed_pattern_key_sees_matched_value():
    """(expr): pattern keys evaluate with `.` bound to the value being
    destructured, not the as-site input (review finding)."""
    assert jq_eval('.items[] as {(.k): $v} | $v',
                   {"items": [{"k": "x", "x": 1}]}) == [1]
    assert jq_eval('reduce .[] as {(.k): $n} (0; . + $n)',
                   [{"k": "a", "a": 2}, {"k": "b", "b": 3}]) == [5]


FORMAT_CASES = [
    ('@base64', "hi", ["aGk="]),
    ('@base64d', "aGk=", ["hi"]),
    ('@base64 | @base64d', "round", ["round"]),
    ('@csv', [1, "a,b", None, True, 2.5], ['1,"a,b",,true,2.5']),
    ('@tsv', ["a\tb", 3], ["a\\tb\t3"]),
    ('@json', {"a": 1}, ['{"a":1}']),
    ('@text', 42, ["42"]),
    ('@html', "<b>&'\"", ["&lt;b&gt;&amp;&#39;&quot;"]),
    ('@uri', "a b/c?", ["a%20b%2Fc%3F"]),
    ('@sh', ["a b", "it's"], ["'a b' 'it'\\''s'"]),
    # jq formats null via tojson (like booleans/numbers): "null", not
    # an error
    ('@sh', None, ["null"]),
    ('@sh', [None, "x"], ["null 'x'"]),
    # format-prefixed strings format INTERPOLATIONS only, jq-style
    ('@base64 "user=\\(.u)"', {"u": "bob"}, ["user=Ym9i"]),
    ('@uri "q=\\(.q)&x=1"', {"q": "a b"}, ["q=a%20b&x=1"]),
]


@pytest.mark.parametrize("prog,doc,want", FORMAT_CASES,
                         ids=[c[0] for c in FORMAT_CASES])
def test_jq_format_strings(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_format_errors():
    with pytest.raises(JqError, match="format"):
        jq_eval("@nope", 1)
    with pytest.raises(JqError):
        jq_eval("@csv", "not an array")
    with pytest.raises(JqError):
        jq_eval("@base64d", 42)


def test_jq_uri_and_base64d_strictness():
    """@uri encodes everything outside RFC 3986 unreserved; @base64d
    rejects non-alphabet input instead of silently discarding it
    (review findings)."""
    assert jq_eval('@uri', "don't(x)!*") == ["don%27t%28x%29%21%2A"]
    with pytest.raises(JqError, match="base64"):
        jq_eval('@base64d', "!!!")


ALT_PATTERN_CASES = [
    ('. as [$a] ?// {a: $a} | $a', [7], [7]),
    ('. as [$a] ?// {a: $a} | $a', {"a": 9}, [9]),
    # vars only in the unmatched alternative bind null
    ('. as [$a, $b] ?// {c: $c} | [$a, $b, $c]', {"c": 1},
     [[None, None, 1]]),
    ('. as {x: $x} ?// [$x] | $x', [5], [5]),
    # a BODY error with one alternative retries the next (jq)
    ('.[] as [$a] ?// $a | $a', [[1], 2], [1, 2]),
    ('reduce .[] as [$n] ?// {n: $n} (0; . + $n)', [[1], {"n": 2}], [3]),
]


@pytest.mark.parametrize("prog,doc,want", ALT_PATTERN_CASES,
                         ids=[c[0] for c in ALT_PATTERN_CASES])
def test_jq_pattern_alternatives(prog, doc, want):
    assert jq_eval(prog, doc) == want


def test_jq_pattern_alternatives_all_fail():
    with pytest.raises(JqError):
        jq_eval('. as [$a] ?// {a: $a} | $a', "neither")


def test_jq_pattern_alternative_body_error_retries():
    """The ?// retry unit is MATCH AND BODY: a body/update error with
    one alternative retries the next, in `as` and in reduce/foreach
    alike (review finding)."""
    assert jq_eval(
        '.[] as [$a] ?// $a | '
        '(if ($a | type) == "number" then $a else error("e") end)',
        [[1], 2]) == [1, 2]
    assert jq_eval(
        'reduce .[] as [$n] ?// {n: $n} '
        '(0; if ($n | type) == "number" then . + $n '
        'else error("e") end)',
        [[1], {"n": 2}]) == [3]
