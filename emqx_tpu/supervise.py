"""Supervision tree for long-lived background tasks — the ``emqx_sup``
analog.

Behavioral reference: ``emqx_sup.erl`` / OTP supervisor semantics [U]
(SURVEY.md §2.1): every long-lived process sits under a supervisor with
a per-child restart policy, exponential backoff, and a restart-intensity
window.  Before this module, the broker's delivery stack ran on ad-hoc
``asyncio.create_task`` loops — a crashed fanout drain, cluster sync or
bridge worker silently stopped delivering until node restart.

Differences from OTP, deliberate:

* **escalation degrades, never dies**: exceeding the restart-intensity
  window does NOT kill the supervisor (there is no parent to restart
  *us*); the child enters *degraded* mode — an :class:`Alarms` alarm
  activates, ``broker.supervisor.degraded`` reflects the degraded-child
  count, and restarts continue at the maximum backoff so an external
  fix (network back, config change) still heals the node without a
  restart;
* **determinism is injectable**: the clock, the sleep primitive and the
  jitter RNG are constructor parameters, so tests drive backoff and
  intensity windows with a fake clock and a seeded RNG — no wall-clock
  flakiness;
* **shutdown is reverse-registration-order**: children register in
  dependency order (boot order) and stop in reverse, matching the
  reference's ``emqx_app`` stop discipline; a child may carry a
  ``drain`` callback that runs after its task is down (the fanout
  pipeline re-publishes its un-drained queue there, preserving the
  PR-1 "accepted publishes never drop" guarantee across supervised
  shutdown).

Restart policies (OTP names):

* ``permanent`` — always restarted (crash, kill, or normal return);
* ``transient`` — restarted only on abnormal exit (exception or an
  externally cancelled run); a clean return ends supervision;
* ``temporary`` — never restarted.

A :class:`Child` handle mimics enough of the ``asyncio.Task`` surface
(``cancel()`` / ``done()`` / ``await``) that converted call sites treat
it exactly like the raw task they used to hold; ``kill()`` is the chaos
surface — it cancels only the *current run*, which the supervisor then
restarts.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

log = logging.getLogger(__name__)

__all__ = ["Supervisor", "Child", "PERMANENT", "TRANSIENT", "TEMPORARY"]

PERMANENT = "permanent"
TRANSIENT = "transient"
TEMPORARY = "temporary"


class Child:
    """One supervised task: a factory (callable returning a coroutine)
    plus its restart policy and backoff parameters."""

    def __init__(
        self,
        sup: "Supervisor",
        name: str,
        factory: Callable[[], Any],
        restart: str,
        backoff_base: float,
        backoff_max: float,
        reset_after: float,
        drain: Optional[Callable[[], Any]] = None,
    ) -> None:
        self.sup = sup
        self.name = name
        self.factory = factory
        self.restart = restart
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.reset_after = reset_after
        self.drain = drain
        self.task: Optional[asyncio.Task] = None     # current run
        self.runner: Optional[asyncio.Task] = None   # supervision wrapper
        self.restarts = 0
        self.degraded = False
        self.stopping = False
        self.state = "starting"  # running|backoff|degraded|done|stopped
        self._restart_times: Deque[float] = deque()

    # -- task-like surface (drop-in for converted call sites) ----------

    def cancel(self) -> None:
        """Stop supervising AND cancel the current run (no restart)."""
        self.stopping = True
        if self.runner is not None and not self.runner.done():
            self.runner.cancel()

    def done(self) -> bool:
        return self.runner is None or self.runner.done()

    def __await__(self):
        return self.runner.__await__()

    # -- supervision surface -------------------------------------------

    def kill(self) -> bool:
        """Chaos/fault surface: cancel the CURRENT run only.  The
        supervisor treats it as an abnormal exit and restarts per
        policy.  Returns False when no run is active to kill."""
        t = self.task
        if t is not None and not t.done():
            t.cancel()
            return True
        return False

    async def stop(self) -> None:
        """Graceful stop: cancel, await the wrapper, then run ``drain``."""
        await self.sup._stop_child(self)

    def alive(self) -> bool:
        return self.task is not None and not self.task.done()

    def info(self) -> Dict[str, Any]:
        return {
            "name": self.name, "restart": self.restart,
            "state": self.state, "restarts": self.restarts,
            "degraded": self.degraded,
        }


class Supervisor:
    """Owns the background tasks of one node (see module docstring)."""

    def __init__(
        self,
        metrics: Any = None,
        alarms: Any = None,
        *,
        max_restarts: int = 5,
        window_s: float = 10.0,
        backoff_base: float = 0.05,
        backoff_max: float = 5.0,
        jitter: float = 0.1,
        seed: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], Any]] = None,
    ) -> None:
        self.metrics = metrics
        self.alarms = alarms
        # always-on flight recorder (observe/flightrec.py): set by the
        # node; a degraded-mode escalation dumps the last few hundred
        # batch events so the forensics survive the restart storm
        self.flightrec = None
        self.max_restarts = max_restarts
        self.window_s = window_s
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else asyncio.sleep
        self.children: List[Child] = []
        self.restarts = 0          # lifetime total across children
        self._stopping = False

    # ------------------------------------------------------------------

    def start_child(
        self,
        name: str,
        factory: Callable[[], Any],
        restart: str = PERMANENT,
        *,
        backoff_base: Optional[float] = None,
        backoff_max: Optional[float] = None,
        reset_after: Optional[float] = None,
        drain: Optional[Callable[[], Any]] = None,
    ) -> Child:
        if restart not in (PERMANENT, TRANSIENT, TEMPORARY):
            raise ValueError(f"unknown restart policy {restart!r}")
        # reap finished same-name children: transient loops that end and
        # re-register per activity cycle (quic.timer) must not grow the
        # child table across cycles
        self.children = [
            c for c in self.children
            if not (c.name == name and c.done())
        ]
        child = Child(
            self, name, factory, restart,
            backoff_base if backoff_base is not None else self.backoff_base,
            backoff_max if backoff_max is not None else self.backoff_max,
            reset_after if reset_after is not None else self.window_s,
            drain=drain,
        )
        child.runner = asyncio.ensure_future(self._supervise(child))
        self.children.append(child)
        return child

    def lookup(self, name: str) -> Optional[Child]:
        """Latest child registered under ``name`` (chaos tooling)."""
        for child in reversed(self.children):
            if child.name == name:
                return child
        return None

    async def stop(self) -> None:
        """Stop every child, reverse registration (dependency) order."""
        self._stopping = True
        try:
            for child in reversed(list(self.children)):
                await self._stop_child(child)
            self.children.clear()
        finally:
            self._stopping = False

    async def _stop_child(self, child: Child) -> None:
        child.stopping = True
        runner = child.runner
        if runner is not None:
            if not runner.done():
                runner.cancel()
            try:
                await runner
            except (asyncio.CancelledError, Exception):
                log.debug("supervised child %r runner exit", child.name,
                          exc_info=True)
        child.state = "stopped"
        if child.degraded:
            self._clear_degraded(child)
        if child.drain is not None:
            try:
                r = child.drain()
                if asyncio.iscoroutine(r):
                    await r
            except Exception:
                log.exception("supervised child %r drain failed", child.name)

    # ------------------------------------------------------------------

    async def _supervise(self, child: Child) -> None:
        backoff_n = 0
        while True:
            started = self._clock()
            child.state = "running"
            inner: Optional[asyncio.Task] = None
            try:
                inner = asyncio.ensure_future(child.factory())
            except Exception:
                log.exception("supervised child %r factory failed",
                              child.name)
            if inner is not None:
                child.task = inner
                try:
                    # wait() shields: an inner crash/kill completes the
                    # wait; only OUR cancellation (stop) raises here
                    await asyncio.wait([inner])
                except asyncio.CancelledError:
                    inner.cancel()
                    try:
                        await inner
                    except BaseException:
                        log.debug("supervised child %r run exit on stop",
                                  child.name, exc_info=True)
                    child.task = None
                    raise
                child.task = None
                if inner.cancelled():
                    kind = "killed"
                    log.warning("supervised child %r was cancelled "
                                "externally", child.name)
                else:
                    exc = inner.exception()
                    if exc is None:
                        kind = "normal"
                    else:
                        kind = "error"
                        log.error("supervised child %r crashed",
                                  child.name, exc_info=exc)
            else:
                kind = "error"
            now = self._clock()
            if now - started >= child.reset_after:
                # ran long enough: the failure is fresh, not a loop
                backoff_n = 0
                if child.degraded:
                    self._clear_degraded(child)
            if kind == "normal" and child.restart != PERMANENT:
                child.state = "done"
                return
            if child.restart == TEMPORARY:
                child.state = "done"
                return
            self._note_restart(child, now)
            delay = (child.backoff_max if child.degraded
                     else min(child.backoff_max,
                              child.backoff_base * (2 ** backoff_n)))
            backoff_n += 1
            delay *= 1.0 + self.jitter * self._rng.random()
            child.state = "degraded" if child.degraded else "backoff"
            await self._sleep(delay)

    def _note_restart(self, child: Child, now: float) -> None:
        child.restarts += 1
        self.restarts += 1
        if self.metrics is not None:
            self.metrics.inc("broker.supervisor.restarts")
        rt = child._restart_times
        rt.append(now)
        while rt and now - rt[0] > self.window_s:
            rt.popleft()
        if len(rt) > self.max_restarts and not child.degraded:
            self._degrade(child)

    def _degrade(self, child: Child) -> None:
        child.degraded = True
        log.error(
            "supervised child %r exceeded restart intensity (%d in %.1fs); "
            "degraded mode — restarting at max backoff",
            child.name, len(child._restart_times), self.window_s,
        )
        if self.alarms is not None:
            self.alarms.activate(
                f"supervisor_degraded:{child.name}",
                {"child": child.name, "restarts": child.restarts},
                f"supervised child {child.name} restarting too fast",
            )
        if self.flightrec is not None:
            self.flightrec.dump("supervisor_degraded", note=child.name)
        self._sync_degraded_metric()

    def _clear_degraded(self, child: Child) -> None:
        child.degraded = False
        child._restart_times.clear()
        if self.alarms is not None:
            self.alarms.deactivate(f"supervisor_degraded:{child.name}")
        self._sync_degraded_metric()

    def _sync_degraded_metric(self) -> None:
        if self.metrics is not None:
            self.metrics.set(
                "broker.supervisor.degraded",
                sum(1 for c in self.children if c.degraded),
            )

    @property
    def degraded(self) -> bool:
        """Node-level degraded-mode flag: any child over intensity."""
        return any(c.degraded for c in self.children)

    def info(self) -> Dict[str, Any]:
        return {
            "children": [c.info() for c in self.children],
            "restarts": self.restarts,
            "degraded": self.degraded,
        }
