"""Cluster substrate (SURVEY.md §2.2): membership, route replication,
inter-node forwarding, session registry/takeover — the ekka + mria +
gen_rpc layer of the reference, rebuilt on asyncio + protobuf streams."""

from .cluster import Cluster, ClusterError
from .transport import PeerConn, PeerServer

__all__ = ["Cluster", "ClusterError", "PeerConn", "PeerServer"]
