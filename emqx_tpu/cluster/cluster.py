"""Cluster manager: membership + route replication + forwarding.

The ekka/mria/gen_rpc layer rebuilt for this broker (SURVEY.md §2.2,
§5.3, §5.8), one asyncio control plane per node:

* **membership** (ekka): static seed discovery, Hello handshake with an
  incarnation counter, peer gossip in the HelloAck, heartbeats, and a
  reconnect loop (autoheal: a returning node re-bootstraps state, the
  mria replicant pattern);
* **route replication** (mria rlog): each node broadcasts its own-origin
  route deltas in batches (the 5.x ``emqx_router_syncer`` behavior);
  receivers detect epoch gaps and re-bootstrap with a full snapshot —
  the same snapshot-then-replay discipline the device NFA mirror uses;
* **forwarding** (gen_rpc): publishes matching a remote node's routes
  ship as cast frames on the peer stream; shared groups dispatch in two
  levels (sender picks the node, receiver's shared table picks the
  member);
* **session registry + takeover** (emqx_cm_registry): clientid → node
  broadcast; a resuming CONNECT on the wrong node pulls the session
  state over (subscriptions + pending messages) and the old node
  discards, exactly the SURVEY.md §3.2 takeover flow;
* **nodedown** (emqx_router_helper): a peer missing heartbeats past the
  timeout has its routes, shared members, and registry entries purged.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .. import topic as T
from ..broker.message import Message
from ..broker.session import Session, SubOpts
from . import cluster_pb2 as pb
from .transport import PeerConn, PeerServer, dial

log = logging.getLogger(__name__)

__all__ = ["Cluster", "ClusterError"]


class ClusterError(Exception):
    pass


@dataclass
class Peer:
    name: str
    host: str = ""
    port: int = 0
    conn: Optional[PeerConn] = None
    incarnation: int = 0
    last_seen: float = field(default_factory=time.monotonic)
    # replication state: the origin numbers its broadcast batches with its
    # own sequence counter (NOT router epochs — those advance for remote
    # deltas too and are a different clock on every node)
    route_seq: int = 0          # last applied origin batch seq
    durable_seq: int = 0        # last applied origin durable batch seq
    bootstrapped: bool = False
    bootstrapping: bool = False
    pending_deltas: List[Any] = field(default_factory=list)
    pending_durable: List[Any] = field(default_factory=list)

    @property
    def up(self) -> bool:
        return self.conn is not None and not self.conn.closed


def _wire_msg(msg: Message) -> pb.WireMessage:
    return pb.WireMessage(
        id=str(msg.id), sender=msg.sender or "", topic=msg.topic,
        payload=bytes(msg.payload or b""), qos=msg.qos, retain=msg.retain,
        timestamp=float(getattr(msg, "timestamp", 0.0) or 0.0),
        properties_json=json.dumps(msg.properties) if msg.properties else "",
    )


def _from_wire(w: pb.WireMessage) -> Message:
    return Message(
        id=int(w.id) if w.id.isdigit() else 0,
        sender=w.sender or None, topic=w.topic, payload=w.payload,
        qos=w.qos, retain=w.retain, timestamp=w.timestamp or time.time(),
        properties=json.loads(w.properties_json) if w.properties_json else {},
    )


class Cluster:
    HEARTBEAT_INTERVAL = 1.0
    NODE_TIMEOUT = 5.0
    SYNC_INTERVAL = 0.05
    RECONNECT_INTERVAL = 2.0

    def __init__(
        self,
        node: Any,                      # BrokerNode
        listen: str = "127.0.0.1:0",
        seeds: str = "",
        cluster_name: str = "emqx_tpu",
    ) -> None:
        self.node = node
        self.broker = node.broker
        self.name = self.broker.node
        self.cluster_name = cluster_name
        host, _, port = listen.rpartition(":")
        self.listen_host, self.listen_port = host or "127.0.0.1", int(port)
        self.seeds: List[Tuple[str, int]] = []
        for part in (seeds or "").split(","):
            if part.strip():
                h, _, p = part.strip().rpartition(":")
                self.seeds.append((h, int(p)))
        self.incarnation = int(time.time() * 1000) & 0x7FFFFFFF
        self.peers: Dict[str, Peer] = {}
        self._server: Optional[PeerServer] = None
        self._tasks: List[asyncio.Task] = []
        self._synced_epoch = 0   # local router epoch already drained
        self._sync_seq = 0       # own broadcast batch counter
        self._registry: Dict[str, str] = {}   # clientid -> remote node
        self._running = False
        self.forwards_out = 0
        self.forwards_in = 0
        # cluster config sync (emqx_conf analog).  The txn counter seeds
        # from the wall clock so a RESTARTED node's updates still sort
        # after its previous life's (peers keep per-origin high-water
        # marks; a reset-to-zero counter would be silently discarded)
        self._config_txn = int(time.time() * 1000)
        self._config_seen: Dict[str, int] = {}  # origin -> last txn applied
        # per-path version: (txn, origin) of the last applied update —
        # snapshot adoption is last-writer-wins against this, so a
        # re-bootstrap can never roll back a newer local change
        self._config_versions: Dict[str, Tuple[int, str]] = {}
        self._applying_remote_config = False
        # durable-state replication (retained + persistent sessions);
        # replicas persisted by Persistence are restored through the
        # node attribute before the cluster comes up
        from .durable import DurableReplicator

        self.durable = DurableReplicator(
            self,
            restored_replicas=getattr(
                node, "_restored_session_replicas", None),
        )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        self._attach_broker()
        self._server = PeerServer(
            self.listen_host, self.listen_port, self._handle,
            on_closed=self._conn_closed,
        )
        await self._server.start()
        self.listen_port = self._server.port
        for h, p in self.seeds:
            if (h, p) != (self.listen_host, self.listen_port):
                await self._join(h, p)
        sup = getattr(self.node, "supervisor", None)

        def spawn(name, factory):
            # supervised when the node carries a supervision tree: a
            # crashed replication/heartbeat loop restarts with backoff
            # instead of silently partitioning this node
            if sup is not None:
                return sup.start_child(name, factory)
            return asyncio.ensure_future(factory())

        self._tasks = [
            spawn("cluster.heartbeat", self._heartbeat_loop),
            spawn("cluster.sync", self._sync_loop),
            spawn("cluster.reconnect", self._reconnect_loop),
            spawn("cluster.durable", self.durable.loop),
        ]

    async def stop(self) -> None:
        self._running = False
        # stash replicas where Persistence's FINAL sync (which runs
        # after the cluster is gone) and the next life's Cluster both
        # find them
        self.node._restored_session_replicas = dict(
            self.durable.session_replicas)
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        for peer in self.peers.values():
            if peer.conn is not None:
                peer.conn.cast(
                    pb.ClusterFrame(leave=pb.Leave(node=self.name))
                )
                peer.conn.close()
        self.peers.clear()
        if self._server is not None:
            await self._server.stop()
            self._server = None
        self._detach_broker()

    def _attach_broker(self) -> None:
        self.broker.on_forward = self._forward
        self.broker.on_forward_shared = self._forward_shared
        hooks = self.broker.hooks
        hooks.add("session.created",
                  lambda cid: self._broadcast_session_op(cid, pb.SessionOp.ADD),
                  name="cluster.session.created")
        hooks.add("session.terminated",
                  lambda cid: self._broadcast_session_op(cid, pb.SessionOp.DEL),
                  name="cluster.session.terminated")
        # cluster-wide config sync: every locally-validated put (REST,
        # CLI, library) broadcasts AFTER its handlers ran clean — the
        # reference's check-then-broadcast two-phase (emqx_conf [U])
        self.node.config.on_update("", self._on_local_config_update)
        self.durable.attach()

    def _on_local_config_update(self, path: str, old: Any, new: Any) -> None:
        if self._applying_remote_config or not self._running:
            return
        import json as _json

        self._config_txn += 1
        self._config_versions[path] = (self._config_txn, self.name)
        frame = pb.ClusterFrame(config_update=pb.ConfigUpdate(
            origin=self.name, txn=self._config_txn, path=path,
            value_json=_json.dumps(new, default=str),
        ))
        for peer in self.peers.values():
            if peer.conn is not None:
                peer.conn.cast(frame)

    def _apply_config_update(self, cu: "pb.ConfigUpdate") -> None:
        if cu.origin == self.name:
            return
        if self._config_seen.get(cu.origin, 0) >= cu.txn:
            return  # replay/reorder: already applied
        self._config_seen[cu.origin] = cu.txn
        import json as _json

        self._config_versions[cu.path] = (cu.txn, cu.origin)
        self._applying_remote_config = True
        try:
            self.node.config.put(cu.path, _json.loads(cu.value_json))
        except Exception:
            # a node that can't apply keeps serving with its old value —
            # same degradation the reference accepts on apply failure
            log.exception("remote config update %s=%s failed",
                          cu.path, cu.value_json)
        finally:
            self._applying_remote_config = False

    def _detach_broker(self) -> None:
        self.broker.on_forward = None
        self.broker.on_forward_shared = None
        self.broker.hooks.delete("session.created", "cluster.session.created")
        self.broker.hooks.delete(
            "session.terminated", "cluster.session.terminated"
        )
        self.node.config.remove_handler(self._on_local_config_update)
        self.durable.detach()

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    async def _join(self, host: str, port: int) -> Optional[Peer]:
        try:
            conn = await dial(host, port, self._handle, self._conn_closed)
            resp = await conn.call(
                pb.ClusterFrame(hello=self._hello()), timeout=5.0
            )
            ack = resp.hello_ack
            if not ack.accepted:
                log.warning("join %s:%d rejected: %s", host, port, ack.reason)
                conn.close()
                return None
            peer = self._peer_up(ack.node, host, port, conn, ack.incarnation)
            # gossip: learn the acceptor's view (static-discovery helper)
            for info in ack.peers:
                if info.node != self.name and info.node not in self.peers:
                    await self._join(info.host, info.port)
            return peer
        except Exception as e:
            log.debug("join %s:%d failed: %s", host, port, e)
            return None

    def _hello(self) -> pb.Hello:
        return pb.Hello(
            node=self.name, listen_host=self.listen_host,
            listen_port=self.listen_port, incarnation=self.incarnation,
            cluster_name=self.cluster_name,
        )

    def _peer_up(
        self, name: str, host: str, port: int, conn: PeerConn, incarnation: int
    ) -> Peer:
        peer = self.peers.get(name)
        if peer is None:
            peer = self.peers[name] = Peer(name=name)
        if incarnation > peer.incarnation:
            # a restarted node: everything we learned from its past life
            # is stale
            self._purge_node_state(name)
            peer.route_seq = 0
            peer.durable_seq = 0
            peer.bootstrapped = False
            peer.pending_deltas.clear()
            peer.pending_durable.clear()
        peer.host, peer.port = host, port
        peer.incarnation = incarnation
        if peer.conn is not None and peer.conn is not conn:
            peer.conn.close()
        peer.conn = conn
        conn.node = name
        conn.incarnation = incarnation
        peer.last_seen = time.monotonic()
        if not peer.bootstrapped:
            asyncio.ensure_future(self._bootstrap_from(peer))
        log.info("%s: peer %s up (%s:%d)", self.name, name, host, port)
        return peer

    async def _bootstrap_from(self, peer: Peer) -> None:
        """Pull the peer's own-origin state (mria bootstrap).  Deltas that
        arrive mid-bootstrap are buffered and replayed after the snapshot
        installs (mria's bootstrap-then-replay-rlog ordering)."""
        if peer.conn is None or peer.bootstrapping:
            return
        peer.bootstrapping = True
        try:
            resp = await peer.conn.call(
                pb.ClusterFrame(
                    snapshot_request=pb.SnapshotRequest(requester=self.name)
                ),
                timeout=10.0,
            )
            self._apply_snapshot(resp.snapshot)
            for rd in peer.pending_deltas:
                if rd.to_epoch > peer.route_seq:
                    self._apply_delta_ops(rd)
                    peer.route_seq = rd.to_epoch
            peer.pending_deltas.clear()
            self.durable.replay_pending(peer)
            peer.bootstrapped = True
        except Exception as e:
            log.warning("bootstrap from %s failed: %s", peer.name, e)
        finally:
            peer.bootstrapping = False

    async def _heartbeat_loop(self) -> None:
        while self._running:
            await asyncio.sleep(self.HEARTBEAT_INTERVAL)
            now = time.monotonic()
            for peer in list(self.peers.values()):
                if peer.up:
                    conn = peer.conn
                    conn.cast(pb.ClusterFrame(
                        ping=pb.Ping(epoch=self.broker.router.epoch)
                    ))
                    # cast() may have closed the conn (write-buffer
                    # overflow), nulling peer.conn via _conn_closed
                    if not conn.closed:
                        await conn.drain()
                if now - peer.last_seen > self.NODE_TIMEOUT:
                    self._node_down(peer.name, "heartbeat timeout")

    async def _reconnect_loop(self) -> None:
        while self._running:
            await asyncio.sleep(self.RECONNECT_INTERVAL)
            # re-dial lost peers and unjoined seeds (autoheal)
            for peer in list(self.peers.values()):
                if not peer.up and peer.host:
                    await self._join(peer.host, peer.port)
            known = {(p.host, p.port) for p in self.peers.values()}
            for h, p in self.seeds:
                if (h, p) not in known and (h, p) != (
                    self.listen_host, self.listen_port
                ):
                    await self._join(h, p)

    def _conn_closed(self, conn: PeerConn) -> None:
        if conn.node is None:
            return
        peer = self.peers.get(conn.node)
        if peer is not None and peer.conn is conn:
            peer.conn = None

    def _node_down(self, name: str, reason: str) -> None:
        peer = self.peers.pop(name, None)
        if peer is None:
            return
        if peer.conn is not None:
            peer.conn.close()
        self._purge_node_state(name)
        log.warning("%s: peer %s down (%s): state purged", self.name, name,
                    reason)

    def _purge_node_state(self, name: str) -> None:
        """emqx_router_helper nodedown cleanup: routes, shared members,
        session registry entries owned by the dead node."""
        router = self.broker.router
        router.cleanup_routes(name)
        for flt in list(router.topics()):
            for dest in list(router.routes_of(flt)):
                if isinstance(dest, tuple) and dest[1] == name:
                    router.delete_route(flt, dest)
        shared = self.broker.shared
        for group, flt in list(shared.groups()):
            for clientid, mnode in list(shared.members(group, flt)):
                if mnode == name:
                    shared.unsubscribe(group, flt, clientid, mnode)
        for cid in [c for c, n in self._registry.items() if n == name]:
            del self._registry[cid]

    # ------------------------------------------------------------------
    # route replication
    # ------------------------------------------------------------------

    def _own_origin(self, dest: Any) -> bool:
        return dest == self.name or (
            isinstance(dest, tuple) and dest[1] == self.name
        )

    def _entry(self, flt: str, dest: Any) -> pb.RouteEntry:
        if isinstance(dest, tuple):
            return pb.RouteEntry(
                filter=flt, dest=pb.Dest(node=dest[1], share_group=dest[0])
            )
        return pb.RouteEntry(filter=flt, dest=pb.Dest(node=str(dest)))

    @staticmethod
    def _dest_of(entry: pb.RouteEntry) -> Any:
        if entry.dest.share_group:
            return (entry.dest.share_group, entry.dest.node)
        return entry.dest.node

    async def _sync_loop(self) -> None:
        """Broadcast own-origin route deltas (emqx_router_syncer batching).

        Batches carry this node's own sequence counter in
        ``from_epoch``/``to_epoch``; receivers detect missed batches by
        sequence gap (router epochs are per-node clocks and never cross
        the wire)."""
        while self._running:
            await asyncio.sleep(self.SYNC_INTERVAL)
            router = self.broker.router
            if router.epoch == self._synced_epoch:
                continue
            deltas = router.deltas_since(self._synced_epoch)
            frame = pb.ClusterFrame()
            frame.route_deltas.origin = self.name
            if deltas is None:
                # local delta log overflowed: force a gap so peers
                # re-bootstrap (skip a seq number)
                self._sync_seq += 1
                frame.route_deltas.from_epoch = self._sync_seq
                self._sync_seq += 1
                frame.route_deltas.to_epoch = self._sync_seq
            else:
                own = [d for d in deltas if self._own_origin(d.dest)]
                frame.route_deltas.from_epoch = self._sync_seq
                self._sync_seq += 1
                frame.route_deltas.to_epoch = self._sync_seq
                for d in own:
                    fd = frame.route_deltas.deltas.add()
                    fd.op = (
                        pb.RouteDeltas.Delta.ADD if d.op == "add"
                        else pb.RouteDeltas.Delta.DEL
                    )
                    fd.entry.CopyFrom(self._entry(d.filter, d.dest))
            self._synced_epoch = router.epoch
            for peer in self.peers.values():
                if peer.up:
                    peer.conn.cast(frame)

    def _apply_delta_ops(self, rd: pb.RouteDeltas) -> None:
        router = self.broker.router
        for d in rd.deltas:
            dest = self._dest_of(d.entry)
            if d.op == pb.RouteDeltas.Delta.ADD:
                router.add_route(d.entry.filter, dest)
            else:
                router.delete_route(d.entry.filter, dest)

    def _apply_route_deltas(self, conn: PeerConn, rd: pb.RouteDeltas) -> None:
        peer = self.peers.get(rd.origin)
        if peer is None:
            return
        if peer.bootstrapping:
            # snapshot install in flight: buffer, replay after (in order)
            peer.pending_deltas.append(rd)
            return
        if rd.from_epoch > peer.route_seq:
            # gap (missed batch / origin log overflow): re-bootstrap
            peer.bootstrapped = False
            asyncio.ensure_future(self._bootstrap_from(peer))
            peer.pending_deltas.append(rd)
            return
        if rd.to_epoch <= peer.route_seq:
            return  # duplicate/old batch
        self._apply_delta_ops(rd)
        peer.route_seq = rd.to_epoch

    def _snapshot(self) -> pb.Snapshot:
        # epoch carries our broadcast seq: the table may already contain
        # not-yet-broadcast mutations, whose upcoming batch (from == this
        # seq) then re-applies idempotently on the receiver
        router = self.broker.router
        snap = pb.Snapshot(origin=self.name, epoch=self._sync_seq)
        for flt in router.topics():
            for dest in router.routes_of(flt):
                if self._own_origin(dest):
                    snap.routes.append(self._entry(flt, dest))
        for cid in self.broker.sessions:
            snap.session_clientids.append(cid)
        import json as _json

        for path, value in self.node.config.runtime_overrides().items():
            txn, origin = self._config_versions.get(path, (0, self.name))
            snap.config.append(pb.Snapshot.ConfigEntry(
                path=path, value_json=_json.dumps(value, default=str),
                origin=origin, txn=txn,
            ))
        snap.durable_seq = self.durable._seq
        self.durable.fill_snapshot(snap)
        return snap

    def _apply_snapshot(self, snap: pb.Snapshot) -> None:
        origin = snap.origin
        router = self.broker.router
        # drop everything previously learned from origin, then install
        router.cleanup_routes(origin)
        for flt in list(router.topics()):
            for dest in list(router.routes_of(flt)):
                if isinstance(dest, tuple) and dest[1] == origin:
                    router.delete_route(flt, dest)
        for entry in snap.routes:
            router.add_route(entry.filter, self._dest_of(entry))
        for cid in [c for c, n in self._registry.items() if n == origin]:
            del self._registry[cid]
        for cid in snap.session_clientids:
            self._registry[cid] = origin
        peer = self.peers.get(origin)
        if peer is not None:
            peer.route_seq = snap.epoch
            peer.durable_seq = snap.durable_seq
        self.durable.apply_snapshot(snap)
        # adopt the cluster's hot config state (joiner side of emqx_conf)
        import json as _json

        for entry in snap.config:
            known = self._config_versions.get(entry.path, (0, ""))
            if (entry.txn, entry.origin) <= known:
                continue  # we already hold this or a NEWER value
            self._config_versions[entry.path] = (entry.txn, entry.origin)
            self._applying_remote_config = True
            try:
                self.node.config.put(entry.path,
                                     _json.loads(entry.value_json))
            except Exception:
                log.exception("snapshot config %s apply failed", entry.path)
            finally:
                self._applying_remote_config = False

    # ------------------------------------------------------------------
    # forwarding (broker seams)
    # ------------------------------------------------------------------

    def _forward(self, node: str, flt: str, msg: Message) -> bool:
        peer = self.peers.get(node)
        if peer is None or not peer.up:
            self.broker.hooks.run("message.dropped", (msg, "forward_no_peer"))
            return False
        peer.conn.cast(pb.ClusterFrame(forward=pb.Forward(
            origin=self.name, filter=flt, message=_wire_msg(msg),
        )))
        self.forwards_out += 1
        return True

    def _forward_shared(
        self, node: str, group: str, flt: str, msg: Message
    ) -> bool:
        """Returns False when the peer is unreachable so the broker's
        shared dispatch can try another group member instead of silently
        losing the message."""
        peer = self.peers.get(node)
        if peer is None or not peer.up:
            return False
        peer.conn.cast(pb.ClusterFrame(shared_forward=pb.SharedForward(
            origin=self.name, group=group, filter=flt,
            message=_wire_msg(msg),
        )))
        self.forwards_out += 1
        return True

    # ------------------------------------------------------------------
    # session registry + takeover
    # ------------------------------------------------------------------

    def _broadcast_session_op(self, clientid: str, op) -> None:
        frame = pb.ClusterFrame(session_op=pb.SessionOp(
            origin=self.name, op=op, clientid=clientid,
        ))
        for peer in self.peers.values():
            if peer.up:
                peer.conn.cast(frame)

    def owner_of(self, clientid: str) -> Optional[str]:
        """Which remote node (if any) currently owns this clientid."""
        return self._registry.get(clientid)

    async def prepare_connect(self, pkt: Any) -> None:
        """Pre-CONNECT stage: if the clientid's session lives on another
        node, pull it over (resume) or have it discarded (clean start) —
        the cross-node half of emqx_cm:open_session (SURVEY.md §3.2)."""
        cid = pkt.clientid
        if not cid or cid in self.broker.sessions:
            return
        owner = self._registry.get(cid)
        if owner is None:
            # no live owner on record: a dead node's durable replica may
            # still hold the session — promote it here (emqx_ds failover)
            self.durable.maybe_promote(cid, pkt.clean_start)
            return
        peer = self.peers.get(owner)
        if peer is None or not peer.up:
            self._registry.pop(cid, None)
            self.durable.maybe_promote(cid, pkt.clean_start)
            return
        try:
            resp = await peer.conn.call(
                pb.ClusterFrame(takeover_request=pb.TakeoverRequest(
                    requester=self.name, clientid=cid,
                )),
                timeout=5.0,
            )
        except Exception as e:
            log.warning("takeover of %s from %s failed: %s", cid, owner, e)
            return
        self._registry.pop(cid, None)
        reply = resp.takeover_reply
        if not reply.present or pkt.clean_start:
            return
        # install the migrated session; the channel's CONNECT handling
        # then resumes it (session_present=True)
        sess, _ = self.broker.open_session(
            cid, clean_start=False,
            expiry_interval=reply.expiry_interval,
        )
        sess.connected = False
        for s in reply.subscriptions:
            opts = SubOpts(
                qos=s.qos, nl=s.nl, rap=s.rap, rh=s.rh,
                subid=s.subid if s.subid >= 0 else None,
            )
            try:
                self.broker.subscribe(cid, s.filter, opts)
            except Exception:
                log.exception("takeover: resubscribe %r failed", s.filter)
        if reply.pending:
            sess.deliver([_from_wire(w) for w in reply.pending])

    def _handle_takeover(self, req: pb.TakeoverRequest) -> pb.TakeoverReply:
        cid = req.clientid
        sess = self.broker.sessions.get(cid)
        if sess is None:
            return pb.TakeoverReply(present=False)
        reply = pb.TakeoverReply(
            present=True, expiry_interval=sess.expiry_interval
        )
        for flt, opts in sess.subscriptions.items():
            reply.subscriptions.append(pb.SessionSub(
                filter=flt, qos=opts.qos, nl=opts.nl, rap=opts.rap,
                rh=opts.rh, subid=opts.subid if opts.subid is not None else -1,
            ))
        for msg in sess.pending_messages():
            reply.pending.append(_wire_msg(msg))
        self.broker.hooks.run("session.takenover", (cid,))
        # displace the live connection (if any), then discard local state —
        # unsubscribes fire route deltas so peers drop our routes
        conn = self.node.connections.get(cid)
        if conn is not None:
            conn.kick("takeover")
        self.broker.close_session(cid, discard=True)
        return reply

    # ------------------------------------------------------------------
    # frame dispatch
    # ------------------------------------------------------------------

    async def _handle(
        self, conn: PeerConn, frame: pb.ClusterFrame
    ) -> Optional[pb.ClusterFrame]:
        kind = frame.WhichOneof("msg")
        if conn.node is not None:
            peer = self.peers.get(conn.node)
            if peer is not None:
                peer.last_seen = time.monotonic()
        if kind == "hello":
            h = frame.hello
            if h.node == self.name or h.cluster_name != self.cluster_name:
                return pb.ClusterFrame(hello_ack=pb.HelloAck(
                    node=self.name, incarnation=self.incarnation,
                    accepted=False, reason="name conflict or wrong cluster",
                ))
            ack = pb.ClusterFrame(hello_ack=pb.HelloAck(
                node=self.name, incarnation=self.incarnation, accepted=True,
            ))
            for p in self.peers.values():
                if p.name != h.node and p.host:
                    ack.hello_ack.peers.append(pb.PeerInfo(
                        node=p.name, host=p.host, port=p.port,
                    ))
            self._peer_up(
                h.node, h.listen_host, h.listen_port, conn, h.incarnation
            )
            return ack
        if kind == "ping":
            return None  # last_seen refreshed above; no pong needed (TCP)
        if kind == "leave":
            self._node_down(frame.leave.node, "leave")
            return None
        if kind == "route_deltas":
            self._apply_route_deltas(conn, frame.route_deltas)
            return None
        if kind == "snapshot_request":
            return pb.ClusterFrame(snapshot=self._snapshot())
        if kind == "forward":
            f = frame.forward
            n = self.broker.dispatch_remote(f.filter, _from_wire(f.message))
            self.forwards_in += 1
            if f.want_ack:
                return pb.ClusterFrame(forward_ack=pb.ForwardAck(dispatched=n))
            return None
        if kind == "shared_forward":
            f = frame.shared_forward
            self.broker.dispatch_shared_remote(
                f.group, f.filter, _from_wire(f.message)
            )
            self.forwards_in += 1
            return None
        if kind == "session_op":
            op = frame.session_op
            if op.op == pb.SessionOp.ADD:
                self._registry[op.clientid] = op.origin
            elif self._registry.get(op.clientid) == op.origin:
                del self._registry[op.clientid]
            return None
        if kind == "config_update":
            self._apply_config_update(frame.config_update)
            return None
        if kind == "durable_deltas":
            self.durable.apply_deltas(frame.durable_deltas)
            return None
        if kind == "takeover_request":
            return pb.ClusterFrame(
                takeover_reply=self._handle_takeover(frame.takeover_request)
            )
        log.debug("unhandled cluster frame kind %r", kind)
        return None

    # ------------------------------------------------------------------

    def info(self) -> dict:
        return {
            "name": self.name,
            "listen": f"{self.listen_host}:{self.listen_port}",
            "incarnation": self.incarnation,
            "peers": {
                p.name: {
                    "up": p.up, "host": p.host, "port": p.port,
                    "route_seq": p.route_seq,
                    "bootstrapped": p.bootstrapped,
                    "overflow_closes": (
                        p.conn.overflow_closes if p.conn is not None else 0
                    ),
                }
                for p in self.peers.values()
            },
            "registry_size": len(self._registry),
            "forwards_out": self.forwards_out,
            "forwards_in": self.forwards_in,
            "durable": self.durable.info(),
        }
