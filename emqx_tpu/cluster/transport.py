"""Peer transport: length-prefixed protobuf frames over asyncio TCP.

The gen_rpc analog (SURVEY.md §2.2): every node pair gets a dedicated
stream (dial side reuses one connection), so bulk message forwarding
never head-of-line-blocks the control traffic the way a single Erlang
dist channel would.  ``call`` correlates a reply via the frame ``seq`` /
``reply_to`` pair; ``cast`` is fire-and-forget (the QoS0 forwarding
path).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import Awaitable, Callable, Dict, Optional

from .. import faultinject as _fi
from . import cluster_pb2 as pb

log = logging.getLogger(__name__)

__all__ = ["PeerConn", "PeerServer", "pb"]

_LEN = struct.Struct(">I")
MAX_FRAME = 64 << 20
# cast() backpressure bound: a stalled peer must not grow the transport
# write buffer without limit.  Exceeding it closes the conn — the
# reconnect loop re-bootstraps state, which is strictly safer than
# silently dropping individual route-sync / forward frames.
MAX_WRITE_BUFFER = 8 << 20

# handler(conn, frame) -> Optional[reply frame]
Handler = Callable[["PeerConn", pb.ClusterFrame], Awaitable[Optional[pb.ClusterFrame]]]


class PeerConn:
    """One framed stream to a peer; owned by whichever side dialled or
    accepted it.  ``node`` is filled in after the Hello handshake."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Handler,
        on_closed: Optional[Callable[["PeerConn"], None]] = None,
    ) -> None:
        self._r = reader
        self._w = writer
        self._handler = handler
        self._on_closed = on_closed
        self.node: Optional[str] = None   # peer's node name (post-Hello)
        self.incarnation: int = 0
        self._seq = itertools.count(1)
        self._waiting: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._task: Optional[asyncio.Task] = None
        self.overflow_closes = 0  # times cast() hit MAX_WRITE_BUFFER

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._recv_loop())

    # ------------------------------------------------------------------

    def cast(self, frame: pb.ClusterFrame) -> None:
        """Fire-and-forget send, bounded: if the peer stalls past
        MAX_WRITE_BUFFER of queued bytes the conn is closed (and the
        owner's reconnect loop re-bootstraps), never buffered unbounded."""
        if self._closed:
            return
        if _fi._injector is not None:
            # chaos seam: drop one cluster frame on the floor (the
            # replication seq-gap / heartbeat machinery must heal it)
            # or fail the link outright (reconnect loop re-bootstraps)
            act = _fi._injector.act("cluster.rpc")
            if act == "drop":
                return
            if act == "raise":
                self.close()
                return
        try:
            transport = self._w.transport
            if (
                transport is not None
                and transport.get_write_buffer_size() > MAX_WRITE_BUFFER
            ):
                self.overflow_closes += 1
                log.warning(
                    "peer %s write buffer over %d bytes; closing",
                    self.node, MAX_WRITE_BUFFER,
                )
                self.close()
                return
            data = frame.SerializeToString()
            self._w.write(_LEN.pack(len(data)) + data)
        except Exception:
            self.close()

    async def call(
        self, frame: pb.ClusterFrame, timeout: float = 5.0
    ) -> pb.ClusterFrame:
        """Request/response: assigns a seq, awaits the matching reply."""
        if self._closed:
            raise ConnectionError("peer connection closed")
        seq = next(self._seq)
        frame.seq = seq
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiting[seq] = fut
        try:
            self.cast(frame)
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._waiting.pop(seq, None)

    def reply(self, req: pb.ClusterFrame, resp: pb.ClusterFrame) -> None:
        resp.reply_to = req.seq
        self.cast(resp)

    async def drain(self) -> None:
        try:
            await self._w.drain()
        except ConnectionError:
            self.close()

    # ------------------------------------------------------------------

    async def _recv_loop(self) -> None:
        try:
            while not self._closed:
                hdr = await self._r.readexactly(_LEN.size)
                (n,) = _LEN.unpack(hdr)
                if n > MAX_FRAME:
                    raise ConnectionError(f"frame too large: {n}")
                data = await self._r.readexactly(n)
                frame = pb.ClusterFrame.FromString(data)
                if frame.reply_to:
                    fut = self._waiting.pop(frame.reply_to, None)
                    if fut is not None and not fut.done():
                        fut.set_result(frame)
                    continue
                try:
                    resp = await self._handler(self, frame)
                except Exception:
                    log.exception("peer frame handler failed (%s)", self.node)
                    continue
                if resp is not None and frame.seq:
                    self.reply(frame, resp)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass  # peer socket died: normal churn — the finally below
            #     runs the close path and the reconnect loop heals it
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("peer recv loop crashed (%s)", self.node)
        finally:
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fut in self._waiting.values():
            if not fut.done():
                fut.set_exception(ConnectionError("peer connection closed"))
        self._waiting.clear()
        try:
            self._w.close()
        except Exception:
            log.debug("peer transport close failed", exc_info=True)
        if self._on_closed is not None:
            self._on_closed(self)

    @property
    def closed(self) -> bool:
        return self._closed

    def peername(self):
        return self._w.get_extra_info("peername")


class PeerServer:
    """Accepts inbound peer streams."""

    def __init__(
        self,
        host: str,
        port: int,
        handler: Handler,
        on_closed: Optional[Callable[[PeerConn], None]] = None,
    ) -> None:
        self.host = host
        self.port = port
        self._handler = handler
        self._on_closed = on_closed
        self._server: Optional[asyncio.AbstractServer] = None
        self.conns: list = []

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._accept, self.host, self.port
        )
        socks = self._server.sockets or []
        if socks and self.port == 0:
            self.port = socks[0].getsockname()[1]

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = PeerConn(reader, writer, self._handler, self._on_closed)
        self.conns.append(conn)
        conn.start()
        try:
            await conn._task  # keep the accept handler alive for wait_closed
        finally:
            # reconnect churn must not leak closed conns for the life of
            # the server
            try:
                self.conns.remove(conn)
            except ValueError:
                pass

    async def stop(self) -> None:
        for conn in list(self.conns):
            conn.close()
        self.conns.clear()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5.0)
            except asyncio.TimeoutError:
                pass
            self._server = None


async def dial(
    host: str,
    port: int,
    handler: Handler,
    on_closed: Optional[Callable[[PeerConn], None]] = None,
    timeout: float = 5.0,
) -> PeerConn:
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout
    )
    conn = PeerConn(reader, writer, handler, on_closed)
    conn.start()
    return conn
