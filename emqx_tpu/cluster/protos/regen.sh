#!/bin/sh
# Regenerate cluster_pb2.py from cluster.proto (plain protoc).
cd "$(dirname "$0")/../../.." || exit 1
exec protoc --python_out=emqx_tpu/cluster -Iemqx_tpu/cluster/protos \
    emqx_tpu/cluster/protos/cluster.proto
