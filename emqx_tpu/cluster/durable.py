"""Durable-state replication: retained messages + persistent sessions
survive node loss.

The ``emqx_ds`` generations/replication analog (SURVEY.md §5.4; 5.x
``emqx_persistent_session_ds`` + ``emqx_retainer_mnesia`` replicated
tables [U]) rebuilt on the existing cluster delta channel:

* **retained messages** become a fully replicated table: every local
  store/delete broadcasts a ``DurableOp`` on the peer stream; receivers
  apply it into their OWN retainer (last-writer-wins by message
  timestamp, deletions remembered as TTL'd tombstones so a lagging put
  cannot resurrect a deleted topic).  Every node then serves
  subscribe-replay locally — exactly the mnesia table semantics —
  and the existing per-node :class:`~emqx_tpu.storage.persistence.
  Persistence` makes the replica durable on each node's disk.
* **persistent sessions** (clean_start=false or expiry>0) ship as
  passive replicas: the owning node diffs+broadcasts its durable
  sessions' serialized state (``session_to_dict``) every
  ``SYNC_INTERVAL``; peers hold ``{clientid: (ts, state)}``.  When the
  owner is GONE (nodedown/partition) and the client reconnects
  elsewhere, the receiving node PROMOTES its replica — resubscribing
  (which re-feeds routes and the device mirror) and redelivering
  pending messages.  While the owner is alive, the ordinary takeover
  protocol runs instead; promotion during a partition can briefly
  double-own a session, resolved by the same last-writer-wins shipping
  once the partition heals (the autoheal trade the reference makes).

Sequencing and bootstrap reuse the route-replication discipline: own
sequence counter per origin, gap ⇒ re-bootstrap via the ordinary
Snapshot (which carries retained + durable sessions + tombstones).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from ..storage.codec import session_restore, session_to_dict
from . import cluster_pb2 as pb

log = logging.getLogger(__name__)

__all__ = ["DurableReplicator"]


class DurableReplicator:
    SYNC_INTERVAL = 0.5
    TOMBSTONE_TTL = 3600.0

    def __init__(self, cluster: Any,
                 restored_replicas: Optional[Dict[str, Tuple[float, dict]]]
                 = None) -> None:
        self.cluster = cluster
        self.node = cluster.node
        self.broker = cluster.broker
        self._seq = 0
        self._pending: List[pb.DurableOp] = []
        # clientid -> (lww_ts, session_to_dict state) for sessions OWNED
        # BY PEERS; promoted on reconnect when the owner is gone
        self.session_replicas: Dict[str, Tuple[float, dict]] = dict(
            restored_replicas or {})
        # deletion tombstones, SEPARATE per namespace: a terminated
        # session's clientid must never shadow a retained topic of the
        # same name (and vice versa)
        self._retain_tombstones: Dict[str, float] = {}
        self._session_tombstones: Dict[str, float] = {}
        self._shipped: Dict[str, str] = {}        # cid -> last shipped json
        # sessions whose state changed since the last flush (fed by the
        # broker hooks); bounds the per-flush serialization work to what
        # actually changed instead of O(all session state) every 0.5 s
        self._dirty: set = set()
        self._flushes = 0
        self.FULL_RESCAN_EVERY = 20   # safety-net sweep for missed signals
        self._applying_remote = False
        self.promotions = 0

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------

    _DIRTY_HOOKS = ("session.created", "session.resumed",
                    "session.subscribed", "session.unsubscribed",
                    "message.delivered", "message.acked")

    def attach(self) -> None:
        if self.node.retainer is not None:
            self.node.retainer.on_change = self._on_retained_change
        self.broker.hooks.add(
            "session.terminated", self._on_session_terminated,
            name="cluster.durable.terminated")
        for point in self._DIRTY_HOOKS:
            self.broker.hooks.add(
                point, self._mark_dirty, name=f"cluster.durable.{point}")

    def _mark_dirty(self, clientid, *_a) -> None:
        self._dirty.add(clientid)

    def detach(self) -> None:
        if self.node.retainer is not None \
                and self.node.retainer.on_change == self._on_retained_change:
            self.node.retainer.on_change = None
        self.broker.hooks.delete(
            "session.terminated", "cluster.durable.terminated")
        for point in self._DIRTY_HOOKS:
            self.broker.hooks.delete(point, f"cluster.durable.{point}")

    # ------------------------------------------------------------------
    # local mutations -> queued ops
    # ------------------------------------------------------------------

    def _on_retained_change(self, topic: str, msg) -> None:
        if self._applying_remote:
            return
        now = time.time()
        if msg is None:
            self._retain_tombstones[topic] = now
            self._pending.append(pb.DurableOp(
                kind=pb.DurableOp.RETAIN_DEL, key=topic, ts=now))
        else:
            self._retain_tombstones.pop(topic, None)
            from .cluster import _wire_msg

            self._pending.append(pb.DurableOp(
                kind=pb.DurableOp.RETAIN_PUT, key=topic,
                message=_wire_msg(msg),
                ts=float(getattr(msg, "timestamp", 0.0) or now)))

    def _on_session_terminated(self, clientid: str) -> None:
        if self._applying_remote or clientid not in self._shipped:
            return
        self._shipped.pop(clientid, None)
        self._dirty.discard(clientid)
        now = time.time()
        self._session_tombstones[clientid] = now
        self._pending.append(pb.DurableOp(
            kind=pb.DurableOp.SESSION_DEL, key=clientid, ts=now))

    def _durable_sessions(self):
        for cid, sess in self.broker.sessions.items():
            if not sess.clean_start or sess.expiry_interval > 0:
                yield cid, sess

    def _collect_session_changes(self) -> None:
        now = time.time()
        self._flushes += 1
        full = self._flushes % self.FULL_RESCAN_EVERY == 0
        dirty, self._dirty = self._dirty, set()
        for cid, sess in list(self._durable_sessions()):
            # serialize only never-shipped, hook-flagged, or (on the
            # periodic safety-net sweep) every durable session
            if not full and cid in self._shipped and cid not in dirty:
                continue
            try:
                j = json.dumps(session_to_dict(sess), sort_keys=True,
                               default=str)
            except Exception:
                log.exception("serialize session %r failed", cid)
                continue
            if self._shipped.get(cid) != j:
                self._shipped[cid] = j
                self._session_tombstones.pop(cid, None)
                self._pending.append(pb.DurableOp(
                    kind=pb.DurableOp.SESSION_PUT, key=cid,
                    session_json=j, ts=now))

    # ------------------------------------------------------------------
    # broadcast loop
    # ------------------------------------------------------------------

    async def loop(self) -> None:
        while self.cluster._running:
            await asyncio.sleep(self.SYNC_INTERVAL)
            try:
                self.flush()
            except Exception:
                log.exception("durable flush failed")

    def flush(self) -> None:
        """Diff durable sessions, then broadcast every queued op as one
        sequenced batch (no-op when nothing changed)."""
        self._collect_session_changes()
        self._prune_tombstones()
        if not self._pending:
            return
        ops, self._pending = self._pending, []
        frame = pb.ClusterFrame()
        frame.durable_deltas.origin = self.cluster.name
        frame.durable_deltas.from_seq = self._seq
        self._seq += 1
        frame.durable_deltas.to_seq = self._seq
        for op in ops:
            frame.durable_deltas.ops.add().CopyFrom(op)
        for peer in self.cluster.peers.values():
            if peer.up:
                peer.conn.cast(frame)

    def _prune_tombstones(self) -> None:
        cut = time.time() - self.TOMBSTONE_TTL
        for tombs in (self._retain_tombstones, self._session_tombstones):
            for k in [k for k, ts in tombs.items() if ts < cut]:
                del tombs[k]

    # ------------------------------------------------------------------
    # receive path
    # ------------------------------------------------------------------

    def apply_deltas(self, dd: pb.DurableDeltas) -> None:
        """Same gap discipline as route deltas: buffer during bootstrap,
        re-bootstrap on a sequence gap, drop duplicates."""
        peer = self.cluster.peers.get(dd.origin)
        if peer is None:
            return
        if peer.bootstrapping:
            peer.pending_durable.append(dd)
            return
        if dd.from_seq > peer.durable_seq:
            peer.bootstrapped = False
            peer.pending_durable.append(dd)
            asyncio.ensure_future(self.cluster._bootstrap_from(peer))
            return
        if dd.to_seq <= peer.durable_seq:
            return
        for op in dd.ops:
            self._apply_op(op)
        peer.durable_seq = dd.to_seq

    def replay_pending(self, peer) -> None:
        """Post-bootstrap replay (called by Cluster._bootstrap_from)."""
        for dd in peer.pending_durable:
            if dd.to_seq > peer.durable_seq:
                for op in dd.ops:
                    self._apply_op(op)
                peer.durable_seq = dd.to_seq
        peer.pending_durable.clear()

    def _apply_op(self, op: pb.DurableOp) -> None:
        key, ts = op.key, op.ts
        if op.kind == pb.DurableOp.RETAIN_PUT:
            self._apply_retain_put(key, ts, wire=op.message)
        elif op.kind == pb.DurableOp.RETAIN_DEL:
            self._apply_retain_del(key, ts)
        elif op.kind == pb.DurableOp.SESSION_PUT:
            try:
                state = json.loads(op.session_json)
            except Exception:
                log.debug("durable op for %s carried undecodable "
                          "session state", key, exc_info=True)
                return
            self._apply_session_put(key, ts, state)
        elif op.kind == pb.DurableOp.SESSION_DEL:
            cur = self.session_replicas.get(key)
            if cur is None or cur[0] <= ts:
                self.session_replicas.pop(key, None)
            self._session_tombstones[key] = max(
                self._session_tombstones.get(key, 0.0), ts)

    def _apply_retain_put(self, topic: str, ts: float, wire) -> None:
        ret = self.node.retainer
        if ret is None:
            return
        if self._retain_tombstones.get(topic, -1.0) >= ts:
            return                        # deleted later than this put
        cur = ret.get(topic)
        if cur is not None and (cur.timestamp or 0.0) > ts:
            return                        # local copy is newer (LWW)
        from .cluster import _from_wire

        msg = _from_wire(wire)
        self._applying_remote = True
        try:
            ret.insert(msg.clone(retain=True))
        finally:
            self._applying_remote = False

    def _apply_retain_del(self, topic: str, ts: float) -> None:
        ret = self.node.retainer
        if ret is None:
            return
        cur = ret.get(topic)
        if cur is not None and (cur.timestamp or 0.0) > ts:
            return                        # a newer put wins over this del
        self._retain_tombstones[topic] = max(
            self._retain_tombstones.get(topic, 0.0), ts)
        self._applying_remote = True
        try:
            ret.delete(topic)
        finally:
            self._applying_remote = False

    def _apply_session_put(self, cid: str, ts: float, state: dict) -> None:
        if cid in self.broker.sessions:
            return                        # we own the live session
        if self._session_tombstones.get(cid, -1.0) >= ts:
            return
        cur = self.session_replicas.get(cid)
        if cur is not None and cur[0] >= ts:
            return
        self.session_replicas[cid] = (ts, state)

    # ------------------------------------------------------------------
    # snapshot integration
    # ------------------------------------------------------------------

    def fill_snapshot(self, snap: pb.Snapshot) -> None:
        from .cluster import _wire_msg

        ret = self.node.retainer
        if ret is not None:
            for topic in ret.topics():
                m = ret.get(topic)
                if m is not None:
                    snap.retained.append(pb.Snapshot.RetainedEntry(
                        message=_wire_msg(m),
                        ts=float(m.timestamp or 0.0)))
        now = time.time()
        for cid, sess in self._durable_sessions():
            try:
                snap.durable_sessions.append(pb.Snapshot.DurableSession(
                    clientid=cid,
                    session_json=json.dumps(session_to_dict(sess),
                                            default=str),
                    ts=now))
            except Exception:
                log.exception("snapshot session %r failed", cid)
        for key, ts in self._retain_tombstones.items():
            snap.durable_tombstones.append(pb.Snapshot.Tombstone(
                key=key, ts=ts, kind=pb.DurableOp.RETAIN_DEL))
        for key, ts in self._session_tombstones.items():
            snap.durable_tombstones.append(pb.Snapshot.Tombstone(
                key=key, ts=ts, kind=pb.DurableOp.SESSION_DEL))

    def apply_snapshot(self, snap: pb.Snapshot) -> None:
        for t in snap.durable_tombstones:
            if t.kind == pb.DurableOp.SESSION_DEL:
                if self._session_tombstones.get(t.key, 0.0) < t.ts:
                    cur = self.session_replicas.get(t.key)
                    if cur is not None and cur[0] <= t.ts:
                        del self.session_replicas[t.key]
                    self._session_tombstones[t.key] = t.ts
            elif self._retain_tombstones.get(t.key, 0.0) < t.ts:
                self._apply_retain_del(t.key, t.ts)
        for entry in snap.retained:
            self._apply_retain_put(entry.message.topic, entry.ts,
                                   wire=entry.message)
        for ds in snap.durable_sessions:
            try:
                state = json.loads(ds.session_json)
            except Exception:
                log.debug("durable snapshot carried undecodable session "
                          "state for %s", ds.clientid, exc_info=True)
                continue
            self._apply_session_put(ds.clientid, ds.ts, state)

    # ------------------------------------------------------------------
    # promotion (owner gone, client reconnected here)
    # ------------------------------------------------------------------

    def maybe_promote(self, clientid: str, clean_start: bool) -> bool:
        """Restore the replica of a dead owner's durable session into
        THIS broker (resubscribe + redeliver pending).  For clean-start
        connects the replica is discarded cluster-wide instead."""
        rep = self.session_replicas.get(clientid)
        if rep is None:
            return False
        now = time.time()
        if clean_start:
            del self.session_replicas[clientid]
            self._session_tombstones[clientid] = now
            self._pending.append(pb.DurableOp(
                kind=pb.DurableOp.SESSION_DEL, key=clientid, ts=now))
            return False
        try:
            sess = session_restore(self.broker, rep[1])
        except Exception:
            # keep the replica: a transient restore failure must not
            # destroy the only surviving copy of the session
            log.exception("promote session %r failed", clientid)
            return False
        self.session_replicas.pop(clientid, None)
        if sess is not None:
            sess.connected = False
        self.promotions += 1
        log.info("%s: promoted durable session %r from replica",
                 self.cluster.name, clientid)
        return True

    def info(self) -> dict:
        return {
            "session_replicas": len(self.session_replicas),
            "tombstones": len(self._retain_tombstones)
            + len(self._session_tombstones),
            "promotions": self.promotions,
            "seq": self._seq,
        }
