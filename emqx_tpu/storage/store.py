"""Durable storage engine — the ``emqx_ds`` / mnesia-disc analog.

Behavioral reference (SURVEY.md §2.1 persistent session, §5.4): the
reference persists retained messages, sessions, banned and delayed
tables in mnesia ``disc_copies`` (4.x) or RocksDB via ``emqx_ds``
(5.4+), with *generations* — immutable snapshot + append log — per
shard.  This is the same log-structured shape in plain files:

* one directory per table;
* ``snapshot.jsonl`` — the compacted key/value state (one record per
  line, crash-tolerant: a torn tail line is dropped on load);
* ``wal.jsonl`` — puts/deletes appended since the snapshot, replayed
  over it on open (bootstrap-then-replay, the same discipline as the
  mria rlog and the device NFA mirror);
* compaction rewrites the snapshot atomically (tmp + rename) and
  truncates the wal once it outgrows the snapshot.

Values are JSON-safe dicts; binary fields ride base64 via the codec
helpers in :mod:`emqx_tpu.storage.codec`.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Iterator, Optional, Tuple

log = logging.getLogger(__name__)

__all__ = ["Store", "Table"]


class Table:
    """One persistent key→value table (snapshot + wal)."""

    def __init__(
        self,
        path: str,
        compact_ratio: float = 2.0,
        fsync_interval_s: float = 0.0,
    ) -> None:
        """``fsync_interval_s`` bounds the durability window of WAL
        appends: 0 (default) fsyncs every append — a crash loses at most
        the torn tail line; ``t > 0`` fsyncs at most once per ``t``
        seconds (the documented loss bound is then one interval's worth
        of appends, the RocksDB ``bytes_per_sync`` trade the reference's
        ``emqx_durable_storage`` makes [U])."""
        self.path = path
        self.compact_ratio = compact_ratio
        self.fsync_interval_s = fsync_interval_s
        os.makedirs(path, exist_ok=True)
        self._snap_path = os.path.join(path, "snapshot.jsonl")
        self._wal_path = os.path.join(path, "wal.jsonl")
        self._data: Dict[str, Any] = {}
        self._wal_records = 0
        self._wal = None
        self._last_fsync = 0.0
        self._load()

    # -- open / replay -------------------------------------------------

    def _read_lines(self, path: str) -> Iterator[Tuple[str, Any]]:
        if not os.path.exists(path):
            return
        with open(path, "r", encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    # torn tail write from a crash: drop the remainder
                    log.warning("%s: dropping torn record", path)
                    return
                yield rec.get("op", "put"), rec

    def _load(self) -> None:
        for _op, rec in self._read_lines(self._snap_path):
            self._data[rec["k"]] = rec["v"]
        for op, rec in self._read_lines(self._wal_path):
            if op == "put":
                self._data[rec["k"]] = rec["v"]
            else:
                self._data.pop(rec["k"], None)
            self._wal_records += 1
        self._wal = open(self._wal_path, "a", encoding="utf-8")

    # -- mutation ------------------------------------------------------

    def put(self, key: str, value: Any) -> None:
        self._data[key] = value
        self._append({"op": "put", "k": key, "v": value})

    def delete(self, key: str) -> bool:
        existed = self._data.pop(key, None) is not None
        if existed:
            self._append({"op": "del", "k": key})
        return existed

    def write_batch(
        self, puts: Dict[str, Any], dels: Optional[list] = None
    ) -> None:
        """Apply many mutations with ONE flush+fsync at the end —
        identical durability for a reconciliation pass (the caller acks
        nothing until the whole batch returns) at 1/N the fsync cost."""
        for k, v in puts.items():
            self._data[k] = v
            self._wal.write(
                json.dumps({"op": "put", "k": k, "v": v},
                           separators=(",", ":")) + "\n")
            self._wal_records += 1
        for k in dels or ():
            # key-membership, not value truthiness: a stored None value
            # must still produce a del record or it resurrects on replay
            if k in self._data:
                del self._data[k]
                self._wal.write(
                    json.dumps({"op": "del", "k": k},
                               separators=(",", ":")) + "\n")
                self._wal_records += 1
        self._wal.flush()
        os.fsync(self._wal.fileno())
        if self._wal_records > max(64, self.compact_ratio * len(self._data)):
            self.compact()

    def _append(self, rec: Dict[str, Any]) -> None:
        self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.flush()
        # durability: fsync per append (default), or rate-limited with a
        # bounded loss window (VERDICT.md round-2 weak item 6)
        if self.fsync_interval_s <= 0:
            os.fsync(self._wal.fileno())
        else:
            import time as _time

            now = _time.monotonic()
            if now - self._last_fsync >= self.fsync_interval_s:
                os.fsync(self._wal.fileno())
                self._last_fsync = now
        self._wal_records += 1
        if self._wal_records > max(64, self.compact_ratio * len(self._data)):
            self.compact()

    # -- read ----------------------------------------------------------

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def items(self):
        return self._data.items()

    def keys(self):
        return list(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: str) -> bool:
        return key in self._data

    # -- maintenance ---------------------------------------------------

    def compact(self) -> None:
        """Rewrite the snapshot atomically; reset the wal."""
        tmp = self._snap_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for k, v in self._data.items():
                f.write(json.dumps({"k": k, "v": v},
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        # make the rename itself durable BEFORE truncating the wal —
        # otherwise a power cut can surface the old snapshot beside an
        # empty wal, losing fsync-acked writes
        dfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._wal.close()
        self._wal = open(self._wal_path, "w", encoding="utf-8")
        self._wal_records = 0

    def close(self) -> None:
        if self._wal is not None:
            self.compact()
            self._wal.close()
            self._wal = None

    def clear(self) -> None:
        self._data.clear()
        self.compact()


class Store:
    """Directory of named tables under the node's data dir."""

    def __init__(self, data_dir: str, fsync_interval_s: float = 0.0) -> None:
        self.data_dir = data_dir
        self.fsync_interval_s = fsync_interval_s
        os.makedirs(data_dir, exist_ok=True)
        self._tables: Dict[str, Table] = {}

    def table(self, name: str) -> Table:
        t = self._tables.get(name)
        if t is None:
            t = self._tables[name] = Table(
                os.path.join(self.data_dir, name),
                fsync_interval_s=self.fsync_interval_s,
            )
        return t

    def close(self) -> None:
        for t in self._tables.values():
            t.close()
        self._tables.clear()

    def table_names(self):
        return list(self._tables)
