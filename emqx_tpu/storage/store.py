"""Durable storage engine — the ``emqx_ds`` / mnesia-disc analog.

Behavioral reference (SURVEY.md §2.1 persistent session, §5.4): the
reference persists retained messages, sessions, banned and delayed
tables in mnesia ``disc_copies`` (4.x) or RocksDB via ``emqx_ds``
(5.4+), with *generations* — immutable snapshot + append log — per
shard.  This is the same log-structured shape in plain files:

* one directory per table;
* ``snapshot.jsonl`` — the compacted key/value state (one record per
  line, crash-tolerant: a torn tail line is dropped on load);
* ``wal.jsonl`` — puts/deletes appended since the snapshot, replayed
  over it on open (bootstrap-then-replay, the same discipline as the
  mria rlog and the device NFA mirror);
* compaction rewrites the snapshot atomically (tmp + rename) and
  truncates the wal once it outgrows the snapshot.

Values are JSON-safe dicts; binary fields ride base64 via the codec
helpers in :mod:`emqx_tpu.storage.codec`.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Iterator, Optional, Tuple

log = logging.getLogger(__name__)

__all__ = ["Store", "Table"]


class Table:
    """One persistent key→value table (snapshot + wal)."""

    def __init__(self, path: str, compact_ratio: float = 2.0) -> None:
        self.path = path
        self.compact_ratio = compact_ratio
        os.makedirs(path, exist_ok=True)
        self._snap_path = os.path.join(path, "snapshot.jsonl")
        self._wal_path = os.path.join(path, "wal.jsonl")
        self._data: Dict[str, Any] = {}
        self._wal_records = 0
        self._wal = None
        self._load()

    # -- open / replay -------------------------------------------------

    def _read_lines(self, path: str) -> Iterator[Tuple[str, Any]]:
        if not os.path.exists(path):
            return
        with open(path, "r", encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    # torn tail write from a crash: drop the remainder
                    log.warning("%s: dropping torn record", path)
                    return
                yield rec.get("op", "put"), rec

    def _load(self) -> None:
        for _op, rec in self._read_lines(self._snap_path):
            self._data[rec["k"]] = rec["v"]
        for op, rec in self._read_lines(self._wal_path):
            if op == "put":
                self._data[rec["k"]] = rec["v"]
            else:
                self._data.pop(rec["k"], None)
            self._wal_records += 1
        self._wal = open(self._wal_path, "a", encoding="utf-8")

    # -- mutation ------------------------------------------------------

    def put(self, key: str, value: Any) -> None:
        self._data[key] = value
        self._append({"op": "put", "k": key, "v": value})

    def delete(self, key: str) -> bool:
        existed = self._data.pop(key, None) is not None
        if existed:
            self._append({"op": "del", "k": key})
        return existed

    def _append(self, rec: Dict[str, Any]) -> None:
        self._wal.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal.flush()
        self._wal_records += 1
        if self._wal_records > max(64, self.compact_ratio * len(self._data)):
            self.compact()

    # -- read ----------------------------------------------------------

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def items(self):
        return self._data.items()

    def keys(self):
        return list(self._data)

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key: str) -> bool:
        return key in self._data

    # -- maintenance ---------------------------------------------------

    def compact(self) -> None:
        """Rewrite the snapshot atomically; reset the wal."""
        tmp = self._snap_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for k, v in self._data.items():
                f.write(json.dumps({"k": k, "v": v},
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        self._wal.close()
        self._wal = open(self._wal_path, "w", encoding="utf-8")
        self._wal_records = 0

    def close(self) -> None:
        if self._wal is not None:
            self.compact()
            self._wal.close()
            self._wal = None

    def clear(self) -> None:
        self._data.clear()
        self.compact()


class Store:
    """Directory of named tables under the node's data dir."""

    def __init__(self, data_dir: str) -> None:
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._tables: Dict[str, Table] = {}

    def table(self, name: str) -> Table:
        t = self._tables.get(name)
        if t is None:
            t = self._tables[name] = Table(
                os.path.join(self.data_dir, name)
            )
        return t

    def close(self) -> None:
        for t in self._tables.values():
            t.close()
        self._tables.clear()

    def table_names(self):
        return list(self._tables)
