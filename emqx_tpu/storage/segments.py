"""Persistent compacted table segments — cold start without the rebuild.

At 10M filters the match table costs 64 s to build (BENCH_r03/r05); the
broker should instead cold-start from a compacted on-disk segment in
seconds and replay only the delta tail against the live router — the
mria "bootstrap from a checkpoint, then replay the rlog" pattern
(SURVEY.md §5.4) applied to the device mirror, with the join-style
flattened-trie layout serialized whole (TrieJax, PAPERS.md).

Two segment kinds, selected by the host table implementation:

* ``state`` (Python :class:`~emqx_tpu.ops.incremental.IncrementalNfa`)
  — the FULL mutable state: the flattened arrays verbatim plus a flat
  preorder trie relation ``(sid, parent_sid, edge_word_id, hash_aid,
  aid)``, the vocab interning table, the accept/alias/free-list state.
  :func:`restore_incremental` rebuilds the live table in one linear
  pass — no per-filter trie walks, no cuckoo inserts, no numpy row
  writes — so load is bounded by I/O + one Python loop over states.
* ``filters`` (native C++ table) — the filter set as one NUL-framed
  blob; load replays it through ``NativeNfa.bulk_add`` (one native
  call, seconds at 10M — vs one ctypes round trip per filter on the
  router-replay path).

File format: a single ``.npz`` written via temp-file + ``os.replace``
(crash-atomic), carrying a JSON meta record with ``version`` and a
sha1 ``checksum`` over every payload array; :func:`load_segment`
re-hashes and raises :class:`SegmentError` on any mismatch — a torn or
bit-rotten segment is REJECTED and the caller falls back to the full
rebuild (chaos-tested in tests/test_chaos_delivery.py).

Alias/deep-filter state (filters deeper than the device table) and the
routing-aid set ride in both kinds so the serving layer restores its
id-space bookkeeping without an O(n) re-derivation.

``extra_meta`` entries land inside the checksummed meta record, so a
writer can bind a segment to state that lives OUTSIDE the file: the
multichip plane stamps ``placement_crc`` (the crc32 of its popularity
placement override map, ISSUE 20) into every per-shard segment — a
shard file cut under a different placement than the manifest restores
is then rejected at load even though its own payload checksum is
intact (the torn-save mixed-generation case the epoch guard alone
cannot see).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["SegmentError", "Segment", "save_segment", "load_segment",
           "restore_incremental", "SEGMENT_VERSION"]

# v2 (ISSUE 13): state segments may carry the sorted join-relation
# arrays (join_start/join_word/join_next — the relational-join match
# backend's CSR edge relation, ops/join_match.py) so a cold start can
# seed the device mirror without re-paying the build sort.  v1 files
# are version-rejected (full rebuild serves once after upgrade).
SEGMENT_VERSION = 2

_SEP = "\x00"  # MQTT strings never contain U+0000 (MQTT-1.5.4-2)


class SegmentError(RuntimeError):
    """Segment unusable: bad magic/version, checksum mismatch, or a
    structurally impossible payload.  Callers fall back to the full
    rebuild — never serve from a suspect table."""


@dataclass
class Segment:
    """Decoded segment payload (see module docstring for the kinds)."""

    kind: str                      # "state" | "filters"
    depth: int
    epoch: int
    filters: List[str]             # live NFA filters (aliases excluded)
    deep: Dict[str, int]           # too-deep filter -> alias aid
    routing_aids: List[int]        # aids that belonged to routing filters
    meta: dict = field(default_factory=dict)
    # state-kind payload (None for "filters" segments)
    node_tab: Optional[np.ndarray] = None
    edge_tab: Optional[np.ndarray] = None
    seeds: Optional[np.ndarray] = None
    trie: Optional[np.ndarray] = None       # (n, 5) int32 BFS relation
    vocab_words: Optional[List[str]] = None  # id order (1-based)
    accept_mask: Optional[np.ndarray] = None
    accept_filters: Optional[List[str]] = None  # holes as None
    alias_aids: Optional[List[int]] = None
    free_aids: Optional[np.ndarray] = None  # (k, 2) int64 (epoch, aid)
    n_filters: int = 0
    n_states: int = 0
    aid_reuses: int = 0
    # sorted join-relation arrays (v2, optional — present when the
    # writer served the join backend): CSR offsets + word/next columns
    join_start: Optional[np.ndarray] = None  # (S+1,) int32
    join_word: Optional[np.ndarray] = None   # (E_cap,) int32
    join_next: Optional[np.ndarray] = None   # (E_cap,) int32


def _blob(strings) -> np.ndarray:
    data = _SEP.join(strings).encode("utf-8")
    return np.frombuffer(data, dtype=np.uint8).copy()


def _unblob(arr: np.ndarray) -> List[str]:
    if arr.size == 0:
        return []
    return bytes(arr.tobytes()).decode("utf-8").split(_SEP)


def _checksum(arrays: Dict[str, np.ndarray], meta: dict) -> str:
    h = hashlib.sha1()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(json.dumps(meta, sort_keys=True).encode())
    return h.hexdigest()


def _trie_rows(inc) -> np.ndarray:
    """Flatten the live trie into the preorder relation ``(sid,
    parent_sid, edge_word_id, hash_aid, aid)``; parents precede their
    children so the restore pass links in one forward scan.
    edge_word_id: the vocab id of the literal parent edge, -1 for a
    ``+`` edge, -2 for the root."""
    inc._hydrate()   # a restored-but-never-touched trie links here
    rows: List[Tuple[int, int, int, int, int]] = []
    stack = [inc.root]
    vocab = inc.vocab
    while stack:
        node = stack.pop()
        if node.parent is None:
            wid = -2
            parent = -1
        else:
            parent = node.parent.sid
            wid = -1 if node.pword is None else vocab[node.pword]
        rows.append((node.sid, parent, wid, node.hash_aid, node.aid))
        stack.extend(node.lit.values())
        if node.plus is not None:
            stack.append(node.plus)
    out = np.asarray(rows, dtype=np.int32).reshape(len(rows), 5)
    return out


def save_segment(path: str, inc, *, deep: Dict[str, int],
                 routing_aids, filters: Optional[List[str]] = None,
                 extra_meta: Optional[dict] = None,
                 join_relation: bool = False) -> dict:
    """Serialize ``inc`` (+ the serving layer's deep/routing id state)
    to ``path`` atomically.  ``filters`` must be supplied for native
    tables (the caller already has the list — iterating the accept view
    back out would cost one ctypes round trip per filter).

    ``join_relation`` (state segments only) additionally persists the
    sorted edge relation built fresh from the edge table — always
    overlay-free, so a restore can seed the join backend's device
    mirror verbatim (epoch-guarded by the consumer)."""
    is_state = hasattr(inc, "node_tab") and hasattr(inc, "root")
    meta: dict = {
        "version": SEGMENT_VERSION,
        "kind": "state" if is_state else "filters",
        "depth": int(inc.depth),
        "epoch": int(inc.epoch),
        "n_filters": int(inc.n_filters),
        "n_states": int(inc.n_states),
        "aid_reuses": int(inc.aid_reuses),
    }
    if extra_meta:
        meta.update(extra_meta)
    arrays: Dict[str, np.ndarray] = {
        "deep_filters": _blob(list(deep.keys())),
        "deep_aids": np.asarray(list(deep.values()), np.int32),
        "routing_aids": np.asarray(sorted(routing_aids), np.int32),
    }
    if is_state:
        accepts = list(inc.accept_filters)
        mask = np.asarray([f is not None for f in accepts], bool)
        arrays.update(
            node_tab=inc.node_tab,
            edge_tab=inc.edge_tab,
            seeds=inc.seeds,
            trie=_trie_rows(inc),
            vocab=_blob(list(inc.vocab.keys())),
            accept_mask=mask,
            accepts=_blob([f for f in accepts if f is not None]),
            alias_aids=np.asarray(sorted(inc._alias_aids), np.int32),
            free_aids=np.asarray(
                [(e, a) for e, a in inc._free_aids], np.int64
            ).reshape(-1, 2),
        )
        if join_relation:
            from ..ops.join_match import JoinRelation

            rel = JoinRelation(
                int(inc.node_tab.shape[0]), inc.edge_tab)
            arrays.update(
                join_start=rel.state_start,
                join_word=rel.edge_word,
                join_next=rel.edge_next,
            )
    else:
        if filters is None:
            raise ValueError(
                "filters list required for native-table segments")
        arrays["filters"] = _blob(filters)
    meta["checksum"] = _checksum(arrays, {
        k: v for k, v in meta.items() if k != "checksum"})
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        np.savez(f, meta=_blob([json.dumps(meta)]), **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return meta


def load_segment(path: str) -> Segment:
    """Read + verify a segment; raises :class:`SegmentError` on ANY
    defect (missing file, version skew, checksum mismatch)."""
    try:
        z = np.load(path)
    except Exception as e:  # np.load surfaces zipfile/format errors too
        raise SegmentError(f"segment unreadable: {e}") from e
    with z:
        try:
            meta = json.loads(_unblob(z["meta"])[0])
        except Exception as e:
            raise SegmentError(f"segment meta unreadable: {e}") from e
        if meta.get("version") != SEGMENT_VERSION:
            raise SegmentError(
                f"segment version {meta.get('version')!r} != "
                f"{SEGMENT_VERSION}")
        try:
            # zip CRC / npy header defects surface here, per array
            arrays = {name: z[name] for name in z.files if name != "meta"}
        except Exception as e:
            raise SegmentError(f"segment arrays unreadable: {e}") from e
        want = meta.get("checksum")
        got = _checksum(arrays, {
            k: v for k, v in meta.items() if k != "checksum"})
        if want != got:
            raise SegmentError(
                f"segment checksum mismatch ({want!r} != {got!r})")
    deep_filters = _unblob(arrays["deep_filters"])
    deep_aids = arrays["deep_aids"].tolist()
    seg = Segment(
        kind=meta["kind"], depth=int(meta["depth"]),
        epoch=int(meta["epoch"]),
        filters=[], deep=dict(zip(deep_filters, deep_aids)),
        routing_aids=arrays["routing_aids"].tolist(), meta=meta,
        n_filters=int(meta["n_filters"]), n_states=int(meta["n_states"]),
        aid_reuses=int(meta.get("aid_reuses", 0)),
    )
    if meta["kind"] == "state":
        accepts_live = _unblob(arrays["accepts"])
        mask = arrays["accept_mask"]
        accept_filters: List[Optional[str]] = [None] * len(mask)
        it = iter(accepts_live)
        for i, live in enumerate(mask):
            if live:
                accept_filters[i] = next(it)
        seg.node_tab = arrays["node_tab"]
        seg.edge_tab = arrays["edge_tab"]
        seg.seeds = arrays["seeds"]
        seg.trie = arrays["trie"]
        seg.vocab_words = _unblob(arrays["vocab"])
        seg.accept_mask = mask
        seg.accept_filters = accept_filters
        seg.alias_aids = arrays["alias_aids"].tolist()
        seg.free_aids = arrays["free_aids"]
        if "join_start" in arrays:
            seg.join_start = arrays["join_start"]
            seg.join_word = arrays["join_word"]
            seg.join_next = arrays["join_next"]
        alias = set(seg.alias_aids)
        seg.filters = [
            f for aid, f in enumerate(accept_filters)
            if f is not None and aid not in alias
        ]
    else:
        seg.filters = _unblob(arrays["filters"])
    return seg


def restore_incremental(seg: Segment, seed: int = 0xE709):
    """Rebuild a live Python :class:`IncrementalNfa` from a ``state``
    segment in one linear pass (no per-filter adds — the point)."""
    from collections import deque

    from ..ops.incremental import IncrementalNfa, _INode

    if seg.kind != "state":
        raise SegmentError(f"cannot restore from a {seg.kind!r} segment")
    inc = IncrementalNfa.__new__(IncrementalNfa)
    inc.depth = seg.depth
    inc._rng = np.random.default_rng(seed ^ (seg.epoch & 0xFFFF))
    inc.node_tab = np.ascontiguousarray(seg.node_tab, np.int32)
    inc.edge_tab = np.ascontiguousarray(seg.edge_tab, np.int32)
    inc.seeds = np.ascontiguousarray(seg.seeds, np.int32)
    inc._seed_ints = (int(inc.seeds[0]), int(inc.seeds[1]))
    words = list(seg.vocab_words or [])
    inc.vocab = {w: i + 1 for i, w in enumerate(words)}
    id2word = [None] + words   # vocab ids are 1-based, dense
    inc.accept_filters = list(seg.accept_filters or [])
    inc.epoch = seg.epoch
    inc.n_filters = seg.n_filters
    inc.n_states = seg.n_states
    inc.n_edges = int(np.count_nonzero(
        inc.edge_tab.reshape(-1, 4)[:, 0] >= 0))
    # trie relink is LAZY: the flat relation parks on the table and
    # links into _INode objects on first mutation/walk (or when the
    # serving layer's background hydrate gets there first) — the cold
    # start itself pays only the array load above.  Parents precede
    # children in the relation, so one forward scan rebuilds the tree.
    s = int(inc.node_tab.shape[0])
    trie = np.ascontiguousarray(
        seg.trie if seg.trie is not None else np.zeros((0, 5), np.int32))
    used = np.zeros(s, bool)
    used[trie[:, 0]] = True
    used[0] = True
    inc._free_sids = np.flatnonzero(~used)[::-1].tolist()
    inc.root = None   # valid only after hydration (all entry points do)
    lock = threading.Lock()

    def hydrate() -> None:
        with lock:
            if inc._pending_trie is None:
                return   # lost the race: another thread linked it
            nodes: List[Optional[_INode]] = [None] * s
            for sid, parent, wid, hash_aid, aid in trie.tolist():
                if wid == -2:
                    node = _INode(sid, None, None)
                elif wid == -1:
                    node = _INode(sid, nodes[parent], None)
                    nodes[parent].plus = node
                else:
                    word = id2word[wid]
                    node = _INode(sid, nodes[parent], word)
                    nodes[parent].lit[word] = node
                node.hash_aid = hash_aid
                node.aid = aid
                nodes[sid] = node
            inc.root = nodes[0] if nodes and nodes[0] is not None \
                else _INode(0, None, None)
            inc._pending_trie = None

    inc._pending_trie = hydrate
    inc._free_aids = deque(
        (int(e), int(a)) for e, a in
        (seg.free_aids.tolist() if seg.free_aids is not None else ()))
    inc.device_epoch = None
    inc.aid_reuses = seg.aid_reuses
    inc._alias_aids = set(seg.alias_aids or ())
    inc._dirty_states = set()
    inc._dirty_buckets = set()
    inc._resized = False
    inc.track_regions = False
    inc._node_grown_from = -1
    inc._edges_rehashed = False
    inc._node_wholesale = False
    return inc
