"""Node persistence: durable sessions, retained, delayed, banned.

Behavioral reference (SURVEY.md §5.4): the reference persists retained
messages, persistent sessions (clean_start=false / expiry>0), the
banned table and delayed messages across restarts (mnesia disc_copies /
``emqx_ds``).  Here a :class:`~emqx_tpu.storage.store.Store` holds one
table per concern; restore happens at node construction (before
listeners accept), and a periodic sync flushes changes (plus a final
sync on stop) — the flush interval bounds data loss on crash the same
way mnesia's dump_log interval does.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Dict, List

from .codec import (
    ban_to_dict,
    msg_from_dict,
    msg_to_dict,
    session_restore,
    session_to_dict,
)
from .store import Store, Table

log = logging.getLogger(__name__)

__all__ = ["Persistence"]


class Persistence:
    def __init__(self, node: Any, data_dir: str) -> None:
        self.node = node
        self.broker = node.broker
        try:
            fsync_s = node.config.get("durable_storage.fsync_interval")
        except Exception:
            fsync_s = 0.0
        self.store = Store(data_dir, fsync_interval_s=fsync_s)
        self.t_sessions = self.store.table("sessions")
        self.t_retained = self.store.table("retained")
        self.t_delayed = self.store.table("delayed")
        self.t_banned = self.store.table("banned")
        # replicas of OTHER nodes' durable sessions (cluster durable
        # replication): persisted so a full-cluster restart still allows
        # promotion; restored via node._restored_session_replicas and
        # NEVER re-opened as local sessions
        self.t_session_replicas = self.store.table("session_replicas")
        self.last_sync = 0.0
        # serializes threaded sync_async writes against close(): a
        # cancelled housekeeping task does NOT stop its to_thread worker,
        # so close() must wait for any in-flight _write before the final
        # sync/compact touches the same WAL handle
        self._write_lock = threading.RLock()
        self._closed = False

    # ------------------------------------------------------------------
    # restore (at node construction)
    # ------------------------------------------------------------------

    def restore(self) -> Dict[str, int]:
        counts = {"sessions": 0, "retained": 0, "delayed": 0, "banned": 0,
                  "session_replicas": 0}
        replicas = {}
        for cid, d in list(self.t_session_replicas.items()):
            try:
                replicas[cid] = (float(d["ts"]), d["session"])
                counts["session_replicas"] += 1
            except Exception:
                log.exception("restore session replica %r failed", cid)
        self.node._restored_session_replicas = replicas
        for _cid, d in list(self.t_sessions.items()):
            try:
                sess = session_restore(self.broker, d)
                # restored sessions are disconnected: enter the expiry
                # sweep now so they don't outlive their expiry interval
                if sess is not None:
                    self.node._disconnected_at.setdefault(
                        sess.clientid, time.time()
                    )
                counts["sessions"] += 1
            except Exception:
                log.exception("restore session %r failed", _cid)
        if self.node.retainer is not None:
            for _topic, d in list(self.t_retained.items()):
                try:
                    self.node.retainer.insert(msg_from_dict(d))
                    counts["retained"] += 1
                except Exception:
                    log.exception("restore retained %r failed", _topic)
        if self.node.delayed is not None:
            now = time.time()
            for key, d in list(self.t_delayed.items()):
                try:
                    fire_at = float(d["fire_at"])
                    msg = msg_from_dict(d["msg"])
                    delay = max(0.0, fire_at - now)
                    self.node.delayed.schedule(msg, delay, now=now)
                    counts["delayed"] += 1
                except Exception:
                    log.exception("restore delayed %r failed", key)
        for _key, d in list(self.t_banned.items()):
            try:
                until = d.get("until")
                self.node.banned.add(
                    d["kind"], d["who"],
                    duration=(until - time.time()) if until else None,
                    by=d.get("by", "restore"), reason=d.get("reason", ""),
                )
                counts["banned"] += 1
            except Exception:
                log.exception("restore ban %r failed", _key)
        log.info("persistence restored: %s", counts)
        return counts

    # ------------------------------------------------------------------
    # sync (periodic from housekeeping + on stop)
    # ------------------------------------------------------------------

    @staticmethod
    def _sync_table(table: Table, want: Dict[str, Any]) -> None:
        """Reconcile the persistent table with the live dict (puts ride
        the wal; removals too; unchanged keys are skipped).  One fsync
        per pass, not per key — nothing is acked mid-pass."""
        live = dict(table.items())
        puts = {k: v for k, v in want.items() if live.get(k) != v}
        dels = [k for k in live if k not in want]
        if puts or dels:
            table.write_batch(puts, dels)

    def _collect(self) -> List[tuple]:
        """Serialize live state to JSON-safe dicts ON the event loop (the
        state may not be read from another thread); returns the
        (table, want) work list for :meth:`_write`."""
        want_sessions: Dict[str, Any] = {}
        for cid, sess in self.broker.sessions.items():
            # durable sessions: resumable (clean_start False or expiry>0)
            if not sess.clean_start or sess.expiry_interval > 0:
                want_sessions[cid] = session_to_dict(sess)
        work = [(self.t_sessions, want_sessions)]
        if self.node.retainer is not None:
            ret = self.node.retainer
            want = {}
            for t in ret.topics():
                for m in ret.match(t):
                    want[m.topic] = msg_to_dict(m)
            work.append((self.t_retained, want))
        if self.node.delayed is not None:
            work.append((self.t_delayed, {
                f"{seq}": {"fire_at": fire_at, "msg": msg_to_dict(msg)}
                for fire_at, seq, msg in self.node.delayed.entries()
            }))
        work.append((self.t_banned, {
            f"{e.kind}:{e.who}": ban_to_dict(e)
            for e in self.node.banned.list()
        }))
        cluster = getattr(self.node, "cluster", None)
        if cluster is not None:
            replicas = cluster.durable.session_replicas
        else:
            # after cluster teardown the final stash (or the restored
            # set, if clustering never came up) is still authoritative
            replicas = getattr(self.node, "_restored_session_replicas", None)
        if replicas is not None:
            work.append((self.t_session_replicas, {
                cid: {"ts": ts, "session": state}
                for cid, (ts, state) in replicas.items()
            }))
        return work

    def _write(self, work: List[tuple]) -> None:
        with self._write_lock:
            if self._closed:
                return
            for table, want in work:
                self._sync_table(table, want)

    def sync(self) -> None:
        self.last_sync = time.time()
        self._write(self._collect())

    async def sync_async(self) -> None:
        """Housekeeping entry: collect on the loop, write in a thread so
        disk flushes never stall connections."""
        self.last_sync = time.time()
        work = self._collect()
        await asyncio.to_thread(self._write, work)

    def close(self) -> None:
        with self._write_lock:
            self.sync()
            self._closed = True
            self.store.close()
