"""Durable storage (SURVEY.md §5.4): log-structured store (emqx_ds
analog), session/retained/delayed/banned persistence, NFA table
checkpoints, and data import/export."""

from .backup import export_data, import_data
from .checkpoint import load_table, save_table
from .persistence import Persistence
from .segments import (
    SegmentError, load_segment, restore_incremental, save_segment,
)
from .store import Store, Table

__all__ = [
    "Store", "Table", "Persistence",
    "save_table", "load_table",
    "save_segment", "load_segment", "restore_incremental", "SegmentError",
    "export_data", "import_data",
]
