"""JSON-safe serialization of broker records (messages, subscriptions,
sessions, bans) for the durable store and the data export archive."""

from __future__ import annotations

import base64
from typing import Any, Dict, Optional

from ..broker.message import Message
from ..broker.session import Session, SubOpts

__all__ = [
    "msg_to_dict", "msg_from_dict",
    "subopts_to_dict", "subopts_from_dict",
    "session_to_dict", "session_restore",
    "ban_to_dict",
]


def msg_to_dict(m: Message) -> Dict[str, Any]:
    return {
        "id": m.id, "qos": m.qos, "from": m.sender, "topic": m.topic,
        "payload": base64.b64encode(m.payload or b"").decode(),
        "retain": m.retain, "ts": m.timestamp,
        "props": m.properties or None,
        "headers": {k: v for k, v in (m.headers or {}).items()
                    if isinstance(v, (str, int, float, bool))} or None,
    }


def msg_from_dict(d: Dict[str, Any]) -> Message:
    return Message(
        id=int(d.get("id", 0)), qos=int(d.get("qos", 0)),
        sender=d.get("from"), topic=d["topic"],
        payload=base64.b64decode(d.get("payload", "")),
        retain=bool(d.get("retain", False)),
        timestamp=float(d.get("ts", 0.0)),
        properties=d.get("props") or {},
        headers=d.get("headers") or {},
    )


def subopts_to_dict(o: SubOpts) -> Dict[str, Any]:
    return {
        "qos": o.qos, "nl": int(o.nl), "rap": int(o.rap), "rh": o.rh,
        "share": o.share, "subid": o.subid,
    }


def subopts_from_dict(d: Dict[str, Any]) -> SubOpts:
    return SubOpts(
        qos=int(d.get("qos", 0)), nl=bool(d.get("nl", 0)),
        rap=bool(d.get("rap", 0)), rh=int(d.get("rh", 0)),
        share=d.get("share"), subid=d.get("subid"),
    )


def session_to_dict(sess: Session) -> Dict[str, Any]:
    return {
        "clientid": sess.clientid,
        "clean_start": sess.clean_start,
        "created_at": sess.created_at,
        "expiry_interval": sess.expiry_interval,
        "subscriptions": {
            flt: subopts_to_dict(o)
            for flt, o in sess.subscriptions.items()
        },
        "pending": [msg_to_dict(m) for m in sess.pending_messages()],
    }


def session_restore(broker: Any, d: Dict[str, Any]) -> Optional[Session]:
    """Recreate a persisted session in the broker (resubscribing restores
    routes, and thus the route replication + device mirror feeds)."""
    cid = d["clientid"]
    sess, _present = broker.open_session(
        cid, clean_start=False,
        expiry_interval=float(d.get("expiry_interval", 0.0)),
    )
    sess.created_at = float(d.get("created_at", sess.created_at))
    sess.connected = False
    for flt, od in (d.get("subscriptions") or {}).items():
        try:
            broker.subscribe(cid, flt, subopts_from_dict(od))
        except Exception:
            continue
    pending = [msg_from_dict(md) for md in d.get("pending") or []]
    if pending:
        sess.deliver(pending)
    return sess


def ban_to_dict(e: Any) -> Dict[str, Any]:
    return {
        "kind": e.kind, "who": e.who, "by": e.by, "reason": e.reason,
        "at": e.at, "until": e.until,
    }
