"""NFA table checkpoint — skip the cold compile on restart.

SURVEY.md §5.4: the device mirror needs versioned snapshots; beyond the
in-memory epoch/delta discipline, a compiled :class:`NfaTable` can be
checkpointed to disk (arrays as ``.npz``, metadata as JSON inside it)
and restored directly, the way orbax checkpoints compiled train state —
a restart then serves from the checkpoint while the background rebuild
catches up with any missed deltas.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ..ops.compiler import NfaTable

__all__ = ["save_table", "load_table"]


def save_table(table: NfaTable, path: str) -> None:
    tmp = path + ".tmp"
    meta = {
        "n_states": table.n_states,
        "depth": table.depth,
        "epoch": table.epoch,
        "vocab": table.vocab,
        "accept_filters": table.accept_filters,
    }
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            node_tab=table.node_tab,
            edge_tab=table.edge_tab,
            seeds=table.seeds,
            meta=np.frombuffer(
                json.dumps(meta).encode("utf-8"), dtype=np.uint8
            ),
        )
    os.replace(tmp, path)


def load_table(path: str) -> Optional[NfaTable]:
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode("utf-8"))
        return NfaTable(
            node_tab=z["node_tab"],
            edge_tab=z["edge_tab"],
            seeds=z["seeds"],
            n_states=int(meta["n_states"]),
            depth=int(meta["depth"]),
            vocab=dict(meta["vocab"]),
            accept_filters=list(meta["accept_filters"]),
            epoch=int(meta["epoch"]),
        )
