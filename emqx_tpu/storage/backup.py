"""Data import/export — the ``emqx_mgmt_data_backup`` analog.

Behavioral reference (SURVEY.md §5.4): the reference exports a tar of
tables + config (``emqx export``) and re-imports it on any node.  Here
the archive is a tar.gz holding one JSON document per concern (retained,
sessions, banned, delayed, rules, config overrides) plus a manifest;
import merges into the running node.
"""

from __future__ import annotations

import asyncio
import io
import json
import tarfile
import time
from typing import Any, Dict
import logging

log = logging.getLogger(__name__)

from .codec import (
    ban_to_dict,
    msg_from_dict,
    msg_to_dict,
    session_restore,
    session_to_dict,
)

__all__ = ["export_data", "import_data"]

_VERSION = 1


def _collect(node: Any) -> Dict[str, Any]:
    broker = node.broker
    docs: Dict[str, Any] = {
        "manifest": {
            "version": _VERSION,
            "node": broker.node,
            "exported_at": time.time(),
        },
        "sessions": [
            session_to_dict(s)
            for s in broker.sessions.values()
            if not s.clean_start or s.expiry_interval > 0
        ],
        "banned": [ban_to_dict(e) for e in node.banned.list()],
        "rules": [
            {"id": r.id, "sql": r.sql, "enable": r.enable,
             "description": r.description,
             # dict actions (republish/console) and string bridge refs
             # both round-trip; only bare callables are non-serializable
             "actions": [a for a in r.actions
                         if isinstance(a, (dict, str))]}
            for r in node.rule_engine.rules.values()
        ],
        "bridges": node.bridges.export_config()
        if getattr(node, "bridges", None) is not None else [],
        # runtime-managed auth: the FACTORY CONFIGS round-trip (secrets
        # included — same posture as the reference's config export).
        # KNOWN GAP: built-in-db users added AFTER create (via
        # /authentication/{idx}/users) are not exported — only the
        # creation-time "users" seeds rebuild
        "auth": {
            "authenticators": [c for c, _ in
                               getattr(node, "_auth_confs", [])],
            "sources": [c for c, _ in getattr(node, "_authz_confs", [])],
        },
    }
    if node.retainer is not None:
        docs["retained"] = [
            msg_to_dict(m)
            for t in node.retainer.topics()
            for m in node.retainer.match(t)
        ]
    if node.delayed is not None:
        docs["delayed"] = [
            {"fire_at": at, "msg": msg_to_dict(m)}
            for at, m in node.delayed.to_list()
        ]
    return docs


def export_data(node: Any) -> bytes:
    """Returns a tar.gz archive of the node's durable state."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for name, doc in _collect(node).items():
            data = json.dumps(doc, indent=1, default=str).encode()
            info = tarfile.TarInfo(name=f"{name}.json")
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def import_data(node: Any, archive: bytes) -> Dict[str, int]:
    """Merge an exported archive into the running node."""
    counts = {"sessions": 0, "retained": 0, "banned": 0, "rules": 0,
              "delayed": 0, "auth": 0}
    docs: Dict[str, Any] = {}
    with tarfile.open(fileobj=io.BytesIO(archive), mode="r:gz") as tar:
        for member in tar.getmembers():
            f = tar.extractfile(member)
            if f is None:
                continue
            docs[member.name.removesuffix(".json")] = json.load(f)
    manifest = docs.get("manifest", {})
    if manifest.get("version") not in (None, _VERSION):
        raise ValueError(
            f"unsupported backup version {manifest.get('version')!r}"
        )
    for d in docs.get("sessions", []):
        if d.get("clientid") not in node.broker.sessions:
            sess = session_restore(node.broker, d)
            # imported offline durable sessions must enter the expiry
            # sweep (same as Persistence.restore) or they live forever
            if sess is not None:
                node._disconnected_at.setdefault(sess.clientid, time.time())
            counts["sessions"] += 1
    if node.retainer is not None:
        for md in docs.get("retained", []):
            node.retainer.insert(msg_from_dict(md))
            counts["retained"] += 1
    for bd in docs.get("banned", []):
        until = bd.get("until")
        node.banned.add(
            bd["kind"], bd["who"],
            duration=(until - time.time()) if until else None,
            by=bd.get("by", "import"), reason=bd.get("reason", ""),
        )
        counts["banned"] += 1
    if node.delayed is not None:
        now = time.time()
        for dd in docs.get("delayed", []):
            node.delayed.schedule(
                msg_from_dict(dd["msg"]),
                max(0.0, float(dd["fire_at"]) - now),
            )
            counts["delayed"] += 1
    # bridges restore BEFORE rules so restored rule actions resolve; the
    # workers start asynchronously (enqueue buffers until then)
    if getattr(node, "bridges", None) is not None:
        counts["bridges"] = 0
        for it in docs.get("bridges", []):
            bid = f"{it['type']}:{it['name']}"
            if node.bridges.get(bid) is None:
                br = node.bridges.register(it["type"], it["name"], it["conf"])
                if br.enable:
                    try:
                        asyncio.get_running_loop()
                        asyncio.ensure_future(br.worker.start())
                    except RuntimeError:
                        pass  # no loop (sync restore path); started later
                counts["bridges"] += 1
    for rd in docs.get("rules", []):
        if rd["id"] not in node.rule_engine.rules:
            node.rule_engine.create_rule(
                rd["id"], rd["sql"], actions=rd.get("actions"),
                description=rd.get("description", ""),
                enable=bool(rd.get("enable", True)),
            )
            counts["rules"] += 1
    # runtime-managed auth configs rebuild through the factory
    auth_doc = docs.get("auth") or {}
    if auth_doc.get("authenticators") or auth_doc.get("sources"):
        from ..auth.factory import make_authenticator, make_authz_source

        ac = node.ensure_access_control()
        for conf in auth_doc.get("authenticators", []):
            try:
                auth, conf = make_authenticator(conf)
            except (ValueError, KeyError, TypeError,
                    AttributeError) as e:
                # a bad conf must not abort the import — but dropping a
                # SECURITY config silently would be worse than noisy
                log.error("import: dropping authenticator conf "
                          "(type=%r): %s",
                          conf.get("type") if isinstance(conf, dict)
                          else type(conf).__name__, e)
                continue
            ac.chain.add(auth)
            if "allow_anonymous" in conf:
                ac.chain.allow_anonymous = bool(conf["allow_anonymous"])
            node._auth_confs.append((conf, auth))
            counts["auth"] += 1
        for conf in auth_doc.get("sources", []):
            try:
                src, conf = make_authz_source(conf)
            except (ValueError, KeyError, TypeError,
                    AttributeError) as e:
                log.error("import: dropping authz source conf "
                          "(type=%r): %s",
                          conf.get("type") if isinstance(conf, dict)
                          else type(conf).__name__, e)
                continue
            ac.authz.sources.append(src)
            node._authz_confs.append((conf, src))
            counts["auth"] += 1
        ac.authz.clear_cache()
        ac.invalidate_async_cache()
    return counts
