// Fast single-field JSON extraction for the rule-engine hot path.
//
// Role: the jiffy-NIF analog (SURVEY.md §2.4).  Measured 2026-07-30:
// stdlib json.loads is ~10.5% of the publish+rules hot path at config-3
// payload shapes, and most rules touch one or two payload fields — so
// instead of a full decoder (stdlib's scanner is already C), this
// extracts ONE dot-path scalar without materializing any Python
// containers.
//
// Semantics contract: a found=non-zero result must be EXACTLY what
// json.loads would produce for that path.  The scanner therefore
// VALIDATES everything it walks over with the strict JSON grammar
// (RFC 8259: no trailing garbage, no leading zeros or '+', no raw
// control chars in strings, escape sequences well-formed, literals
// exact) — any deviation, and anything a scalar can't represent
// (escaped strings, containers, over-long-long ints), returns
// NOT_FOUND=bail and the caller falls back to json.loads.
//
// C ABI (ctypes):
//   int fj_get(buf, len, path, pathlen,
//              &sptr, &slen, &dval, &ival)
//   returns: 0 bail/missing, 1 string (sptr/slen into buf),
//            2 int (ival), 3 double (dval), 4 true, 5 false, 6 null
//
// Path segments are '\x1f'-joined UTF-8 object keys (no array
// indexing: the rule engine's payload paths are dict walks).

#include <cerrno>
#include <cstddef>
#include <cstdlib>
#include <cstring>

namespace {

constexpr int kMaxDepth = 64;

struct Cur {
    const char* p;
    const char* end;
};

inline void ws(Cur& c) {
    while (c.p < c.end && (*c.p == ' ' || *c.p == '\t' || *c.p == '\n' ||
                           *c.p == '\r'))
        ++c.p;
}

inline bool is_hex(char ch) {
    return (ch >= '0' && ch <= '9') || (ch >= 'a' && ch <= 'f') ||
           (ch >= 'A' && ch <= 'F');
}

inline bool is_cont(unsigned char b) { return (b & 0xC0) == 0x80; }

// Strict UTF-8 sequence validation at c.p (first byte >= 0x80):
// advances past the sequence or fails — json.loads(bytes) rejects
// invalid UTF-8 anywhere, so the scanner must too.
bool scan_utf8(Cur& c) {
    unsigned char b0 = static_cast<unsigned char>(*c.p);
    ptrdiff_t left = c.end - c.p;
    if (b0 >= 0xC2 && b0 <= 0xDF) {
        if (left < 2 || !is_cont(c.p[1])) return false;
        c.p += 2;
        return true;
    }
    if (b0 == 0xE0) {
        if (left < 3 || static_cast<unsigned char>(c.p[1]) < 0xA0 ||
            static_cast<unsigned char>(c.p[1]) > 0xBF || !is_cont(c.p[2]))
            return false;
        c.p += 3;
        return true;
    }
    if ((b0 >= 0xE1 && b0 <= 0xEC) || b0 == 0xEE || b0 == 0xEF) {
        if (left < 3 || !is_cont(c.p[1]) || !is_cont(c.p[2])) return false;
        c.p += 3;
        return true;
    }
    if (b0 == 0xED) {  // excludes UTF-16 surrogates
        if (left < 3 || static_cast<unsigned char>(c.p[1]) < 0x80 ||
            static_cast<unsigned char>(c.p[1]) > 0x9F || !is_cont(c.p[2]))
            return false;
        c.p += 3;
        return true;
    }
    if (b0 == 0xF0) {
        if (left < 4 || static_cast<unsigned char>(c.p[1]) < 0x90 ||
            static_cast<unsigned char>(c.p[1]) > 0xBF || !is_cont(c.p[2]) ||
            !is_cont(c.p[3]))
            return false;
        c.p += 4;
        return true;
    }
    if (b0 >= 0xF1 && b0 <= 0xF3) {
        if (left < 4 || !is_cont(c.p[1]) || !is_cont(c.p[2]) ||
            !is_cont(c.p[3]))
            return false;
        c.p += 4;
        return true;
    }
    if (b0 == 0xF4) {
        if (left < 4 || static_cast<unsigned char>(c.p[1]) < 0x80 ||
            static_cast<unsigned char>(c.p[1]) > 0x8F || !is_cont(c.p[2]) ||
            !is_cont(c.p[3]))
            return false;
        c.p += 4;
        return true;
    }
    return false;  // C0/C1 overlongs, F5+, stray continuation
}

// Validate + skip the string at c.p (opening quote), strict grammar.
// Sets *escaped if any backslash escape occurred; span excludes quotes.
bool scan_string(Cur& c, const char** sp, size_t* sl, bool* escaped) {
    if (c.p >= c.end || *c.p != '"') return false;
    const char* start = ++c.p;
    *escaped = false;
    while (c.p < c.end) {
        unsigned char ch = static_cast<unsigned char>(*c.p);
        if (ch == '"') {
            *sp = start;
            *sl = static_cast<size_t>(c.p - start);
            ++c.p;
            return true;
        }
        if (ch < 0x20) return false;  // raw control char: json.loads rejects
        if (ch == '\\') {
            *escaped = true;
            if (c.p + 1 >= c.end) return false;
            char e = c.p[1];
            if (e == 'u') {
                if (c.p + 5 >= c.end || !is_hex(c.p[2]) || !is_hex(c.p[3]) ||
                    !is_hex(c.p[4]) || !is_hex(c.p[5]))
                    return false;
                c.p += 6;
                continue;
            }
            if (e != '"' && e != '\\' && e != '/' && e != 'b' && e != 'f' &&
                e != 'n' && e != 'r' && e != 't')
                return false;
            c.p += 2;
            continue;
        }
        if (ch >= 0x80) {
            if (!scan_utf8(c)) return false;
            continue;
        }
        ++c.p;
    }
    return false;  // unterminated
}

// Validate + skip a number with the strict JSON grammar; reports span
// and whether it is integral.
bool scan_number(Cur& c, const char** np, size_t* nl, bool* floaty) {
    const char* start = c.p;
    *floaty = false;
    if (c.p < c.end && *c.p == '-') ++c.p;
    if (c.p >= c.end) return false;
    if (*c.p == '0') {
        ++c.p;  // leading zero: nothing more of the int part may follow
    } else if (*c.p >= '1' && *c.p <= '9') {
        while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
    } else {
        return false;  // '+', '.', 'Inf', 'NaN', '0123' all rejected
    }
    if (c.p < c.end && *c.p == '.') {
        *floaty = true;
        ++c.p;
        if (c.p >= c.end || *c.p < '0' || *c.p > '9') return false;
        while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
    }
    if (c.p < c.end && (*c.p == 'e' || *c.p == 'E')) {
        *floaty = true;
        ++c.p;
        if (c.p < c.end && (*c.p == '+' || *c.p == '-')) ++c.p;
        if (c.p >= c.end || *c.p < '0' || *c.p > '9') return false;
        while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
    }
    *np = start;
    *nl = static_cast<size_t>(c.p - start);
    return true;
}

// Validate + skip one JSON value of any type (recursive descent with a
// depth cap; no allocation).  This is what keeps the fast path's
// accept-set a SUBSET of json.loads'.
bool skip_value(Cur& c, int depth) {
    if (depth > kMaxDepth) return false;
    ws(c);
    if (c.p >= c.end) return false;
    char ch = *c.p;
    if (ch == '"') {
        const char* sp;
        size_t sl;
        bool esc;
        return scan_string(c, &sp, &sl, &esc);
    }
    if (ch == '{') {
        ++c.p;
        ws(c);
        if (c.p < c.end && *c.p == '}') { ++c.p; return true; }
        for (;;) {
            ws(c);
            const char* sp;
            size_t sl;
            bool esc;
            if (!scan_string(c, &sp, &sl, &esc)) return false;
            ws(c);
            if (c.p >= c.end || *c.p != ':') return false;
            ++c.p;
            if (!skip_value(c, depth + 1)) return false;
            ws(c);
            if (c.p >= c.end) return false;
            if (*c.p == ',') { ++c.p; continue; }
            if (*c.p == '}') { ++c.p; return true; }
            return false;
        }
    }
    if (ch == '[') {
        ++c.p;
        ws(c);
        if (c.p < c.end && *c.p == ']') { ++c.p; return true; }
        for (;;) {
            if (!skip_value(c, depth + 1)) return false;
            ws(c);
            if (c.p >= c.end) return false;
            if (*c.p == ',') { ++c.p; continue; }
            if (*c.p == ']') { ++c.p; return true; }
            return false;
        }
    }
    if (c.end - c.p >= 4 && memcmp(c.p, "true", 4) == 0) {
        c.p += 4;
        return true;
    }
    if (c.end - c.p >= 5 && memcmp(c.p, "false", 5) == 0) {
        c.p += 5;
        return true;
    }
    if (c.end - c.p >= 4 && memcmp(c.p, "null", 4) == 0) {
        c.p += 4;
        return true;
    }
    const char* np;
    size_t nl;
    bool fl;
    return scan_number(c, &np, &nl, &fl);
}

}  // namespace

extern "C" int fj_get(const char* buf, size_t len, const char* path,
                      size_t pathlen, const char** sptr, size_t* slen,
                      double* dval, long long* ival) {
    Cur c{buf, buf + len};
    const char* seg = path;
    const char* pend = path + pathlen;
    int depth = 0;

    while (seg < pend) {
        const char* segend = static_cast<const char*>(
            memchr(seg, '\x1f', static_cast<size_t>(pend - seg)));
        if (segend == nullptr) segend = pend;
        size_t seglen = static_cast<size_t>(segend - seg);

        ws(c);
        if (c.p >= c.end || *c.p != '{') return 0;
        if (++depth > kMaxDepth) return 0;
        ++c.p;
        ws(c);
        // scan the whole object (validating every member — a later
        // syntax error must bail even if the key already matched,
        // because json.loads would reject the whole document); keep
        // the LAST duplicate key, as dict construction does
        const char* match_at = nullptr;
        if (c.p < c.end && *c.p == '}') {
            ++c.p;
        } else {
            for (;;) {
                ws(c);
                const char* kp;
                size_t kl;
                bool kesc;
                if (!scan_string(c, &kp, &kl, &kesc)) return 0;
                if (kesc) return 0;  // escaped key: fall back
                ws(c);
                if (c.p >= c.end || *c.p != ':') return 0;
                ++c.p;
                ws(c);
                bool hit = (kl == seglen && memcmp(kp, seg, kl) == 0);
                if (hit) match_at = c.p;
                if (!skip_value(c, depth)) return 0;
                ws(c);
                if (c.p >= c.end) return 0;
                if (*c.p == ',') { ++c.p; continue; }
                if (*c.p == '}') { ++c.p; break; }
                return 0;
            }
        }
        if (seg == path) {
            // top level: json.loads rejects trailing garbage — check
            // the REMAINDER of the document before trusting anything
            Cur tail = c;
            ws(tail);
            if (tail.p != tail.end) return 0;
        }
        if (match_at == nullptr) return 0;
        c.p = match_at;  // descend into the (last) matching value
        seg = (segend < pend) ? segend + 1 : pend;
    }

    ws(c);
    if (c.p >= c.end) return 0;
    char ch = *c.p;
    if (ch == '"') {
        bool esc;
        if (!scan_string(c, sptr, slen, &esc)) return 0;
        return esc ? 0 : 1;  // escapes: json.loads must build the string
    }
    if (ch == '{' || ch == '[') return 0;  // non-scalar: full decode
    if (c.end - c.p >= 4 && memcmp(c.p, "true", 4) == 0) return 4;
    if (c.end - c.p >= 5 && memcmp(c.p, "false", 5) == 0) return 5;
    if (c.end - c.p >= 4 && memcmp(c.p, "null", 4) == 0) return 6;
    {
        const char* np;
        size_t nl;
        bool floaty;
        if (!scan_number(c, &np, &nl, &floaty)) return 0;
        char tmp[64];
        if (nl == 0 || nl >= sizeof(tmp)) return 0;
        memcpy(tmp, np, nl);
        tmp[nl] = '\0';
        char* endp = nullptr;
        if (!floaty) {
            errno = 0;
            long long v = strtoll(tmp, &endp, 10);
            if (errno == 0 && endp == tmp + nl) {
                *ival = v;
                return 2;
            }
            return 0;  // overflow: Python bignum path
        }
        errno = 0;
        double d = strtod(tmp, &endp);
        if (endp != tmp + nl) return 0;
        *dval = d;
        return 3;
    }
}
