"""ctypes wrapper for the C++ single-field JSON extractor (jiffy
analog — see fastjson.cpp for the measurement that justifies it).

``get_path(payload, ("a", "b")) -> (found, value)``: found=False means
"use json.loads" — missing key, escaped string, non-scalar result,
bignum, or no native toolchain all land there, so the fast path can
never change semantics, only skip work.
"""

from __future__ import annotations

import ctypes
from typing import Any, Sequence, Tuple

from .build import load_library

__all__ = ["get_path", "available"]

_lib = None
_loaded = False


def _load():
    global _lib, _loaded
    if not _loaded:
        _loaded = True
        lib = load_library("fastjson")
        if lib is not None:
            lib.fj_get.restype = ctypes.c_int
            lib.fj_get.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_longlong),
            ]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def get_path(payload: bytes, path: Sequence[str]) -> Tuple[bool, Any]:
    lib = _load()
    if lib is None or not path or any(p == "" for p in path):
        # empty segments would collapse in the \x1f join and skip both
        # the lookup and the trailing-garbage check — fall back
        return False, None
    try:
        p = "\x1f".join(path).encode("utf-8")
    except UnicodeEncodeError:
        return False, None
    sptr = ctypes.c_char_p()
    slen = ctypes.c_size_t()
    dval = ctypes.c_double()
    ival = ctypes.c_longlong()
    rc = lib.fj_get(payload, len(payload), p, len(p),
                    ctypes.byref(sptr), ctypes.byref(slen),
                    ctypes.byref(dval), ctypes.byref(ival))
    if rc == 0:
        return False, None
    if rc == 1:
        raw = ctypes.string_at(sptr, slen.value)
        try:
            return True, raw.decode("utf-8")
        except UnicodeDecodeError:
            return False, None
    if rc == 2:
        return True, int(ival.value)
    if rc == 3:
        return True, float(dval.value)
    if rc == 4:
        return True, True
    if rc == 5:
        return True, False
    if rc == 6:
        return True, None
    return False, None
