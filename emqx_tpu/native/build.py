"""Lazy g++ build + ctypes load for the native components.

The .so is cached beside the source keyed by a hash of the source text,
so editing the .cpp triggers a rebuild and stale caches are never
loaded.  Build failures degrade to the Python fallbacks (callers treat
``load_library() is None`` as "no native path").
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_cache: dict = {}


def load_library(name: str = "encoder") -> Optional[ctypes.CDLL]:
    """Compile (if needed) and load ``<name>.cpp`` from this directory."""
    if name in _cache:
        return _cache[name]
    src = os.path.join(_DIR, f"{name}.cpp")
    try:
        with open(src, "rb") as f:
            text = f.read()
    except OSError:
        _cache[name] = None
        return None
    tag = hashlib.sha256(text).hexdigest()[:16]
    sopath = os.path.join(_DIR, f"_{name}-{tag}.so")
    if not os.path.exists(sopath):
        # drop caches of older source revisions before building the new one
        for stale in os.listdir(_DIR):
            if stale.startswith(f"_{name}-") and stale.endswith(".so"):
                try:
                    os.unlink(os.path.join(_DIR, stale))
                except OSError:
                    pass
        lib = _compile(src, sopath)
    else:
        lib = None
    if lib is None:
        try:
            lib = ctypes.CDLL(sopath)
        except OSError as e:
            log.warning("native %s unavailable: %s", name, e)
            lib = None
    _cache[name] = lib
    return lib


def _compile(src: str, sopath: str) -> None:
    """g++ → temp file → atomic rename (concurrent imports race safely)."""
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++20", src, "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, sopath)
    except (subprocess.SubprocessError, OSError) as e:
        err = getattr(e, "stderr", b"") or b""
        log.warning("native build of %s failed: %s %s", src, e,
                    err.decode(errors="replace")[:500])
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return None
