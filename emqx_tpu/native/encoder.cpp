// Native topic encoder: tokenize publish topics and intern words to the
// NFA vocab ids, at C speed.
//
// Round-1 profiling showed the per-word Python dict loop in
// emqx_tpu/ops/compiler.py::encode_topics consuming ~82% of the
// per-batch serving budget (VERDICT.md weak item 3).  The reference's
// equivalent work — emqx_topic:words/1 binary splitting [U] — is
// BEAM-native; ours is this translation unit, loaded via ctypes
// (pybind11 is not in the image).
//
// Contract mirrors emqx_tpu.ops.compiler.encode_topics exactly:
//   * topics arrive as one uint8 buffer, '\0'-separated (MQTT forbids
//     U+0000 in topics, so the separator is unambiguous);
//   * words[r, i] = vocab id of level i (0 = UNKNOWN) for i < D;
//   * lens[r]     = min(n_levels, D + 1);
//   * is_sys[r]   = 1 when the first byte is '$'.
// Padding rows beyond n_topics are left to the caller.
//
// The vocab is pushed incrementally (append-only between compactions,
// matching IncrementalNfa's interning): enc_add_words() extends the
// table without rebuilding it.
//
// Build: g++ -O2 -shared -fPIC -std=c++20 encoder.cpp -o _encoder.so
// (see emqx_tpu/native/build.py — compiled lazily on first import).

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>

namespace {

struct SvHash {
    using is_transparent = void;
    size_t operator()(std::string_view sv) const noexcept {
        return std::hash<std::string_view>{}(sv);
    }
};
struct SvEq {
    using is_transparent = void;
    bool operator()(std::string_view a, std::string_view b) const noexcept {
        return a == b;
    }
};

struct Encoder {
    std::unordered_map<std::string, int32_t, SvHash, SvEq> vocab;
};

}  // namespace

extern "C" {

void* enc_new() { return new Encoder(); }

void enc_free(void* h) { delete static_cast<Encoder*>(h); }

// words: '\0'-separated word bytes; ids: parallel int32 vocab ids.
void enc_add_words(void* h, const uint8_t* buf, int64_t buflen,
                   const int32_t* ids, int32_t n) {
    auto* enc = static_cast<Encoder*>(h);
    const char* p = reinterpret_cast<const char*>(buf);
    const char* end = p + buflen;
    for (int32_t k = 0; k < n && p <= end; ++k) {
        const char* q = static_cast<const char*>(memchr(p, '\0', end - p));
        size_t len = q ? static_cast<size_t>(q - p)
                       : static_cast<size_t>(end - p);
        enc->vocab.emplace(std::string(p, len), ids[k]);
        p += len + 1;
    }
}

int64_t enc_vocab_size(void* h) {
    return static_cast<int64_t>(static_cast<Encoder*>(h)->vocab.size());
}

// Encode n_topics '\0'-separated topics.  Returns n_topics on success;
// -1 when the buffer does not parse into EXACTLY n_topics segments
// consuming every byte (e.g. a topic smuggled a NUL — MQTT forbids it,
// but a row shift here would corrupt OTHER topics' answers, so the
// caller falls back to the Python path for the whole batch).
// words_out is (n_topics, depth) int32 row-major, zero-initialized by
// the caller; lens_out (n_topics,) int32; is_sys_out (n_topics,) uint8.
int32_t enc_encode(void* h, const uint8_t* buf, int64_t buflen,
                   int32_t n_topics, int32_t depth,
                   int32_t* words_out, int32_t* lens_out,
                   uint8_t* is_sys_out) {
    auto* enc = static_cast<Encoder*>(h);
    const char* p = reinterpret_cast<const char*>(buf);
    const char* end = p + buflen;
    int32_t r = 0;
    bool consumed = (buflen == 0);
    while (r < n_topics) {
        const char* tend = static_cast<const char*>(
            memchr(p, '\0', end - p));
        if (tend == nullptr) tend = end;
        is_sys_out[r] = (p < tend && *p == '$') ? 1 : 0;
        int32_t nlevels = 0;
        const char* w = p;
        int32_t* row = words_out + static_cast<int64_t>(r) * depth;
        while (w <= tend) {
            const char* wend = static_cast<const char*>(
                memchr(w, '/', tend - w));
            if (wend == nullptr) wend = tend;
            if (nlevels < depth) {
#if defined(__cpp_lib_generic_unordered_lookup)
                auto it = enc->vocab.find(
                    std::string_view(w, static_cast<size_t>(wend - w)));
#else
                // libstdc++ < 11: no heterogeneous unordered lookup
                auto it = enc->vocab.find(
                    std::string(w, static_cast<size_t>(wend - w)));
#endif
                row[nlevels] = (it != enc->vocab.end()) ? it->second : 0;
            }
            ++nlevels;
            if (wend == tend) break;
            w = wend + 1;
        }
        lens_out[r] = nlevels < depth + 1 ? nlevels : depth + 1;
        ++r;
        if (tend == end) { consumed = true; break; }
        p = tend + 1;
    }
    return (r == n_topics && consumed) ? r : -1;
}

}  // extern "C"
