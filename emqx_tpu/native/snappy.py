"""Snappy codec + CRC-32C: ctypes front for snappy.cpp with pure-Python
fallbacks, plus the xerial stream framing Kafka wraps around raw blocks.

Reference analog: snappy-erlang-nif / crc32cer in the reference's Kafka
bridge dep tree (SURVEY.md §2.4).  The native path is the fast one; the
Python fallback keeps every feature working (compress emits the trivial
all-literals encoding — valid snappy, zero ratio; decompress is a full
bounds-checked format decoder) when no toolchain is present, so codec
availability never changes behavior, only speed and ratio.

Xerial framing (``compress_xerial``/``decompress_xerial``) is the
``\\x82SNAPPY\\x00`` magic + version/compat ints + repeated
[4-byte BE length | raw snappy block] stream the Java Kafka client's
SnappyOutputStream produces; record batches flagged snappy on the wire
carry this framing, not bare blocks.
"""

from __future__ import annotations

import ctypes
import struct
from typing import List, Optional

from .build import load_library

__all__ = [
    "available", "compress", "decompress", "crc32c",
    "compress_xerial", "decompress_xerial",
]

_XERIAL_MAGIC = b"\x82SNAPPY\x00"
_XERIAL_HEAD = _XERIAL_MAGIC + struct.pack("!ii", 1, 1)
_XERIAL_BLOCK = 32 * 1024

_lib = None
_loaded = False


def _load():
    global _lib, _loaded
    if not _loaded:
        _loaded = True
        lib = load_library("snappy")
        if lib is not None:
            lib.sz_max_compressed_length.restype = ctypes.c_int64
            lib.sz_max_compressed_length.argtypes = [ctypes.c_int64]
            lib.sz_compress.restype = ctypes.c_int64
            lib.sz_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int64]
            lib.sz_uncompressed_length.restype = ctypes.c_int64
            lib.sz_uncompressed_length.argtypes = [
                ctypes.c_char_p, ctypes.c_int64]
            lib.sz_uncompress.restype = ctypes.c_int64
            lib.sz_uncompress.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int64]
            lib.sz_crc32c.restype = ctypes.c_uint32
            lib.sz_crc32c.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32]
        _lib = lib
    return _lib


def available() -> bool:
    """True when the native codec loaded (fast path + real compression)."""
    return _load() is not None


# ---- raw block codec --------------------------------------------------------

def compress(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return _py_compress(data)
    cap = lib.sz_max_compressed_length(len(data))
    dst = ctypes.create_string_buffer(cap)
    n = lib.sz_compress(data, len(data), dst, cap)
    if n < 0:  # pragma: no cover - cap is computed from the same lib
        return _py_compress(data)
    return dst.raw[:n]


# preamble sanity cap before allocating: snappy's own format tops out
# around 21x expansion (64-byte copies per 3-byte tag), so anything
# claiming more is corrupt; the absolute ceiling stops a hostile
# few-byte preamble from demanding a 4 GiB buffer per decode attempt
_MAX_RATIO = 24
_MAX_OUTPUT = 256 << 20


def _checked_len(want: int, srclen: int) -> int:
    if want < 0 or want > srclen * _MAX_RATIO + 4096 \
            or want > _MAX_OUTPUT:
        raise ValueError(f"snappy: implausible uncompressed length {want} "
                         f"for {srclen} input bytes")
    return want


def decompress(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return _py_decompress(data)
    want = lib.sz_uncompressed_length(data, len(data))
    if want < 0:
        raise ValueError("snappy: bad preamble")
    want = _checked_len(want, len(data))
    dst = ctypes.create_string_buffer(max(1, want))
    n = lib.sz_uncompress(data, len(data), dst, want)
    if n < 0:
        raise ValueError("snappy: corrupt input")
    return dst.raw[:n]


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _load()
    if lib is None:
        return _py_crc32c(data, crc)
    return lib.sz_crc32c(data, len(data), crc & 0xFFFFFFFF)


# ---- xerial framing ---------------------------------------------------------

def compress_xerial(data: bytes) -> bytes:
    out = [_XERIAL_HEAD]
    for i in range(0, len(data), _XERIAL_BLOCK) or [0]:
        blk = compress(data[i:i + _XERIAL_BLOCK])
        out.append(struct.pack("!i", len(blk)) + blk)
    return b"".join(out)


def decompress_xerial(data: bytes) -> bytes:
    """Decode xerial-framed input; bare raw blocks (some non-Java
    producers skip the framing) are accepted too."""
    if not data.startswith(_XERIAL_MAGIC):
        return decompress(data)
    pos = len(_XERIAL_HEAD)
    out: List[bytes] = []
    while pos < len(data):
        if pos + 4 > len(data):
            raise ValueError("snappy: truncated xerial block header")
        (blen,) = struct.unpack_from("!i", data, pos)
        pos += 4
        if blen < 0 or pos + blen > len(data):
            raise ValueError("snappy: truncated xerial block")
        out.append(decompress(data[pos:pos + blen]))
        pos += blen
    return b"".join(out)


# ---- pure-Python fallbacks --------------------------------------------------

def _py_varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _py_compress(data: bytes) -> bytes:
    """All-literals encoding: valid snappy, no ratio (fallback only)."""
    out = bytearray(_py_varint(len(data)))
    pos = 0
    while pos < len(data):
        chunk = data[pos:pos + (1 << 24)]
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        elif n < (1 << 8):
            out += bytes((60 << 2, n))
        elif n < (1 << 16):
            out += bytes((61 << 2,)) + n.to_bytes(2, "little")
        else:
            out += bytes((62 << 2,)) + n.to_bytes(3, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)


def _py_decompress(data: bytes) -> bytes:
    if not data:
        raise ValueError("snappy: empty input")
    want = shift = pos = 0
    while True:
        if pos >= len(data) or shift > 28:
            raise ValueError("snappy: bad preamble")
        b = data[pos]
        pos += 1
        want |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    want = _checked_len(want, len(data))
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:
            ln = (tag >> 2) + 1
            if ln > 60:
                nb = ln - 60
                ln = int.from_bytes(data[pos:pos + nb], "little") + 1
                pos += nb
            if pos + ln > len(data):
                raise ValueError("snappy: truncated literal")
            out += data[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:
            ln = ((tag >> 2) & 7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            off = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ValueError("snappy: bad copy offset")
        if off >= ln:
            out += out[-off:len(out) - off + ln]
        else:
            for _ in range(ln):
                out.append(out[-off])
    if len(out) != want:
        raise ValueError("snappy: length mismatch")
    return bytes(out)


_PY_CRC_TAB: Optional[List[int]] = None


def _py_crc32c(data: bytes, crc: int = 0) -> int:
    global _PY_CRC_TAB
    if _PY_CRC_TAB is None:
        tab = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            tab.append(c)
        _PY_CRC_TAB = tab
    tab = _PY_CRC_TAB
    c = (crc & 0xFFFFFFFF) ^ 0xFFFFFFFF
    for b in data:
        c = tab[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF
