"""LZ4: ctypes front for lz4.cpp (block codec + xxHash32) plus the LZ4
FRAME format (magic 0x184D2204) that Kafka's codec-3 record batches
carry.  Same posture as the snappy module: pure-Python fallbacks keep
decode working without a toolchain (the fallback compressor emits
uncompressed frame blocks — valid LZ4F, zero ratio), and a preamble
sanity cap bounds allocations against hostile inputs.
"""

from __future__ import annotations

import ctypes
import struct
from typing import List

from .build import load_library

__all__ = ["available", "compress_frame", "decompress_frame", "xxh32",
           "block_compress", "block_decompress"]

_MAGIC = 0x184D2204
_BLOCK_MAX = 64 * 1024          # BD byte 0x40 = 64 KB max block size
_MAX_RATIO = 256                # lz4 tops out at ~255x (run compression)
_MAX_OUTPUT = 256 << 20

_lib = None
_loaded = False


def _load():
    global _lib, _loaded
    if not _loaded:
        _loaded = True
        lib = load_library("lz4")
        if lib is not None:
            lib.lz4_max_compressed_length.restype = ctypes.c_int64
            lib.lz4_max_compressed_length.argtypes = [ctypes.c_int64]
            lib.lz4_compress.restype = ctypes.c_int64
            lib.lz4_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int64]
            lib.lz4_decompress.restype = ctypes.c_int64
            lib.lz4_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int64]
            lib.lz4_decompress_hist.restype = ctypes.c_int64
            lib.lz4_decompress_hist.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int64,
                ctypes.c_int64]
            lib.lz4_xxh32.restype = ctypes.c_uint32
            lib.lz4_xxh32.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def xxh32(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is None:
        return _py_xxh32(data, seed)
    return lib.lz4_xxh32(data, len(data), seed & 0xFFFFFFFF)


# ---- raw block codec --------------------------------------------------------

def block_compress(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("lz4: no native toolchain (compress)")
    cap = lib.lz4_max_compressed_length(len(data))
    dst = ctypes.create_string_buffer(max(1, cap))
    n = lib.lz4_compress(data, len(data), dst, cap)
    if n < 0:  # pragma: no cover - cap computed by the same lib
        raise ValueError("lz4: compress failed")
    return dst.raw[:n]


def block_decompress(data: bytes, want: int) -> bytes:
    if want < 0 or want > _MAX_OUTPUT:
        raise ValueError(f"lz4: implausible block size {want}")
    lib = _load()
    if lib is None:
        return _py_block_decompress(data, want)
    dst = ctypes.create_string_buffer(max(1, want))
    n = lib.lz4_decompress(data, len(data), dst, want)
    if n != want:                   # capacity decode + exact-size check
        raise ValueError("lz4: corrupt block")
    return dst.raw[:n]


# ---- LZ4 frame format -------------------------------------------------------

def compress_frame(data: bytes) -> bytes:
    """One LZ4 frame: FLG = v01 | block-independent | content-size
    absent, no checksums (Kafka's java client accepts this shape);
    blocks of up to 64 KB, each stored compressed unless incompressible
    (high bit of the block length = uncompressed)."""
    flg = 0x60                               # version 01, blk indep
    bd = 0x40                                # 64 KB max block
    head = struct.pack("<I", _MAGIC) + bytes([flg, bd])
    hc = (xxh32(bytes([flg, bd])) >> 8) & 0xFF
    out: List[bytes] = [head, bytes([hc])]
    native = available()
    for i in range(0, len(data), _BLOCK_MAX):
        blk = data[i:i + _BLOCK_MAX]
        comp = block_compress(blk) if native else blk
        if not native or len(comp) >= len(blk):
            out.append(struct.pack("<I", len(blk) | 0x80000000) + blk)
        else:
            out.append(struct.pack("<I", len(comp)) + comp)
    out.append(struct.pack("<I", 0))         # endmark
    return b"".join(out)


def decompress_frame(data: bytes) -> bytes:
    if len(data) < 7 or struct.unpack_from("<I", data)[0] != _MAGIC:
        raise ValueError("lz4: bad frame magic")
    flg = data[4]
    if (flg >> 6) != 0b01:
        raise ValueError("lz4: unsupported frame version")
    # frame descriptor = FLG + BD [+ 8B content size] [+ 4B dictID],
    # ALL covered by the HC byte that follows (spec order — a frame
    # from liblz4 with store_size=True was rejected before this fix)
    dlen = 2 + (8 if flg & 0x08 else 0) + (4 if flg & 0x01 else 0)
    if 4 + dlen + 1 > len(data):
        raise ValueError("lz4: truncated frame descriptor")
    if (xxh32(data[4:4 + dlen]) >> 8) & 0xFF != data[4 + dlen]:
        raise ValueError("lz4: frame header checksum mismatch")
    pos = 4 + dlen + 1
    has_block_cksum = bool(flg & 0x10)
    has_content_cksum = bool(flg & 0x04)
    block_indep = bool(flg & 0x20)
    # BD byte bounds every block's decoded size (ids 4..7 = 64 KB..4 MB)
    # — sizing buffers from it instead of the worst-case ratio avoids
    # ~4 MB zero-filled allocations per 64 KB block on the fetch path
    bd_id = (data[5] >> 4) & 0x07
    block_max = 1 << (8 + 2 * bd_id) if 4 <= bd_id <= 7 else _BLOCK_MAX * 64
    hist = b""
    out: List[bytes] = []
    total = 0
    while True:
        if pos + 4 > len(data):
            raise ValueError("lz4: truncated frame")
        (bsz,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if bsz == 0:
            break                            # endmark
        raw = bool(bsz & 0x80000000)
        bsz &= 0x7FFFFFFF
        if pos + bsz > len(data):
            raise ValueError("lz4: truncated block")
        blk = data[pos:pos + bsz]
        pos += bsz
        if has_block_cksum:
            if pos + 4 > len(data):
                raise ValueError("lz4: truncated block checksum")
            (ck,) = struct.unpack_from("<I", data, pos)
            pos += 4
            if xxh32(blk) != ck:
                raise ValueError("lz4: block checksum mismatch")
        if raw:
            dec = blk
        else:
            # the ratio bound additionally stops hostile tiny blocks
            # claiming the full BD budget
            want = min(block_max, len(blk) * _MAX_RATIO + 64)
            dec = _block_sized(blk, want, hist)
        out.append(dec)
        total += len(dec)
        if not block_indep:
            hist = (hist + dec)[-_HIST_MAX:]
        if total > _MAX_OUTPUT:
            raise ValueError("lz4: output exceeds cap")
    if has_content_cksum:
        if pos + 4 > len(data):
            raise ValueError("lz4: truncated content checksum")
        (ck,) = struct.unpack_from("<I", data, pos)
        body = b"".join(out)
        if xxh32(body) != ck:
            raise ValueError("lz4: content checksum mismatch")
        return body
    return b"".join(out)


def _block_sized(blk: bytes, max_out: int, hist: bytes) -> bytes:
    """Decompress one frame block of unknown exact size, with the
    previous blocks' tail as match history (the frame format's LINKED
    mode — liblz4's default — lets matches reach back up to 64 KB
    across block boundaries).  Native capacity-mode decode when the
    codec is loaded (the fetch hot path), python fallback otherwise."""
    lib = _load()
    if lib is None:
        return _py_block_decompress(blk, max_out, exact=False,
                                    prefix=hist)
    cap = len(hist) + max_out
    dst = ctypes.create_string_buffer(max(1, cap))
    if hist:
        dst[:len(hist)] = hist
    n = lib.lz4_decompress_hist(blk, len(blk), dst, cap, len(hist))
    if n < 0:
        raise ValueError("lz4: corrupt block")
    return dst.raw[len(hist):len(hist) + n]


# ---- pure-Python fallbacks --------------------------------------------------

_HIST_MAX = 64 * 1024


def _py_block_decompress(data: bytes, want: int,
                         exact: bool = True,
                         prefix: bytes = b"") -> bytes:
    out = bytearray(prefix)
    want += len(prefix)
    ip, n = 0, len(data)
    while ip < n:
        token = data[ip]
        ip += 1
        lit = token >> 4
        if lit == 15:
            while True:
                if ip >= n:
                    raise ValueError("lz4: truncated literal length")
                b = data[ip]
                ip += 1
                lit += b
                if b != 255:
                    break
        if ip + lit > n or len(out) + lit > want:
            raise ValueError("lz4: truncated/oversize literals")
        out += data[ip:ip + lit]
        ip += lit
        if ip >= n:
            break
        if ip + 2 > n:
            raise ValueError("lz4: truncated offset")
        off = data[ip] | (data[ip + 1] << 8)
        ip += 2
        if off == 0 or off > len(out):
            raise ValueError("lz4: bad match offset")
        ml = token & 0x0F
        if ml == 15:
            while True:
                if ip >= n:
                    raise ValueError("lz4: truncated match length")
                b = data[ip]
                ip += 1
                ml += b
                if b != 255:
                    break
        ml += 4
        if len(out) + ml > want:
            raise ValueError("lz4: oversize match")
        if off >= ml:
            out += out[-off:len(out) - off + ml]
        else:
            for _ in range(ml):
                out.append(out[-off])
    if exact and len(out) != want:
        raise ValueError("lz4: length mismatch")
    return bytes(out[len(prefix):])


def _py_xxh32(data: bytes, seed: int = 0) -> int:
    P1, P2, P3, P4, P5 = (2654435761, 2246822519, 3266489917,
                          668265263, 374761393)
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    n = len(data)
    i = 0
    if n >= 16:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        while i + 16 <= n:
            for j, v in enumerate((v1, v2, v3, v4)):
                w = int.from_bytes(data[i + 4 * j:i + 4 * j + 4], "little")
                v = rotl((v + w * P2) & M, 13) * P1 & M
                if j == 0:
                    v1 = v
                elif j == 1:
                    v2 = v
                elif j == 2:
                    v3 = v
                else:
                    v4 = v
            i += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i + 4 <= n:
        h = rotl((h + int.from_bytes(data[i:i + 4], "little") * P3) & M,
                 17) * P4 & M
        i += 4
    while i < n:
        h = rotl((h + data[i] * P5) & M, 11) * P1 & M
        i += 1
    h ^= h >> 15
    h = h * P2 & M
    h ^= h >> 13
    h = h * P3 & M
    h ^= h >> 16
    return h
