// Snappy block-format codec + hardware CRC-32C.
//
// Behavioral reference: the reference broker links google/snappy via its
// Kafka bridge (snappy-erlang / crc32cer deps, SURVEY.md §2.4) for
// record-batch compression.  This is an independent implementation of
// the PUBLIC Snappy format (format_description.txt semantics: varint
// preamble + literal/copy tagged elements), written for this runtime —
// greedy 4-byte-hash matcher, bounds-checked decompressor.  The xerial
// stream framing Kafka wraps around raw blocks lives in snappy.py (it
// is trivial byte plumbing; only the block codec is hot).
//
// CRC-32C (Castagnoli) is here too: the Kafka batch checksum was a
// per-byte Python table loop (~10 MB/s); the SSE4.2 crc32 instruction
// does 8 bytes/cycle.  Runtime-dispatched so the .so still works on
// cpus without SSE4.2 (slice-by-8 software fallback).
//
// Exported (all extern "C", plain buffers, no allocation across the
// boundary — caller supplies dst sized by sz_max_compressed_length /
// the preamble):
//   sz_max_compressed_length(n)              -> worst-case dst size
//   sz_compress(src,n,dst,cap)               -> compressed size, -1 on cap
//   sz_uncompressed_length(src,n)            -> preamble value, -1 bad
//   sz_uncompress(src,n,dst,cap)             -> size, -1 on corrupt/cap
//   sz_crc32c(buf,n,init)                    -> uint32
#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

// ---- varint (LE 7-bit groups, unsigned) -----------------------------------

inline size_t varint_put(uint8_t* dst, uint64_t v) {
    size_t i = 0;
    while (v >= 0x80) { dst[i++] = uint8_t(v) | 0x80; v >>= 7; }
    dst[i++] = uint8_t(v);
    return i;
}

// returns bytes consumed, 0 on truncation/overflow (>32 bits rejected:
// snappy caps uncompressed length at 2^32-1)
inline size_t varint_get(const uint8_t* p, size_t n, uint64_t* out) {
    uint64_t v = 0;
    for (size_t i = 0; i < n && i < 5; ++i) {
        v |= uint64_t(p[i] & 0x7F) << (7 * i);
        if (!(p[i] & 0x80)) {
            if (i == 4 && (p[i] >> 4)) return 0;       // > 32 bits
            *out = v;
            return i + 1;
        }
    }
    return 0;
}

// ---- compressor -----------------------------------------------------------

constexpr int kHashBits = 14;                          // 16K-entry table
constexpr size_t kTabSize = size_t(1) << kHashBits;

inline uint32_t load32(const uint8_t* p) {
    uint32_t v; std::memcpy(&v, p, 4); return v;
}
inline uint64_t load64(const uint8_t* p) {
    uint64_t v; std::memcpy(&v, p, 8); return v;
}

inline uint32_t hash4(uint32_t v) {
    return (v * 0x1E35A7BDu) >> (32 - kHashBits);
}

// emit one literal run [lit, lit+len)
inline uint8_t* emit_literal(uint8_t* op, const uint8_t* lit, size_t len) {
    if (len == 0) return op;
    size_t n = len - 1;
    if (n < 60) {
        *op++ = uint8_t(n << 2);
    } else if (n < (1u << 8)) {
        *op++ = uint8_t(60 << 2); *op++ = uint8_t(n);
    } else if (n < (1u << 16)) {
        *op++ = uint8_t(61 << 2);
        *op++ = uint8_t(n); *op++ = uint8_t(n >> 8);
    } else if (n < (1u << 24)) {
        *op++ = uint8_t(62 << 2);
        *op++ = uint8_t(n); *op++ = uint8_t(n >> 8); *op++ = uint8_t(n >> 16);
    } else {
        *op++ = uint8_t(63 << 2);
        *op++ = uint8_t(n); *op++ = uint8_t(n >> 8);
        *op++ = uint8_t(n >> 16); *op++ = uint8_t(n >> 24);
    }
    std::memcpy(op, lit, len);
    return op + len;
}

// emit copies covering len bytes at `offset` back; splits into <=64 chunks
inline uint8_t* emit_copy(uint8_t* op, size_t offset, size_t len) {
    while (len >= 68) {                                // 2-byte-offset, 64
        *op++ = uint8_t((63 << 2) | 2);
        *op++ = uint8_t(offset); *op++ = uint8_t(offset >> 8);
        len -= 64;
    }
    if (len > 64) {                                    // leave >=4 for tail
        *op++ = uint8_t((59 << 2) | 2);                // 60-byte copy
        *op++ = uint8_t(offset); *op++ = uint8_t(offset >> 8);
        len -= 60;
    }
    if (len >= 4 && len < 12 && offset < 2048) {       // 1-byte-offset form
        *op++ = uint8_t(((offset >> 8) << 5) | ((len - 4) << 2) | 1);
        *op++ = uint8_t(offset);
    } else {
        *op++ = uint8_t(((len - 1) << 2) | 2);
        *op++ = uint8_t(offset); *op++ = uint8_t(offset >> 8);
    }
    return op;
}

}  // namespace

extern "C" {

int64_t sz_max_compressed_length(int64_t n) {
    // preamble (<=5) + worst case all-literals with chunk headers
    return 32 + n + n / 6;
}

int64_t sz_compress(const uint8_t* src, int64_t srclen,
                    uint8_t* dst, int64_t dstcap) {
    if (srclen < 0 || dstcap < sz_max_compressed_length(srclen)) return -1;
    uint8_t* op = dst;
    op += varint_put(op, uint64_t(srclen));
    if (srclen == 0) return op - dst;

    const size_t n = size_t(srclen);
    static thread_local uint16_t* table = nullptr;
    if (!table) table = new uint16_t[kTabSize];
    // positions are stored mod 64K against a sliding base so a 16-bit
    // table covers arbitrarily long inputs (offsets >64K never match
    // anyway: snappy copies reach back at most 64K-1 in 2-byte form and
    // our emitter never uses the 4-byte-offset form)
    size_t ip = 0, lit_start = 0;
    if (n >= 15) {
        std::memset(table, 0, kTabSize * sizeof(uint16_t));
        size_t base = 0;                               // table entries are
        const size_t limit = n - 4;                    // (pos - base) + 1
        while (ip + 4 <= n) {
            if (ip - base >= 60000) {                  // re-base the window
                std::memset(table, 0, kTabSize * sizeof(uint16_t));
                base = ip;
            }
            uint32_t h = hash4(load32(src + ip));
            uint16_t prev = table[h];
            table[h] = uint16_t(ip - base + 1);
            if (prev == 0) { ++ip; continue; }
            size_t cand = base + prev - 1;
            size_t off = ip - cand;
            if (off == 0 || off > 65535 ||
                load32(src + cand) != load32(src + ip)) { ++ip; continue; }
            // extend the match
            size_t len = 4;
            while (ip + len + 8 <= n &&
                   load64(src + cand + len) == load64(src + ip + len))
                len += 8;
            while (ip + len < n && src[cand + len] == src[ip + len]) ++len;
            op = emit_literal(op, src + lit_start, ip - lit_start);
            op = emit_copy(op, off, len);
            // seed the table inside the match so runs keep matching
            size_t next = ip + len;
            for (size_t p = ip + 1; p + 4 <= n && p < next &&
                                    p - base < 65535; p += 13)
                table[hash4(load32(src + p))] = uint16_t(p - base + 1);
            ip = lit_start = next;
            if (ip > limit) break;
        }
    }
    op = emit_literal(op, src + lit_start, n - lit_start);
    return op - dst;
}

int64_t sz_uncompressed_length(const uint8_t* src, int64_t srclen) {
    if (srclen <= 0) return -1;
    uint64_t v;
    if (!varint_get(src, size_t(srclen), &v)) return -1;
    return int64_t(v);
}

int64_t sz_uncompress(const uint8_t* src, int64_t srclen,
                      uint8_t* dst, int64_t dstcap) {
    if (srclen <= 0) return -1;
    uint64_t want;
    size_t ip = varint_get(src, size_t(srclen), &want);
    if (!ip || int64_t(want) > dstcap) return -1;
    const size_t n = size_t(srclen);
    size_t op = 0;
    while (ip < n) {
        uint8_t tag = src[ip++];
        size_t len, off;
        switch (tag & 3) {
        case 0: {                                      // literal
            len = (tag >> 2) + 1;
            if (len > 60) {
                size_t nb = len - 60;                  // 1..4 length bytes
                if (ip + nb > n) return -1;
                len = 0;
                for (size_t i = 0; i < nb; ++i)
                    len |= size_t(src[ip + i]) << (8 * i);
                len += 1;
                ip += nb;
            }
            if (ip + len > n || op + len > want) return -1;
            std::memcpy(dst + op, src + ip, len);
            ip += len; op += len;
            continue;
        }
        case 1:                                        // copy, 1-byte offset
            if (ip >= n) return -1;
            len = ((tag >> 2) & 7) + 4;
            off = (size_t(tag >> 5) << 8) | src[ip++];
            break;
        case 2:                                        // copy, 2-byte offset
            if (ip + 2 > n) return -1;
            len = (tag >> 2) + 1;
            off = size_t(src[ip]) | (size_t(src[ip + 1]) << 8);
            ip += 2;
            break;
        default:                                       // copy, 4-byte offset
            if (ip + 4 > n) return -1;
            len = (tag >> 2) + 1;
            off = size_t(src[ip]) | (size_t(src[ip + 1]) << 8) |
                  (size_t(src[ip + 2]) << 16) | (size_t(src[ip + 3]) << 24);
            ip += 4;
            break;
        }
        if (off == 0 || off > op || op + len > want) return -1;
        if (off >= len) {
            std::memmove(dst + op, dst + op - off, len);
            op += len;
        } else {                                       // overlapping run
            for (size_t i = 0; i < len; ++i, ++op)
                dst[op] = dst[op - off];
        }
    }
    return op == want ? int64_t(op) : -1;
}

// ---- CRC-32C --------------------------------------------------------------

namespace {

uint32_t crc_tab8[8][256];

bool crc_tab_init() {
    constexpr uint32_t poly = 0x82F63B78u;
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = i;
        for (int k = 0; k < 8; ++k) c = (c >> 1) ^ (poly & (0u - (c & 1)));
        crc_tab8[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
        for (int t = 1; t < 8; ++t)
            crc_tab8[t][i] = (crc_tab8[t - 1][i] >> 8) ^
                             crc_tab8[0][crc_tab8[t - 1][i] & 0xFF];
    return true;
}

uint32_t crc32c_sw(const uint8_t* p, size_t n, uint32_t c) {
    // C++11 magic static: thread-safe one-time init (a plain bool flag
    // races on weakly-ordered cpus — asyncio.to_thread workers call in)
    static const bool inited = crc_tab_init();
    (void)inited;
    while (n >= 8) {                                   // slice-by-8
        c ^= uint32_t(p[0]) | (uint32_t(p[1]) << 8) |
             (uint32_t(p[2]) << 16) | (uint32_t(p[3]) << 24);
        c = crc_tab8[7][c & 0xFF] ^ crc_tab8[6][(c >> 8) & 0xFF] ^
            crc_tab8[5][(c >> 16) & 0xFF] ^ crc_tab8[4][c >> 24] ^
            crc_tab8[3][p[4]] ^ crc_tab8[2][p[5]] ^
            crc_tab8[1][p[6]] ^ crc_tab8[0][p[7]];
        p += 8; n -= 8;
    }
    while (n--) c = (c >> 8) ^ crc_tab8[0][(c ^ *p++) & 0xFF];
    return c;
}

#if defined(__x86_64__) || defined(__i386__)
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(const uint8_t* p, size_t n, uint32_t c) {
    uint64_t c64 = c;
    while (n >= 8) {
        uint64_t v; std::memcpy(&v, p, 8);
        c64 = __builtin_ia32_crc32di(c64, v);
        p += 8; n -= 8;
    }
    c = uint32_t(c64);
    while (n--) c = __builtin_ia32_crc32qi(c, *p++);
    return c;
}
#endif

}  // namespace

uint32_t sz_crc32c(const uint8_t* p, int64_t n, uint32_t init) {
    uint32_t c = init ^ 0xFFFFFFFFu;
#if defined(__x86_64__) || defined(__i386__)
    if (__builtin_cpu_supports("sse4.2"))
        return crc32c_hw(p, size_t(n), c) ^ 0xFFFFFFFFu;
#endif
    return crc32c_sw(p, size_t(n), c) ^ 0xFFFFFFFFu;
}

}  // extern "C"
