// Native incremental NFA table: bulk build + O(delta) mutation at
// 10M-filter scale, byte-compatible with the Python compiler's layout
// (emqx_tpu/ops/compiler.py): node_tab (S,4) int32 rows
// [plus_child, hash_accept, accept, 0] and a 2-choice 4-slot cuckoo
// edge_tab (Hb, BUCKET_SLOTS*4) int32 of [state, word, next, 0] slots, with the SAME
// uint32 bucket-hash mixing, so the device kernel consumes either
// producer's arrays unchanged.
//
// Behavioral reference: emqx_trie:insert/1 delete/1 match/1 [U]
// (SURVEY.md §2.1).  The Python IncrementalNfa (ops/incremental.py) is
// the semantics oracle; this is the scale path — a Python object trie at
// 20M nodes costs GBs and minutes, this builds 10M filters in seconds.
//
// C ABI only (ctypes; pybind11 is not in the image).  All buffers are
// caller-allocated numpy arrays sized via nfa_sizes/nfa_delta_sizes.

#include <cstdint>
#include <cstring>
#include <deque>
#include <random>
#include <string>
#include <string_view>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr int BUCKET_SLOTS = 2;   // 32 B rows gather 2.2x faster than
                                  // 64 B on v5e (see compiler.py)
constexpr int ROW = BUCKET_SLOTS * 4;   // int32s per bucket row
constexpr int MAX_KICKS = 500;

inline uint32_t bucket_hash(uint32_t state, uint32_t word, uint32_t seed,
                            uint32_t mask) {
  uint32_t h = state * 2654435761u + word * 2246822519u + seed;
  h ^= h >> 16;
  h *= 3266489917u;
  h ^= h >> 13;
  return h & mask;
}

inline uint64_t ckey(int32_t sid, int32_t wid) {
  return (uint64_t(uint32_t(sid)) << 32) | uint32_t(wid);
}

struct Node {
  int32_t plus = -1;       // '+' child sid
  int32_t hash_aid = -1;   // '#' accept id
  int32_t aid = -1;        // end accept id
  int32_t parent = -1;
  int32_t pword = -1;      // vocab id of parent edge; -2 = '+' edge; -1 root
  uint32_t nlit = 0;       // count of literal children
  bool live = false;
};

struct Nfa {
  int32_t depth;
  uint64_t epoch = 0;
  // -2 = no device consumer (freed aids reusable immediately);
  // -1 = consumer attached, nothing acked yet (no reuse);
  // >=0 = highest epoch the device has applied
  int64_t device_epoch = -2;
  uint64_t aid_reuses = 0;
  int32_t n_states = 1;
  int64_t n_edges = 0;
  int64_t n_filters = 0;

  std::vector<Node> nodes;           // sid-indexed
  std::vector<int32_t> free_sids;
  std::unordered_map<uint64_t, int32_t> children;  // (sid,wid) -> child

  // heterogeneous lookup: find(string_view) without a temp std::string —
  // the build path does tens of millions of interning probes
  struct SvHash {
    using is_transparent = void;
    size_t operator()(std::string_view s) const noexcept {
      return std::hash<std::string_view>{}(s);
    }
  };
  struct SvEq {
    using is_transparent = void;
    bool operator()(std::string_view a, std::string_view b) const noexcept {
      return a == b;
    }
  };
  std::unordered_map<std::string, int32_t, SvHash, SvEq> vocab;
  std::vector<std::string> vocab_list;             // id-1 -> word

  std::vector<std::string> accepts;  // aid -> filter ("" = hole)
  std::vector<uint8_t> accept_live;
  std::deque<std::pair<uint64_t, int32_t>> free_aids;  // (freed_epoch, aid)
  // alias aids: ids in the same accept space with NO trie states —
  // filters deeper than the table that still need one id→filter map
  // (mirrors IncrementalNfa.alloc_alias/free_alias)
  std::unordered_set<int32_t> alias_aids;

  std::vector<int32_t> edge_tab;  // Hb * ROW
  uint32_t Hb;
  uint32_t seeds[2];
  std::mt19937 rng;

  std::unordered_set<int32_t> dirty_states;
  std::unordered_set<int32_t> dirty_buckets;
  bool resized = false;

  Nfa(int32_t depth_, uint32_t state_bucket, uint32_t edge_bucket,
      uint64_t seed)
      : depth(depth_), rng(seed) {
    nodes.resize(state_bucket);
    nodes[0].live = true;
    for (int32_t i = int32_t(state_bucket) - 1; i >= 1; --i)
      free_sids.push_back(i);
    Hb = 8;
    while (Hb < edge_bucket) Hb <<= 1;
    edge_tab.assign(size_t(Hb) * ROW, -1);
    reseed();
    dirty_states.insert(0);
  }

  void reseed() {
    std::uniform_int_distribution<uint32_t> d(1, 0x7fffffffu);
    seeds[0] = d(rng);
    seeds[1] = d(rng);
  }

  uint32_t S() const { return uint32_t(nodes.size()); }

  int32_t alloc_sid() {
    if (free_sids.empty()) {
      size_t old = nodes.size();
      nodes.resize(old * 2);
      for (int32_t i = int32_t(old * 2) - 1; i >= int32_t(old); --i)
        free_sids.push_back(i);
      resized = true;
    }
    int32_t sid = free_sids.back();
    free_sids.pop_back();
    nodes[sid] = Node{};
    nodes[sid].live = true;
    return sid;
  }

  int32_t alloc_aid(std::string_view flt) {
    if (!free_aids.empty()) {
      auto [fe, aid] = free_aids.front();
      if (device_epoch == -2 ||
          (device_epoch >= 0 && fe <= uint64_t(device_epoch))) {
        free_aids.pop_front();
        accepts[aid].assign(flt);
        accept_live[aid] = 1;
        ++aid_reuses;
        return aid;
      }
    }
    accepts.emplace_back(flt);
    accept_live.push_back(1);
    return int32_t(accepts.size()) - 1;
  }

  void free_aid(int32_t aid) {
    accepts[aid].clear();
    accept_live[aid] = 0;
    free_aids.emplace_back(epoch + 1, aid);
  }

  int32_t intern(std::string_view w) {
#if defined(__cpp_lib_generic_unordered_lookup)
    auto it = vocab.find(w);
#else
    // libstdc++ < 11 lacks heterogeneous unordered lookup: pay the temp
    auto it = vocab.find(std::string(w));
#endif
    if (it != vocab.end()) return it->second;
    int32_t id = int32_t(vocab.size()) + 1;  // 0 reserved UNKNOWN
    vocab.emplace(std::string(w), id);
    vocab_list.emplace_back(w);
    return id;
  }

  int32_t vocab_get(std::string_view w) const {
#if defined(__cpp_lib_generic_unordered_lookup)
    auto it = vocab.find(w);
#else
    auto it = vocab.find(std::string(w));
#endif
    return it == vocab.end() ? 0 : it->second;
  }

  // -- cuckoo edges --------------------------------------------------------

  bool place(std::vector<int32_t>& tab, uint32_t hb, const uint32_t sd[2],
             int32_t s, int32_t w, int32_t nxt,
             std::unordered_set<int32_t>* dirty) {
    int32_t cs = s, cw = w, cn = nxt;
    uint32_t mask = hb - 1;
    std::uniform_int_distribution<int> coin(0, 1), slot(0, BUCKET_SLOTS - 1);
    for (int k = 0; k < MAX_KICKS; ++k) {
      uint32_t b[2] = {bucket_hash(cs, cw, sd[0], mask),
                       bucket_hash(cs, cw, sd[1], mask)};
      for (int j = 0; j < 2; ++j) {
        int32_t* row = &tab[size_t(b[j]) * ROW];
        for (int i = 0; i < BUCKET_SLOTS; ++i) {
          if (row[i * 4] < 0) {
            row[i * 4] = cs;
            row[i * 4 + 1] = cw;
            row[i * 4 + 2] = cn;
            if (dirty) dirty->insert(int32_t(b[j]));
            return true;
          }
        }
      }
      uint32_t vb = b[coin(rng)];
      int vi = slot(rng) * 4;
      int32_t* row = &tab[size_t(vb) * ROW];
      int32_t vs = row[vi], vw = row[vi + 1], vn = row[vi + 2];
      row[vi] = cs;
      row[vi + 1] = cw;
      row[vi + 2] = cn;
      if (dirty) dirty->insert(int32_t(vb));
      cs = vs;
      cw = vw;
      cn = vn;
    }
    // homeless victim: put it back conceptually by failing the caller
    // (caller grows and re-places everything including (cs,cw,cn))
    pending[0] = cs;
    pending[1] = cw;
    pending[2] = cn;
    has_pending = true;
    return false;
  }

  int32_t pending[3] = {-1, -1, -1};
  bool has_pending = false;

  void edge_insert(int32_t s, int32_t wid, int32_t nxt) {
    if (n_edges >= int64_t(Hb) * BUCKET_SLOTS * 3 / 4) grow(false);
    if (!place(edge_tab, Hb, seeds, s, wid, nxt, &dirty_buckets)) {
      // failed walk left the new edge placed and ONE homeless victim in
      // `pending`; grow() re-places every live edge plus the victim
      grow(true);
    }
    ++n_edges;
  }

  void grow(bool with_pending) {
    std::vector<std::pair<uint64_t, int32_t>> live;
    live.reserve(size_t(n_edges) + 1);
    for (size_t b = 0; b < Hb; ++b) {
      const int32_t* row = &edge_tab[b * ROW];
      for (int i = 0; i < BUCKET_SLOTS; ++i)
        if (row[i * 4] >= 0)
          live.emplace_back(ckey(row[i * 4], row[i * 4 + 1]), row[i * 4 + 2]);
    }
    if (with_pending && has_pending) {
      live.emplace_back(ckey(pending[0], pending[1]), pending[2]);
      has_pending = false;
    }
    uint32_t hb = Hb;
    for (;;) {
      hb <<= 1;
      for (int attempt = 0; attempt < 4; ++attempt) {
        uint32_t sd[2];
        std::uniform_int_distribution<uint32_t> d(1, 0x7fffffffu);
        sd[0] = d(rng);
        sd[1] = d(rng);
        std::vector<int32_t> tab(size_t(hb) * ROW, -1);
        bool ok = true;
        for (auto& [key, nxt] : live) {
          int32_t s = int32_t(key >> 32), w = int32_t(key & 0xffffffff);
          if (!place(tab, hb, sd, s, w, nxt, nullptr)) {
            has_pending = false;
            ok = false;
            break;
          }
        }
        if (ok) {
          edge_tab.swap(tab);
          Hb = hb;
          seeds[0] = sd[0];
          seeds[1] = sd[1];
          resized = true;
          dirty_buckets.clear();
          return;
        }
      }
    }
  }

  void edge_delete(int32_t s, int32_t wid) {
    uint32_t mask = Hb - 1;
    for (int j = 0; j < 2; ++j) {
      uint32_t b = bucket_hash(s, wid, seeds[j], mask);
      int32_t* row = &edge_tab[size_t(b) * ROW];
      for (int i = 0; i < BUCKET_SLOTS; ++i) {
        if (row[i * 4] == s && row[i * 4 + 1] == wid) {
          row[i * 4] = row[i * 4 + 1] = row[i * 4 + 2] = -1;
          dirty_buckets.insert(int32_t(b));
          --n_edges;
          return;
        }
      }
    }
  }

  // -- filter mutation -----------------------------------------------------

  // split a filter/topic into words; returns false if > depth levels
  static bool split(std::string_view s, std::vector<std::string_view>& out) {
    out.clear();
    size_t start = 0;
    for (size_t i = 0; i <= s.size(); ++i) {
      if (i == s.size() || s[i] == '/') {
        out.push_back(s.substr(start, i - start));
        start = i + 1;
      }
    }
    return true;
  }

  // returns 1 added, 0 duplicate, -1 invalid (too deep, or '#' not in
  // final position — a mid-filter '#' would otherwise truncate-insert a
  // DIFFERENT filter that remove()/aid_of() can never find again)
  int add(std::string_view flt) {
    std::vector<std::string_view> ws;
    split(flt, ws);
    if (int32_t(ws.size()) > depth) return -1;
    for (size_t i = 0; i + 1 < ws.size(); ++i)
      if (ws[i] == "#") return -1;
    int32_t sid = 0;
    for (size_t i = 0; i < ws.size(); ++i) {
      std::string_view w = ws[i];
      if (w == "#") {
        Node& n = nodes[sid];
        if (n.hash_aid >= 0) return 0;
        n.hash_aid = alloc_aid(flt);
        dirty_states.insert(sid);
        ++n_filters;
        ++epoch;
        return 1;
      }
      if (w == "+") {
        if (nodes[sid].plus < 0) {
          int32_t child = alloc_sid();
          nodes[child].parent = sid;
          nodes[child].pword = -2;
          nodes[sid].plus = child;
          dirty_states.insert(sid);
          dirty_states.insert(child);
          ++n_states;
        }
        sid = nodes[sid].plus;
      } else {
        int32_t wid = intern(w);
        auto it = children.find(ckey(sid, wid));
        if (it == children.end()) {
          int32_t child = alloc_sid();
          nodes[child].parent = sid;
          nodes[child].pword = wid;
          children.emplace(ckey(sid, wid), child);
          ++nodes[sid].nlit;
          dirty_states.insert(child);
          edge_insert(sid, wid, child);
          ++n_states;
          sid = child;
        } else {
          sid = it->second;
        }
      }
    }
    Node& n = nodes[sid];
    if (n.aid >= 0) return 0;
    n.aid = alloc_aid(flt);
    dirty_states.insert(sid);
    ++n_filters;
    ++epoch;
    return 1;
  }

  int remove(std::string_view flt) {
    std::vector<std::string_view> ws;
    split(flt, ws);
    if (int32_t(ws.size()) > depth) return 0;
    bool ends_hash = !ws.empty() && ws.back() == "#";
    size_t walk_n = ends_hash ? ws.size() - 1 : ws.size();
    int32_t sid = 0;
    for (size_t i = 0; i < walk_n; ++i) {
      std::string_view w = ws[i];
      if (w == "+") {
        sid = nodes[sid].plus;
      } else {
        int32_t wid = vocab_get(w);
        if (wid == 0) return 0;
        auto it = children.find(ckey(sid, wid));
        sid = (it == children.end()) ? -1 : it->second;
      }
      if (sid < 0) return 0;
    }
    Node& n = nodes[sid];
    if (ends_hash) {
      if (n.hash_aid < 0) return 0;
      free_aid(n.hash_aid);
      n.hash_aid = -1;
    } else {
      if (n.aid < 0) return 0;
      free_aid(n.aid);
      n.aid = -1;
    }
    dirty_states.insert(sid);
    prune(sid);
    --n_filters;
    ++epoch;
    return 1;
  }

  void prune(int32_t sid) {
    while (sid != 0) {
      Node& n = nodes[sid];
      if (n.nlit != 0 || n.plus >= 0 || n.hash_aid >= 0 || n.aid >= 0) return;
      int32_t parent = n.parent;
      if (n.pword == -2) {
        nodes[parent].plus = -1;
      } else {
        children.erase(ckey(parent, n.pword));
        --nodes[parent].nlit;
        edge_delete(parent, n.pword);
      }
      n = Node{};  // clears live
      dirty_states.insert(sid);
      dirty_states.insert(parent);
      free_sids.push_back(sid);
      --n_states;
      sid = parent;
    }
  }

  int32_t aid_of(std::string_view flt) const {
    std::vector<std::string_view> ws;
    split(flt, ws);
    if (int32_t(ws.size()) > depth) return -1;
    bool ends_hash = !ws.empty() && ws.back() == "#";
    size_t walk_n = ends_hash ? ws.size() - 1 : ws.size();
    int32_t sid = 0;
    for (size_t i = 0; i < walk_n; ++i) {
      std::string_view w = ws[i];
      if (w == "+") {
        sid = nodes[sid].plus;
      } else {
        int32_t wid = vocab_get(w);
        if (wid == 0) return -1;
        auto it = children.find(ckey(sid, wid));
        sid = (it == children.end()) ? -1 : it->second;
      }
      if (sid < 0) return -1;
    }
    return ends_hash ? nodes[sid].hash_aid : nodes[sid].aid;
  }

  // host-side authoritative match (fail-open path); same semantics as
  // IncrementalNfa.match_host: '+' one level, '#' >= 0 trailing levels,
  // root wildcards suppressed for '$'-topics
  int match_topic(std::string_view topic, int32_t* out, int cap) const {
    std::vector<std::string_view> ws;
    split(topic, ws);
    bool is_sys = !topic.empty() && topic[0] == '$';
    int cnt = 0;
    auto emit = [&](int32_t aid) {
      if (cnt < cap) out[cnt] = aid;
      ++cnt;
    };
    std::vector<int32_t> frontier{0}, next;
    for (size_t t = 0; t < ws.size(); ++t) {
      next.clear();
      int32_t wid = vocab_get(ws[t]);
      for (int32_t sid : frontier) {
        const Node& n = nodes[sid];
        if (n.hash_aid >= 0 && !(t == 0 && is_sys)) emit(n.hash_aid);
        if (wid != 0) {
          auto it = children.find(ckey(sid, wid));
          if (it != children.end()) next.push_back(it->second);
        }
        if (n.plus >= 0 && !(t == 0 && is_sys)) next.push_back(n.plus);
      }
      frontier.swap(next);
      if (frontier.empty()) return cnt;
    }
    for (int32_t sid : frontier) {
      const Node& n = nodes[sid];
      if (n.hash_aid >= 0) emit(n.hash_aid);
      if (n.aid >= 0) emit(n.aid);
    }
    return cnt;
  }

  void fill_node_tab(int32_t* node_tab) const {
    // caller allocates (S_pow2, 4); S_pow2 from nfa_sizes
    size_t s_pow2 = node_pow2();
    for (size_t i = 0; i < s_pow2; ++i) {
      int32_t* row = node_tab + i * 4;
      if (i < nodes.size() && nodes[i].live) {
        row[0] = nodes[i].plus;
        row[1] = nodes[i].hash_aid;
        row[2] = nodes[i].aid;
        row[3] = 0;
      } else {
        row[0] = row[1] = row[2] = -1;
        row[3] = 0;
      }
    }
  }

  size_t node_pow2() const {
    size_t s = 1024;
    while (s < nodes.size()) s <<= 1;
    return s;
  }
};

}  // namespace

extern "C" {

void* nfa_new(int32_t depth, int32_t state_bucket, int32_t edge_bucket,
              uint64_t seed) {
  return new Nfa(depth, uint32_t(state_bucket), uint32_t(edge_bucket), seed);
}

void nfa_free(void* h) { delete static_cast<Nfa*>(h); }

int32_t nfa_add(void* h, const char* s, int32_t n) {
  return static_cast<Nfa*>(h)->add(std::string_view(s, size_t(n)));
}

int32_t nfa_remove(void* h, const char* s, int32_t n) {
  return static_cast<Nfa*>(h)->remove(std::string_view(s, size_t(n)));
}

// newline-separated filters; returns count of newly-added filters
int64_t nfa_bulk_add(void* h, const char* buf, int64_t len) {
  Nfa* nfa = static_cast<Nfa*>(h);
  // pre-size the hot hash maps: filters average ~2 trie edges each, and
  // reserving 2x headroom up front (a) kills rehash stalls inside the
  // bulk loop and (b) keeps the FIRST post-bulk incremental adds from
  // paying a multi-hundred-ms one-off rehash of a multi-million-entry
  // map (measured 200 ms at 2M filters), which would blow the <50 ms
  // delta-latency bound on whichever unlucky subscribe lands on it
  int64_t approx = 0;
  for (int64_t i = 0; i < len; ++i) approx += buf[i] == '\n';
  nfa->children.reserve(nfa->children.size() + size_t(approx) * 4);
  nfa->vocab.reserve(nfa->vocab.size() + size_t(approx));
  int64_t added = 0;
  int64_t start = 0;
  for (int64_t i = 0; i <= len; ++i) {
    if (i == len || buf[i] == '\n') {
      if (i > start)
        added += nfa->add(std::string_view(buf + start, size_t(i - start))) > 0;
      start = i + 1;
    }
  }
  return added;
}

// intern one word WITHOUT adding any filter; returns its vocab id.
// Ids assign append-only (vocab.size()+1), so replaying the same word
// sequence into several tables keeps their vocabs identical — the
// multichip shard subtables share one encode vocab this way.
int32_t nfa_intern(void* h, const char* s, int32_t n) {
  return static_cast<Nfa*>(h)->intern(std::string_view(s, size_t(n)));
}

// NUL-separated words (topic words may legally contain '\n', never
// NUL); interns each in order, returns the count consumed
int64_t nfa_bulk_intern(void* h, const char* buf, int64_t len) {
  Nfa* nfa = static_cast<Nfa*>(h);
  int64_t approx = 0;
  for (int64_t i = 0; i < len; ++i) approx += buf[i] == '\0';
  nfa->vocab.reserve(nfa->vocab.size() + size_t(approx) + 1);
  int64_t count = 0;
  int64_t start = 0;
  for (int64_t i = 0; i <= len; ++i) {
    if (i == len || buf[i] == '\0') {
      if (i > start) {
        nfa->intern(std::string_view(buf + start, size_t(i - start)));
        ++count;
      }
      start = i + 1;
    }
  }
  return count;
}

// grow the cuckoo edge table until Hb >= hb_target (pow2 doublings,
// full rehash each step — the multichip restack needs every shard on
// one COMMON Hb, because lookups probe modulo the table size)
int64_t nfa_grow_edges_to(void* h, int64_t hb_target) {
  Nfa* nfa = static_cast<Nfa*>(h);
  while (int64_t(nfa->Hb) < hb_target) nfa->grow(false);
  return int64_t(nfa->Hb);
}

int32_t nfa_aid_of(void* h, const char* s, int32_t n) {
  return static_cast<Nfa*>(h)->aid_of(std::string_view(s, size_t(n)));
}

int32_t nfa_alloc_alias(void* h, const char* s, int32_t n) {
  Nfa* nfa = static_cast<Nfa*>(h);
  int32_t aid = nfa->alloc_aid(std::string_view(s, size_t(n)));
  nfa->alias_aids.insert(aid);
  ++nfa->epoch;
  return aid;
}

int32_t nfa_free_alias(void* h, int32_t aid) {
  Nfa* nfa = static_cast<Nfa*>(h);
  if (!nfa->alias_aids.erase(aid)) return 0;
  nfa->free_aid(aid);
  ++nfa->epoch;
  return 1;
}

int32_t nfa_match_topic(void* h, const char* s, int32_t n, int32_t* out,
                        int32_t cap) {
  return static_cast<Nfa*>(h)->match_topic(std::string_view(s, size_t(n)),
                                           out, cap);
}

// out[0]=S_pow2 out[1]=Hb out[2]=n_states out[3]=n_edges out[4]=n_accepts
// out[5]=n_filters out[6]=vocab_count out[7]=vocab_bytes out[8]=epoch
// out[9]=resized out[10]=aid_reuses
void nfa_sizes(void* h, int64_t* out) {
  Nfa* n = static_cast<Nfa*>(h);
  out[0] = int64_t(n->node_pow2());
  out[1] = n->Hb;
  out[2] = n->n_states;
  out[3] = n->n_edges;
  out[4] = int64_t(n->accepts.size());
  out[5] = n->n_filters;
  out[6] = int64_t(n->vocab.size());
  int64_t vb = 0;
  for (auto& w : n->vocab_list) vb += int64_t(w.size()) + 1;
  out[7] = vb;
  out[8] = int64_t(n->epoch);
  out[9] = n->resized ? 1 : 0;
  out[10] = int64_t(n->aid_reuses);
}

void nfa_fill_tables(void* h, int32_t* node_tab, int32_t* edge_tab,
                     int32_t* seeds) {
  Nfa* n = static_cast<Nfa*>(h);
  n->fill_node_tab(node_tab);
  std::memcpy(edge_tab, n->edge_tab.data(),
              n->edge_tab.size() * sizeof(int32_t));
  seeds[0] = int32_t(n->seeds[0]);
  seeds[1] = int32_t(n->seeds[1]);
}

// vocab words NUL-joined in id order (id 1 first); buf sized vocab_bytes.
// NUL is the one byte MQTT forbids in topic names (MQTT-1.5.4-2), so it
// cannot appear inside a word; '\n' CAN, which is why it is not used.
void nfa_vocab_fill(void* h, char* buf) {
  Nfa* n = static_cast<Nfa*>(h);
  char* p = buf;
  for (auto& w : n->vocab_list) {
    std::memcpy(p, w.data(), w.size());
    p += w.size();
    *p++ = '\0';
  }
}

int32_t nfa_accept_get(void* h, int32_t aid, char* buf, int32_t cap) {
  Nfa* n = static_cast<Nfa*>(h);
  if (aid < 0 || size_t(aid) >= n->accepts.size() || !n->accept_live[aid])
    return -1;
  const std::string& s = n->accepts[aid];
  if (int32_t(s.size()) > cap) return -1;
  std::memcpy(buf, s.data(), s.size());
  return int32_t(s.size());
}

void nfa_set_device_epoch(void* h, int64_t e) {
  static_cast<Nfa*>(h)->device_epoch = e;
}

// force the next delta to present as a full re-upload (used after a
// bulk load whose delta was deliberately drained host-side)
void nfa_mark_resized(void* h) { static_cast<Nfa*>(h)->resized = true; }

// out[0]=n_dirty_states out[1]=n_dirty_buckets out[2]=resized out[3]=epoch
void nfa_delta_sizes(void* h, int64_t* out) {
  Nfa* n = static_cast<Nfa*>(h);
  out[0] = n->resized ? 0 : int64_t(n->dirty_states.size());
  out[1] = n->resized ? 0 : int64_t(n->dirty_buckets.size());
  out[2] = n->resized ? 1 : 0;
  out[3] = int64_t(n->epoch);
}

// fills dirty row indices + current row contents, then clears dirty sets
void nfa_delta_fill(void* h, int32_t* state_idx, int32_t* state_rows,
                    int32_t* bucket_idx, int32_t* bucket_rows) {
  Nfa* n = static_cast<Nfa*>(h);
  if (!n->resized) {
    int64_t i = 0;
    for (int32_t sid : n->dirty_states) {
      state_idx[i] = sid;
      int32_t* row = state_rows + i * 4;
      if (size_t(sid) < n->nodes.size() && n->nodes[sid].live) {
        row[0] = n->nodes[sid].plus;
        row[1] = n->nodes[sid].hash_aid;
        row[2] = n->nodes[sid].aid;
        row[3] = 0;
      } else {
        row[0] = row[1] = row[2] = -1;
        row[3] = 0;
      }
      ++i;
    }
    int64_t j = 0;
    for (int32_t b : n->dirty_buckets) {
      bucket_idx[j] = b;
      std::memcpy(bucket_rows + j * ROW, &n->edge_tab[size_t(b) * ROW],
                  ROW * sizeof(int32_t));
      ++j;
    }
  }
  n->dirty_states.clear();
  n->dirty_buckets.clear();
  n->resized = false;
}

}  // extern "C"
