"""Native (C++) runtime components, loaded via ctypes.

The reference leans on BEAM NIFs for its hot host-side loops (jiffy,
bcrypt, quicer — SURVEY.md §2.4); our equivalents live here, compiled
lazily with the in-image g++ on first use and cached next to the
source.  Every native entry point has a pure-Python fallback so the
package works (slower) without a toolchain.
"""

from .build import load_library

__all__ = ["load_library"]
