// Zstandard DECODER (RFC 8878) — the zstd-erlang/NIF analog for the
// Kafka bridge's codec-4 record batches (SURVEY.md §2.4).
//
// Independent implementation of the PUBLIC zstd format, decode side
// only: frame/block framing, raw/RLE/compressed blocks, Huffman
// literals (direct + FSE-compressed weight descriptions, 1- and
// 4-stream), FSE sequence tables (predefined / RLE / described /
// repeat), the 3-slot repeat-offset history with the literals-
// length-0 shift, backward bitstreams, and the xxHash64 content
// checksum.  Dictionaries are NOT supported (Kafka batches never use
// them); a frame naming a dictionary ID fails with "unsupported".
// The produce side emits store-mode frames from Python (zstd.py) —
// valid zstd any consumer decodes — so only the decoder is hot and
// only the decoder lives here.  Interop is proven in
// tests/test_zstd.py against system libzstd in both directions.
//
// Exported (extern "C", caller-allocated buffers):
//   zstd_decompress(src,n,dst,cap) -> decoded size;
//                                     -1 corrupt, -2 cap too small,
//                                     -3 unsupported (dictionary)
//   zstd_content_size(src,n)       -> the FIRST regular frame's
//                                     declared content size (an
//                                     allocation hint; Kafka batches
//                                     are one frame), or -1 when not
//                                     declared (caller sizes
//                                     heuristically and grows on -2)
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int64_t ERR_CORRUPT = -1;
constexpr int64_t ERR_DSTSIZE = -2;
constexpr int64_t ERR_UNSUPPORTED = -3;

constexpr uint32_t kMagic = 0xFD2FB528u;
constexpr uint32_t kSkipMagicBase = 0x184D2A50u;  // ..0x184D2A5F
constexpr int64_t kBlockMax = 1 << 17;            // 128 KB decoded/block
constexpr int kMaxHufLog = 12;
constexpr int kMaxLLLog = 9, kMaxOFLog = 8, kMaxMLLog = 9, kMaxWtLog = 6;

inline int highbit(uint64_t v) {        // index of highest set bit
    return 63 - __builtin_clzll(v);
}

inline uint32_t load32le(const uint8_t* p) {
    uint32_t v; std::memcpy(&v, p, 4); return v;
}

// ---- xxHash64 (content checksum: low 32 bits) ------------------------------

uint64_t xxh64(const uint8_t* p, size_t len, uint64_t seed) {
    constexpr uint64_t P1 = 11400714785074694791ull,
                       P2 = 14029467366897019727ull,
                       P3 = 1609587929392839161ull,
                       P4 = 9650029242287828579ull,
                       P5 = 2870177450012600261ull;
    auto rotl = [](uint64_t x, int r) { return (x << r) | (x >> (64 - r)); };
    auto load64 = [](const uint8_t* q) {
        uint64_t v; std::memcpy(&v, q, 8); return v;
    };
    auto round1 = [&](uint64_t acc, uint64_t input) {
        return rotl(acc + input * P2, 31) * P1;
    };
    const uint8_t* end = p + len;
    uint64_t h;
    if (len >= 32) {
        uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
                 v4 = seed - P1;
        const uint8_t* limit = end - 32;
        do {
            v1 = round1(v1, load64(p)); p += 8;
            v2 = round1(v2, load64(p)); p += 8;
            v3 = round1(v3, load64(p)); p += 8;
            v4 = round1(v4, load64(p)); p += 8;
        } while (p <= limit);
        h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
        auto merge = [&](uint64_t acc, uint64_t v) {
            return (acc ^ round1(0, v)) * P1 + P4;
        };
        h = merge(h, v1); h = merge(h, v2);
        h = merge(h, v3); h = merge(h, v4);
    } else {
        h = seed + P5;
    }
    h += uint64_t(len);
    while (p + 8 <= end) {
        h = rotl(h ^ round1(0, load64(p)), 27) * P1 + P4;
        p += 8;
    }
    if (p + 4 <= end) {
        h = rotl(h ^ (uint64_t(load32le(p)) * P1), 23) * P2 + P3;
        p += 4;
    }
    while (p < end) {
        h = rotl(h ^ (*p++ * P5), 11) * P1;
    }
    h ^= h >> 33; h *= P2;
    h ^= h >> 29; h *= P3;
    h ^= h >> 32;
    return h;
}

// ---- bit readers -----------------------------------------------------------

// Forward LSB-first reader (FSE table descriptions).
struct FwdBits {
    const uint8_t* p;
    int64_t nbits;
    int64_t pos = 0;
    uint64_t peek(int n) const {
        uint64_t v = 0;
        for (int i = 0; i < n; i++) {
            int64_t b = pos + i;
            if (b < nbits && ((p[b >> 3] >> (b & 7)) & 1))
                v |= 1ull << i;
        }
        return v;
    }
    void consume(int n) { pos += n; }
    uint64_t read(int n) { uint64_t v = peek(n); pos += n; return v; }
    bool ok() const { return pos <= nbits; }
    int64_t bytes_used() const { return (pos + 7) >> 3; }
};

// Backward reader: the stream is written LSB-first front-to-back, read
// from the END.  Model: the whole stream is one little-endian integer;
// read(n) returns its top n bits and drops them.  The final byte's
// highest set bit is a sentinel (not data).
struct BackBits {
    const uint8_t* p = nullptr;
    int64_t nbytes = 0;
    int64_t bitpos = 0;     // bits remaining; 0 == fully consumed
    bool bad = false;       // init failure or over-read
    bool init(const uint8_t* src, int64_t n) {
        if (n <= 0 || src[n - 1] == 0) { bad = true; return false; }
        p = src; nbytes = n;
        bitpos = (n - 1) * 8 + highbit(src[n - 1]);   // sentinel removed
        return true;
    }
    // Bits [bitpos-n, bitpos) of the little-endian stream, zero-padded
    // below position 0 (canonical decoders peek past the start near
    // the end of a stream; only CONSUMING past it is an error).
    uint64_t peek(int n) const {
        if (n == 0) return 0;
        uint64_t v = 0;
        int64_t lo = bitpos - n;
        for (int i = 0; i < n; i++) {
            int64_t b = lo + i;
            if (b >= 0 && ((p[b >> 3] >> (b & 7)) & 1))
                v |= 1ull << i;
        }
        return v;
    }
    void consume(int n) {
        bitpos -= n;
        if (bitpos < 0) bad = true;
    }
    uint64_t read(int n) { uint64_t v = peek(n); consume(n); return v; }
    bool done() const { return bitpos == 0; }
};

// ---- FSE -------------------------------------------------------------------

struct FSETable {
    std::vector<uint8_t> symbol;
    std::vector<uint8_t> nbBits;
    std::vector<uint16_t> newState;
    int log = -1;           // -1 == unset
    bool set() const { return log >= 0; }
};

void fse_rle(FSETable& T, uint8_t sym) {
    T.log = 0;
    T.symbol.assign(1, sym);
    T.nbBits.assign(1, 0);
    T.newState.assign(1, 0);
}

// Normalized counts -> decode table (RFC 8878 §4.1.1).
bool fse_build(const int16_t* norm, int nsym, int log, FSETable& T) {
    if (log < 0 || log > 12) return false;
    const int size = 1 << log, mask = size - 1;
    T.log = log;
    T.symbol.assign(size, 0);
    T.nbBits.assign(size, 0);
    T.newState.assign(size, 0);
    std::vector<uint16_t> next(nsym);
    int high = size - 1;
    for (int s = 0; s < nsym; s++) {
        if (norm[s] == -1) {
            if (high < 0) return false;
            T.symbol[high--] = uint8_t(s);
            next[s] = 1;
        } else if (norm[s] > 0) {
            next[s] = uint16_t(norm[s]);
        }
    }
    const int step = (size >> 1) + (size >> 3) + 3;
    int pos = 0;
    for (int s = 0; s < nsym; s++) {
        for (int i = 0; i < norm[s]; i++) {
            T.symbol[pos] = uint8_t(s);
            do { pos = (pos + step) & mask; } while (pos > high);
        }
    }
    if (pos != 0) return false;          // table not exactly filled
    for (int t = 0; t < size; t++) {
        const uint16_t ns = next[T.symbol[t]]++;
        // a symbol with norm k visits states k..2k-1, so ns legally
        // reaches 2·size-1 (nbBits 0) for dominant symbols
        if (ns == 0 || ns >= 2 * size) return false;
        const int nb = log - highbit(ns);
        T.nbBits[t] = uint8_t(nb);
        T.newState[t] = uint16_t((uint32_t(ns) << nb) - size);
    }
    return true;
}

// Parse an FSE table description (forward bitstream).  Returns bytes
// consumed, or -1 on corruption.  maxLog/maxSym bound the header.
int64_t fse_parse(const uint8_t* src, int64_t n, int maxLog, int maxSym,
                  FSETable& T) {
    if (n < 1) return -1;
    FwdBits bits{src, n * 8};
    const int log = int(bits.read(4)) + 5;
    if (log > maxLog) return -1;
    const int size = 1 << log;
    int remaining = size + 1;
    int threshold = size;
    int nbits = log + 1;
    int16_t norm[256] = {0};
    int sym = 0;
    bool prev_zero = false;
    while (remaining > 1 && sym <= maxSym) {
        if (prev_zero) {                 // 2-bit runs of extra zeros
            for (;;) {
                const int rep = int(bits.read(2));
                sym += rep;
                if (sym > maxSym + 1 || !bits.ok()) return -1;
                if (rep != 3) break;
            }
            prev_zero = false;
            continue;
        }
        const int max = (2 * threshold - 1) - remaining;
        int count;
        if (int(bits.peek(nbits - 1)) < max) {
            count = int(bits.read(nbits - 1));
        } else {
            count = int(bits.read(nbits));
            if (count >= threshold) count -= max;
        }
        count--;                         // -1 encodes "less than 1"
        if (!bits.ok()) return -1;
        remaining -= count < 0 ? -count : count;
        if (remaining < 1 || sym > maxSym) return -1;
        norm[sym++] = int16_t(count);
        prev_zero = (count == 0);
        while (remaining < threshold) { nbits--; threshold >>= 1; }
    }
    if (remaining != 1 || !bits.ok()) return -1;
    if (!fse_build(norm, sym, log, T)) return -1;
    return bits.bytes_used();
}

// ---- predefined sequence tables (RFC 8878 §3.1.1.3.2.2) --------------------

const int16_t kLLDefault[36] = {
    4, 3, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 1, 1, 1,
    2, 2, 2, 2, 2, 2, 2, 2, 2, 3, 2, 1, 1, 1, 1, 1,
    -1, -1, -1, -1};
const int16_t kMLDefault[53] = {
    1, 4, 3, 2, 2, 2, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1,
    1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
    1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, -1, -1,
    -1, -1, -1, -1, -1};
const int16_t kOFDefault[29] = {
    1, 1, 1, 1, 1, 1, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1,
    1, 1, 1, 1, 1, 1, 1, 1, -1, -1, -1, -1, -1};

// Code -> (baseline, extra bits) for literal lengths / match lengths.
const uint32_t kLLBase[36] = {
    0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
    16, 18, 20, 22, 24, 28, 32, 40, 48, 64, 128, 256, 512, 1024,
    2048, 4096, 8192, 16384, 32768, 65536};
const uint8_t kLLBits[36] = {
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    1, 1, 1, 1, 2, 2, 3, 3, 4, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16};
const uint32_t kMLBase[53] = {
    3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
    19, 20, 21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34,
    35, 37, 39, 41, 43, 47, 51, 59, 67, 83, 99, 131, 259, 515,
    1027, 2051, 4099, 8195, 16387, 32771, 65539};
const uint8_t kMLBits[53] = {
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    1, 1, 1, 1, 2, 2, 3, 3, 4, 4, 5, 7, 8, 9, 10, 11,
    12, 13, 14, 15, 16};

// ---- Huffman ---------------------------------------------------------------

struct HufTable {
    std::vector<uint8_t> symbol;
    std::vector<uint8_t> nbBits;
    int log = -1;
    bool set() const { return log >= 0; }
};

// weights[0..n-1] (explicit + inferred last already appended) -> table.
bool huf_build(const uint8_t* weights, int n, int maxBits, HufTable& H) {
    if (maxBits <= 0 || maxBits > kMaxHufLog || n < 2 || n > 256)
        return false;
    const int size = 1 << maxBits;
    H.log = maxBits;
    H.symbol.assign(size, 0);
    H.nbBits.assign(size, 0);
    int pos = 0;
    for (int w = 1; w <= maxBits; w++) {
        for (int s = 0; s < n; s++) {
            if (weights[s] != w) continue;
            const int count = 1 << (w - 1);
            const int nb = maxBits + 1 - w;
            if (pos + count > size) return false;
            for (int i = 0; i < count; i++) {
                H.symbol[pos] = uint8_t(s);
                H.nbBits[pos] = uint8_t(nb);
                pos++;
            }
        }
    }
    return pos == size;
}

// Huffman tree description (RFC 8878 §4.2.1) -> table.  Returns header
// bytes consumed, or -1.
int64_t huf_parse(const uint8_t* src, int64_t n, HufTable& H) {
    if (n < 1) return -1;
    const int hbyte = src[0];
    uint8_t weights[256];
    int nw = 0;
    int64_t used;
    if (hbyte >= 128) {                  // direct: 4-bit packed weights
        nw = hbyte - 127;
        const int64_t bytes = (nw + 1) / 2;
        if (1 + bytes > n) return -1;
        for (int i = 0; i < nw; i++) {
            const uint8_t b = src[1 + i / 2];
            weights[i] = (i & 1) ? (b & 0x0F) : (b >> 4);
        }
        used = 1 + bytes;
    } else {                             // FSE-compressed weights
        if (hbyte == 0 || 1 + hbyte > n) return -1;
        FSETable WT;
        const int64_t hdr = fse_parse(src + 1, hbyte, kMaxWtLog, 255, WT);
        if (hdr < 0 || hdr >= hbyte) return -1;
        BackBits bits;
        if (!bits.init(src + 1 + hdr, hbyte - hdr)) return -1;
        uint32_t s1 = uint32_t(bits.read(WT.log));
        uint32_t s2 = uint32_t(bits.read(WT.log));
        if (bits.bad) return -1;
        // two interleaved states; a state update that over-reads ends
        // the stream — flush the OTHER state's symbol and stop
        uint32_t* cur = &s1;
        uint32_t* oth = &s2;
        for (;;) {
            if (nw >= 255) return -1;
            weights[nw++] = WT.symbol[*cur];
            const int nb = WT.nbBits[*cur];
            const uint32_t ns = WT.newState[*cur] + uint32_t(bits.read(nb));
            if (bits.bad) {
                if (nw >= 255) return -1;
                weights[nw++] = WT.symbol[*oth];
                break;
            }
            *cur = ns;
            uint32_t* t = cur; cur = oth; oth = t;
        }
        used = 1 + hbyte;
    }
    // infer the final weight: totals must complete a power of two
    uint64_t sum = 0;
    for (int i = 0; i < nw; i++) {
        if (weights[i] > kMaxHufLog) return -1;
        if (weights[i]) sum += 1ull << (weights[i] - 1);
    }
    if (sum == 0) return -1;
    const int maxBits = highbit(sum) + 1;
    if (maxBits > kMaxHufLog) return -1;
    const uint64_t rest = (1ull << maxBits) - sum;
    if (rest == 0 || (rest & (rest - 1)) != 0) return -1;  // must be 2^k
    if (nw >= 256) return -1;
    weights[nw++] = uint8_t(highbit(rest) + 1);
    if (!huf_build(weights, nw, maxBits, H)) return -1;
    return used;
}

// Decode exactly `count` symbols from one backward Huffman stream.
bool huf_stream(const HufTable& H, const uint8_t* src, int64_t n,
                uint8_t* dst, int64_t count) {
    BackBits bits;
    if (!bits.init(src, n)) return false;
    for (int64_t i = 0; i < count; i++) {
        const uint32_t idx = uint32_t(bits.peek(H.log));
        dst[i] = H.symbol[idx];
        bits.consume(H.nbBits[idx]);
        if (bits.bad) return false;
    }
    return bits.done();                  // all bits must be consumed
}

// ---- frame decoding state --------------------------------------------------

struct FrameState {                      // persists across blocks
    HufTable huf;                        // for treeless literals
    FSETable ll, of, ml;                 // for repeat mode
    uint32_t rep[3] = {1, 4, 8};         // repeat offsets
};

// Decode the literals section.  Appends regenerated literals to `lits`
// and returns bytes of the block consumed, or -1.
int64_t decode_literals(const uint8_t* src, int64_t n, FrameState& fs,
                        std::vector<uint8_t>& lits) {
    if (n < 1) return -1;
    const int type = src[0] & 3;
    const int sf = (src[0] >> 2) & 3;
    int64_t regen, comp = -1, hdr;
    if (type <= 1) {                     // Raw / RLE
        if (sf == 0 || sf == 2) { regen = src[0] >> 3; hdr = 1; }
        else if (sf == 1) {
            if (n < 2) return -1;
            regen = (src[0] >> 4) | (int64_t(src[1]) << 4); hdr = 2;
        } else {
            if (n < 3) return -1;
            regen = (src[0] >> 4) | (int64_t(src[1]) << 4)
                  | (int64_t(src[2]) << 12);
            hdr = 3;
        }
    } else {                             // Compressed / Treeless
        if (sf <= 1) {
            if (n < 3) return -1;
            regen = (src[0] >> 4) | (int64_t(src[1] & 0x3F) << 4);
            comp = (src[1] >> 6) | (int64_t(src[2]) << 2);
            hdr = 3;
        } else if (sf == 2) {
            if (n < 4) return -1;
            regen = (src[0] >> 4) | (int64_t(src[1]) << 4)
                  | (int64_t(src[2] & 3) << 12);
            comp = (src[2] >> 2) | (int64_t(src[3]) << 6);
            hdr = 4;
        } else {
            if (n < 5) return -1;
            regen = (src[0] >> 4) | (int64_t(src[1]) << 4)
                  | (int64_t(src[2] & 0x3F) << 12);
            comp = (src[2] >> 6) | (int64_t(src[3]) << 2)
                 | (int64_t(src[4]) << 10);
            hdr = 5;
        }
    }
    if (regen > kBlockMax) return -1;
    const size_t base = lits.size();
    switch (type) {
    case 0: {                            // Raw
        if (hdr + regen > n) return -1;
        lits.insert(lits.end(), src + hdr, src + hdr + regen);
        return hdr + regen;
    }
    case 1: {                            // RLE
        if (hdr + 1 > n) return -1;
        lits.insert(lits.end(), size_t(regen), src[hdr]);
        return hdr + 1;
    }
    default: {                           // Compressed (2) / Treeless (3)
        if (hdr + comp > n) return -1;
        const uint8_t* body = src + hdr;
        int64_t left = comp;
        if (type == 2) {
            const int64_t used = huf_parse(body, left, fs.huf);
            if (used < 0) return -1;
            body += used;
            left -= used;
        } else if (!fs.huf.set()) {
            return -1;                   // treeless before any tree
        }
        lits.resize(base + regen);
        uint8_t* out = lits.data() + base;
        if (sf == 0) {                   // single stream
            if (!huf_stream(fs.huf, body, left, out, regen)) return -1;
        } else {                         // 4 streams, 6-byte jump table
            if (left < 6) return -1;
            const int64_t s1 = body[0] | (int64_t(body[1]) << 8);
            const int64_t s2 = body[2] | (int64_t(body[3]) << 8);
            const int64_t s3 = body[4] | (int64_t(body[5]) << 8);
            const int64_t s4 = left - 6 - s1 - s2 - s3;
            if (s4 <= 0) return -1;
            const int64_t per = (regen + 3) / 4;
            const int64_t last = regen - 3 * per;
            if (last < 0) return -1;
            const uint8_t* q = body + 6;
            if (!huf_stream(fs.huf, q, s1, out, per)) return -1;
            if (!huf_stream(fs.huf, q + s1, s2, out + per, per)) return -1;
            if (!huf_stream(fs.huf, q + s1 + s2, s3, out + 2 * per, per))
                return -1;
            if (!huf_stream(fs.huf, q + s1 + s2 + s3, s4, out + 3 * per,
                            last))
                return -1;
        }
        return hdr + comp;
    }
    }
}

// One sequence-table slot: predefined / RLE / FSE / repeat.  Every
// mode stores into the frame-persistent slot, because Repeat reuses
// whatever the PREVIOUS block used — including a predefined or RLE
// table (libzstd keeps the last-used table of any kind).
// Returns bytes consumed from the description area, or -1.
int64_t seq_table(int mode, const uint8_t* src, int64_t n,
                  const int16_t* dflt, int dfltN, int dfltLog,
                  int maxLog, int maxSym, FSETable& persist) {
    switch (mode) {
    case 0:                              // predefined
        if (!fse_build(dflt, dfltN, dfltLog, persist)) return -1;
        return 0;
    case 1:                              // RLE: one byte = the symbol
        if (n < 1 || src[0] > maxSym) return -1;
        fse_rle(persist, src[0]);
        return 1;
    case 2: {                            // FSE description
        const int64_t used = fse_parse(src, n, maxLog, maxSym, persist);
        if (used < 0) return -1;
        return used;
    }
    default:                             // repeat
        if (!persist.set()) return -1;
        return 0;
    }
}

// Decode one compressed block into `out`.  `frameBase` = out.size()
// at the start of the frame — match offsets may not reach before it
// (no dictionary, and never into a PREVIOUS concatenated frame).
// Returns 0 or an error code.
int64_t decode_block(const uint8_t* src, int64_t n, FrameState& fs,
                     std::vector<uint8_t>& out, size_t frameBase) {
    std::vector<uint8_t> lits;
    const int64_t lused = decode_literals(src, n, fs, lits);
    if (lused < 0) return ERR_CORRUPT;
    src += lused;
    n -= lused;
    // sequences header
    if (n < 1) return ERR_CORRUPT;
    int64_t nseq;
    int64_t hdr;
    if (src[0] == 0) { nseq = 0; hdr = 1; }
    else if (src[0] < 128) { nseq = src[0]; hdr = 1; }
    else if (src[0] < 255) {
        if (n < 2) return ERR_CORRUPT;
        nseq = (int64_t(src[0] - 128) << 8) + src[1];
        hdr = 2;
    } else {
        if (n < 3) return ERR_CORRUPT;
        nseq = src[1] + (int64_t(src[2]) << 8) + 0x7F00;
        hdr = 3;
    }
    src += hdr;
    n -= hdr;
    if (nseq == 0) {                     // literals only
        out.insert(out.end(), lits.begin(), lits.end());
        return n == 0 ? 0 : ERR_CORRUPT;
    }
    if (n < 1) return ERR_CORRUPT;
    const int mode = src[0];
    if (mode & 3) return ERR_CORRUPT;    // reserved bits
    src += 1;
    n -= 1;
    int64_t used = seq_table((mode >> 6) & 3, src, n, kLLDefault, 36, 6,
                             kMaxLLLog, 35, fs.ll);
    if (used < 0) return ERR_CORRUPT;
    src += used; n -= used;
    used = seq_table((mode >> 4) & 3, src, n, kOFDefault, 29, 5,
                     kMaxOFLog, 31, fs.of);
    if (used < 0) return ERR_CORRUPT;
    src += used; n -= used;
    used = seq_table((mode >> 2) & 3, src, n, kMLDefault, 53, 6,
                     kMaxMLLog, 52, fs.ml);
    if (used < 0) return ERR_CORRUPT;
    src += used; n -= used;
    const FSETable *ll = &fs.ll, *of = &fs.of, *ml = &fs.ml;
    // the rest of the block is the backward sequence bitstream
    BackBits bits;
    if (!bits.init(src, n)) return ERR_CORRUPT;
    uint32_t llS = uint32_t(bits.read(ll->log));
    uint32_t ofS = uint32_t(bits.read(of->log));
    uint32_t mlS = uint32_t(bits.read(ml->log));
    if (bits.bad) return ERR_CORRUPT;
    size_t litPos = 0;
    const size_t blockBase = out.size();
    for (int64_t i = 0; i < nseq; i++) {
        const int ofCode = of->symbol[ofS];
        if (ofCode > 31) return ERR_CORRUPT;
        const uint64_t ofVal = (1ull << ofCode) + bits.read(ofCode);
        const int mlCode = ml->symbol[mlS];
        const uint64_t mlen = kMLBase[mlCode] + bits.read(kMLBits[mlCode]);
        const int llCode = ll->symbol[llS];
        const uint64_t llen = kLLBase[llCode] + bits.read(kLLBits[llCode]);
        if (bits.bad) return ERR_CORRUPT;
        // repeat-offset resolution (RFC 8878 §3.1.1.5)
        uint32_t offset;
        if (ofVal > 3) {
            offset = uint32_t(ofVal - 3);
            fs.rep[2] = fs.rep[1];
            fs.rep[1] = fs.rep[0];
            fs.rep[0] = offset;
        } else {
            const uint64_t idx = ofVal - 1 + (llen == 0 ? 1 : 0);
            if (idx == 0) {
                offset = fs.rep[0];
            } else if (idx == 1) {
                offset = fs.rep[1];
                fs.rep[1] = fs.rep[0];
                fs.rep[0] = offset;
            } else if (idx == 2) {
                offset = fs.rep[2];
                fs.rep[2] = fs.rep[1];
                fs.rep[1] = fs.rep[0];
                fs.rep[0] = offset;
            } else {                     // idx == 3: rep[0] - 1
                if (fs.rep[0] <= 1) return ERR_CORRUPT;
                offset = fs.rep[0] - 1;
                fs.rep[2] = fs.rep[1];
                fs.rep[1] = fs.rep[0];
                fs.rep[0] = offset;
            }
            if (offset == 0) return ERR_CORRUPT;
        }
        if (i + 1 < nseq) {              // update states: LL, ML, OF
            llS = ll->newState[llS] + uint32_t(bits.read(ll->nbBits[llS]));
            mlS = ml->newState[mlS] + uint32_t(bits.read(ml->nbBits[mlS]));
            ofS = of->newState[ofS] + uint32_t(bits.read(of->nbBits[ofS]));
            if (bits.bad) return ERR_CORRUPT;
        }
        // execute
        if (litPos + llen > lits.size()) return ERR_CORRUPT;
        out.insert(out.end(), lits.begin() + litPos,
                   lits.begin() + litPos + llen);
        litPos += llen;
        if (offset > out.size() - frameBase) return ERR_CORRUPT;
        if (out.size() - blockBase + mlen > size_t(kBlockMax) + lits.size())
            return ERR_CORRUPT;          // runaway guard
        size_t from = out.size() - offset;
        for (uint64_t k = 0; k < mlen; k++)
            out.push_back(out[from + k]);   // overlap-safe byte copy
    }
    if (!bits.done()) return ERR_CORRUPT;
    out.insert(out.end(), lits.begin() + litPos, lits.end());
    return 0;
}

// Decode one regular frame starting after its magic.  Advances *pos
// past the frame.  Appends to `out`.
int64_t decode_frame(const uint8_t* src, int64_t n, int64_t* pos,
                     std::vector<uint8_t>& out, int64_t cap) {
    int64_t p = *pos;
    if (p >= n) return ERR_CORRUPT;
    const uint8_t fhd = src[p++];
    if (fhd & 0x08) return ERR_CORRUPT;  // reserved bit
    const int fcsFlag = fhd >> 6;
    const bool single = (fhd >> 5) & 1;
    const bool checksum = (fhd >> 2) & 1;
    const int dictFlag = fhd & 3;
    if (!single) {
        if (p >= n) return ERR_CORRUPT;
        p++;                             // window descriptor (unused:
    }                                    // we bound blocks by kBlockMax)
    static const int kDictBytes[4] = {0, 1, 2, 4};
    uint32_t dictId = 0;
    for (int i = 0; i < kDictBytes[dictFlag]; i++) {
        if (p >= n) return ERR_CORRUPT;
        dictId |= uint32_t(src[p++]) << (8 * i);
    }
    if (dictId != 0) return ERR_UNSUPPORTED;
    int fcsBytes = 0;
    if (fcsFlag == 0) fcsBytes = single ? 1 : 0;
    else if (fcsFlag == 1) fcsBytes = 2;
    else if (fcsFlag == 2) fcsBytes = 4;
    else fcsBytes = 8;
    uint64_t fcs = 0;
    for (int i = 0; i < fcsBytes; i++) {
        if (p >= n) return ERR_CORRUPT;
        fcs |= uint64_t(src[p++]) << (8 * i);
    }
    if (fcsBytes == 2) fcs += 256;
    const size_t frameBase = out.size();
    FrameState fs;
    for (;;) {
        if (p + 3 > n) return ERR_CORRUPT;
        const uint32_t bh = src[p] | (uint32_t(src[p + 1]) << 8)
                          | (uint32_t(src[p + 2]) << 16);
        p += 3;
        const bool last = bh & 1;
        const int btype = (bh >> 1) & 3;
        const int64_t bsize = bh >> 3;
        if (btype == 3) return ERR_CORRUPT;
        const size_t before = out.size();
        if (btype == 0) {                // raw
            if (p + bsize > n || bsize > kBlockMax) return ERR_CORRUPT;
            if (int64_t(out.size()) + bsize > cap) return ERR_DSTSIZE;
            out.insert(out.end(), src + p, src + p + bsize);
            p += bsize;
        } else if (btype == 1) {         // RLE: bsize = regenerated size
            if (p + 1 > n) return ERR_CORRUPT;
            if (bsize > kBlockMax) return ERR_CORRUPT;
            if (int64_t(out.size()) + bsize > cap) return ERR_DSTSIZE;
            out.insert(out.end(), size_t(bsize), src[p]);
            p += 1;
        } else {                         // compressed
            if (p + bsize > n || bsize < 1) return ERR_CORRUPT;
            if (int64_t(out.size()) + kBlockMax > cap) return ERR_DSTSIZE;
            const int64_t rc = decode_block(src + p, bsize, fs, out,
                                            frameBase);
            if (rc != 0) return rc;
            p += bsize;
        }
        if (out.size() - before > size_t(kBlockMax)) return ERR_CORRUPT;
        if (last) break;
    }
    if (fcsBytes && out.size() - frameBase != fcs) return ERR_CORRUPT;
    if (checksum) {
        if (p + 4 > n) return ERR_CORRUPT;
        const uint32_t want = load32le(src + p);
        p += 4;
        const uint32_t got = uint32_t(
            xxh64(out.data() + frameBase, out.size() - frameBase, 0));
        if (want != got) return ERR_CORRUPT;
    }
    *pos = p;
    return 0;
}

}  // namespace

extern "C" {

int64_t zstd_decompress(const uint8_t* src, int64_t n,
                        uint8_t* dst, int64_t cap) {
    if (n < 0 || cap < 0) return ERR_CORRUPT;
    std::vector<uint8_t> out;
    out.reserve(size_t(cap < (1 << 20) ? cap : (1 << 20)));
    int64_t pos = 0;
    while (pos < n) {
        if (pos + 4 > n) return ERR_CORRUPT;
        const uint32_t magic = load32le(src + pos);
        if ((magic & 0xFFFFFFF0u) == kSkipMagicBase) {
            if (pos + 8 > n) return ERR_CORRUPT;
            const int64_t sz = load32le(src + pos + 4);
            if (pos + 8 + sz > n) return ERR_CORRUPT;
            pos += 8 + sz;
            continue;
        }
        if (magic != kMagic) return ERR_CORRUPT;
        pos += 4;
        const int64_t rc = decode_frame(src, n, &pos, out, cap);
        if (rc != 0) return rc;
    }
    if (int64_t(out.size()) > cap) return ERR_DSTSIZE;
    std::memcpy(dst, out.data(), out.size());
    return int64_t(out.size());
}

int64_t zstd_content_size(const uint8_t* src, int64_t n) {
    int64_t pos = 0, total = 0;
    while (pos < n) {
        if (pos + 4 > n) return -1;
        const uint32_t magic = load32le(src + pos);
        if ((magic & 0xFFFFFFF0u) == kSkipMagicBase) {
            if (pos + 8 > n) return -1;
            pos += 8 + load32le(src + pos + 4);
            continue;
        }
        if (magic != kMagic) return -1;
        if (pos + 5 > n) return -1;
        const uint8_t fhd = src[pos + 4];
        const int fcsFlag = fhd >> 6;
        const bool single = (fhd >> 5) & 1;
        if (fcsFlag == 0 && !single) return -1;   // size not declared
        int64_t p = pos + 5 + (single ? 0 : 1);
        static const int kDictBytes[4] = {0, 1, 2, 4};
        p += kDictBytes[fhd & 3];
        const int fcsBytes = fcsFlag == 0 ? 1 : fcsFlag == 1 ? 2
                           : fcsFlag == 2 ? 4 : 8;
        if (p + fcsBytes > n) return -1;
        uint64_t fcs = 0;
        for (int i = 0; i < fcsBytes; i++)
            fcs |= uint64_t(src[p + i]) << (8 * i);
        if (fcsBytes == 2) fcs += 256;
        total += int64_t(fcs);
        // cheap skip: we cannot know the frame's end without walking
        // blocks; callers only use this when ONE frame spans the input
        return total;
    }
    return total;
}

}  // extern "C"
