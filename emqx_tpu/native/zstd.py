"""Zstandard: ctypes front for zstd.cpp (RFC 8878 decoder) plus a
pure-Python compressing encoder, wired as Kafka record-batch codec 4
(SURVEY.md §2.4 — the zstd-erlang/NIF analog).

Posture mirrors the snappy/lz4 modules:

* **decode** is the full format (Huffman literals, FSE sequences,
  repeat offsets, checksums) in ``zstd.cpp`` — the Kafka FETCH side,
  where the broker must accept whatever a Java producer emitted; the
  pure-Python fallback ALSO covers the full non-dictionary format
  since round 5 (treeless literals, Repeat_Mode tables, repeat
  offsets, cross-block window matches — libzstd levels 1-22 proven),
  so a toolchain-less host only loses xxh64 verification and speed;
* **encode** produces real compressed blocks from pure Python: greedy
  LZ77 with sequences coded per-table as the cheapest of the spec's
  PREDEFINED FSE distributions, a 1-byte RLE table, or an
  **FSE-described table** fitted to the block's code statistics
  (RFC 8878 §4.1.1 serialization); literals coded as the smallest of
  raw / RLE / **Huffman** (package-merge length-limited canonical
  code; tree shipped as the direct 4-bit weight description or the
  **FSE-compressed weight description** — which lifts the direct
  form's 128-symbol cap, so high-byte binary payloads compress too;
  1- or 4-stream; TREELESS reuse when the frame's last tree codes a
  section more cheaply), repeat-offset codes, Repeat_Mode table
  reuse, cross-block window matches (frame-persistent LZ77 table)
  and the RLE block type, with raw-block fallback when compression
  doesn't pay — every non-dictionary construct of the format is
  exercised on encode.  Measured ratios: ~1000x on repetitive
  text/JSON, ~2-2.6x on skewed binary/small-alphabet data, 1.0
  floor on incompressible data.  Every mode is proven against
  libzstd.

Interop against system libzstd (both directions, levels 1-22) is
proven in ``tests/test_zstd.py``.  Without a toolchain,
``decompress_frame`` falls back to the pure-Python full-format
decoder, so both a bridge's own production AND foreign frames
round-trip toolchain-less (minus xxh64 verification).
"""

from __future__ import annotations

import ctypes
import struct
from collections import Counter as _Counter
from typing import List

from .build import load_library

__all__ = ["available", "compress_frame", "decompress_frame"]

_MAGIC = 0xFD2FB528
_BLOCK_MAX = 1 << 17            # spec Block_Maximum_Size ceiling
_MAX_OUTPUT = 256 << 20         # same hostile-input cap as lz4/snappy

_lib = None
_loaded = False


def _load():
    global _lib, _loaded
    if not _loaded:
        _loaded = True
        lib = load_library("zstd")
        if lib is not None:
            lib.zstd_decompress.restype = ctypes.c_int64
            lib.zstd_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int64]
            lib.zstd_content_size.restype = ctypes.c_int64
            lib.zstd_content_size.argtypes = [
                ctypes.c_char_p, ctypes.c_int64]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def decompress_frame(data: bytes) -> bytes:
    """Decode a (possibly multi-)frame zstd stream.  The native
    decoder is the fast path; without a toolchain a pure-Python
    fallback decodes the full non-dictionary format too (Huffman
    literals incl. treeless reuse, all four sequence-table modes,
    repeat offsets, cross-block window matches) — foreign libzstd
    frames at every level round-trip either way; the fallback skips
    only xxh64 checksum verification.  ValueError on corrupt or
    dictionary-keyed input."""
    lib = _load()
    if lib is None:
        return _py_store_decompress(data)
    hint = lib.zstd_content_size(data, len(data))
    if hint >= 0:
        cap = min(_MAX_OUTPUT, hint + _BLOCK_MAX)
    else:
        cap = min(_MAX_OUTPUT, max(1 << 20, len(data) * 8))
    while True:
        dst = ctypes.create_string_buffer(max(1, cap))
        n = lib.zstd_decompress(data, len(data), dst, cap)
        if n >= 0:
            return dst.raw[:n]
        if n == -2 and cap < _MAX_OUTPUT:        # grow and retry
            cap = min(_MAX_OUTPUT, cap * 4)
            continue
        if n == -3:
            raise ValueError("zstd: dictionary frames unsupported")
        raise ValueError("zstd: corrupt frame")


def _py_store_decompress(data: bytes) -> bytes:
    """Toolchain-less fallback: full non-dictionary frame decode in
    pure Python (see ``_py_block_decode``).  Content checksums are
    NOT verified here (no xxh64 without the native module); declared
    frame sizes still are."""
    try:
        return _py_store_walk(data)
    except IndexError:
        # short reads past the end must surface as the same corrupt-
        # input error class the native path raises (the Kafka fetch
        # loop classifies on it)
        raise ValueError("zstd: truncated frame")


def _py_store_walk(data: bytes) -> bytes:
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        if pos + 4 > n:
            raise ValueError("zstd: truncated magic")
        magic = int.from_bytes(data[pos:pos + 4], "little")
        if (magic & 0xFFFFFFF0) == 0x184D2A50:       # skippable frame
            if pos + 8 > n:
                raise ValueError("zstd: truncated skippable frame")
            pos += 8 + int.from_bytes(data[pos + 4:pos + 8], "little")
            if pos > n:
                raise ValueError("zstd: truncated skippable frame")
            continue
        if magic != _MAGIC:
            raise ValueError("zstd: bad magic")
        pos += 4
        if pos >= n:
            raise ValueError("zstd: truncated frame header")
        fhd = data[pos]
        pos += 1
        if fhd & 0x08:
            raise ValueError("zstd: reserved FHD bit")
        single = (fhd >> 5) & 1
        if not single:
            pos += 1                                 # window descriptor
        dict_bytes = (0, 1, 2, 4)[fhd & 3]
        if dict_bytes and any(data[pos:pos + dict_bytes]):
            raise ValueError("zstd: dictionary frames unsupported")
        pos += dict_bytes
        fcs_flag = fhd >> 6
        fcs_bytes = (1 if single else 0, 2, 4, 8)[fcs_flag]
        fcs = int.from_bytes(data[pos:pos + fcs_bytes], "little") \
            + (256 if fcs_bytes == 2 else 0) if fcs_bytes else None
        pos += fcs_bytes
        frame_base = len(out)
        rep = [1, 4, 8]             # per-frame repeat-offset history
        fstate: dict = {}           # frame-persistent huf/seq tables
        while True:
            if pos + 3 > n:
                raise ValueError("zstd: truncated block header")
            bh = int.from_bytes(data[pos:pos + 3], "little")
            pos += 3
            last, btype, bsize = bh & 1, (bh >> 1) & 3, bh >> 3
            if btype == 0:                           # raw
                if pos + bsize > n:
                    raise ValueError("zstd: truncated raw block")
                out += data[pos:pos + bsize]
                pos += bsize
            elif btype == 1:                         # RLE
                if pos + 1 > n or bsize > _BLOCK_MAX:
                    raise ValueError("zstd: bad RLE block")
                out += data[pos:pos + 1] * bsize
                pos += 1
            else:                                # compressed block
                if pos + bsize > n:
                    raise ValueError("zstd: truncated block")
                out += _py_block_decode(
                    data[pos:pos + bsize], rep, fstate,
                    window=out, wbase=frame_base)
                pos += bsize
            if len(out) > _MAX_OUTPUT:
                raise ValueError("zstd: output exceeds cap")
            if last:
                break
        if fhd & 0x04:                               # checksum present
            pos += 4                                 # not verified here
        if fcs is not None and len(out) - frame_base != fcs:
            raise ValueError("zstd: content size mismatch")
    return bytes(out)


# ---- encoder: real compressed blocks over the PREDEFINED tables ------------
#
# Greedy LZ77 matcher -> sequences coded with RFC 8878's predefined
# FSE distributions (modes byte 0x00) + RAW literals.  That subset
# needs no Huffman or table descriptions, stays pure Python (works
# toolchain-less), and every consumer decodes it.  FSE encoding walks
# the DECODE table backwards: processing symbols in reverse, the
# predecessor state for (symbol, next_state) is the unique entry whose
# [newState, newState + 2^nbBits) interval contains next_state; the
# offset into that interval is the bits the decoder will read.

_LL_NORM = (4, 3, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 1, 1, 1,
            2, 2, 2, 2, 2, 2, 2, 2, 2, 3, 2, 1, 1, 1, 1, 1,
            -1, -1, -1, -1)
_ML_NORM = (1, 4, 3, 2, 2, 2, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1,
            1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
            1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, -1, -1,
            -1, -1, -1, -1, -1)
_OF_NORM = (1, 1, 1, 1, 1, 1, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1,
            1, 1, 1, 1, 1, 1, 1, 1, -1, -1, -1, -1, -1)

_LL_BASE = (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
            16, 18, 20, 22, 24, 28, 32, 40, 48, 64, 128, 256, 512,
            1024, 2048, 4096, 8192, 16384, 32768, 65536)
_LL_BITS = (0,) * 16 + (1, 1, 1, 1, 2, 2, 3, 3, 4, 6, 7, 8, 9, 10,
                        11, 12, 13, 14, 15, 16)
_ML_BASE = (3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18,
            19, 20, 21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32,
            33, 34, 35, 37, 39, 41, 43, 47, 51, 59, 67, 83, 99, 131,
            259, 515, 1027, 2051, 4099, 8195, 16387, 32771, 65539)
_ML_BITS = (0,) * 32 + (1, 1, 1, 1, 2, 2, 3, 3, 4, 4, 5, 7, 8, 9,
                        10, 11, 12, 13, 14, 15, 16)


_FSE_CACHE: dict = {}


def _fse_decode_table(norm, log, cache: bool = True):
    """Python twin of zstd.cpp's fse_build -> (symbol, nbBits,
    newState, by_symbol); the encoder walks it backwards, the
    fallback decoder forwards.  Cached only for the static predefined
    tables — per-block described tables would grow the cache
    unboundedly."""
    if cache and norm in _FSE_CACHE:
        return _FSE_CACHE[norm]
    size = 1 << log
    symbol = [0] * size
    next_ = {}
    high = size - 1
    for s, c in enumerate(norm):
        if c == -1:
            symbol[high] = s
            high -= 1
            next_[s] = 1
        elif c > 0:
            next_[s] = c
    step = (size >> 1) + (size >> 3) + 3
    pos = 0
    for s, c in enumerate(norm):
        for _ in range(max(0, c)):
            symbol[pos] = s
            while True:
                pos = (pos + step) & (size - 1)
                if pos <= high:
                    break
    nb = [0] * size
    new = [0] * size
    for t in range(size):
        ns = next_[symbol[t]]
        next_[symbol[t]] += 1
        b = log - (ns.bit_length() - 1)
        nb[t] = b
        new[t] = (ns << b) - size
    # per-symbol entry lists for the reverse walk
    by_sym = {}
    for t in range(size):
        by_sym.setdefault(symbol[t], []).append(t)
    entry = (symbol, nb, new, by_sym)
    if cache:
        _FSE_CACHE[norm] = entry
    return entry


class _FseEnc:
    """One interleaved FSE stream's state, walked in reverse symbol
    order.  push(code, next bits...) returns the transition bits."""

    def __init__(self, norm, log, cache: bool = True):
        self.log = log
        _, self.nb, self.new, self.by_sym = _fse_decode_table(
            norm, log, cache)
        self.state = None

    def start(self, code):              # last symbol: any matching entry
        self.state = self.by_sym[code][0]

    def prev(self, code):
        """Move to the predecessor entry for `code`; returns
        (bits_value, bits_width) the decoder will read to get from the
        predecessor to the state we were just in."""
        nxt = self.state
        for t in self.by_sym[code]:
            if self.new[t] <= nxt < self.new[t] + (1 << self.nb[t]):
                self.state = t
                return nxt - self.new[t], self.nb[t]
        raise AssertionError("fse: no predecessor state")   # unreachable


class _BitWriter:
    """Forward LSB-first writer; the decoder reads it backwards, so
    items are pushed in REVERSE read order; finish() adds the sentinel
    bit and pads to bytes.  Completed low bytes flush into a bytearray
    so the accumulator stays a small int (a single growing int made
    sequence-dense blocks quadratic)."""

    def __init__(self):
        self.out = bytearray()
        self.acc = 0
        self.n = 0

    def push(self, value, width):
        if width:
            self.acc |= (value & ((1 << width) - 1)) << self.n
            self.n += width
            while self.n >= 8:
                self.out.append(self.acc & 0xFF)
                self.acc >>= 8
                self.n -= 8

    def finish(self) -> bytes:
        self.acc |= 1 << self.n         # sentinel
        self.n += 1
        return bytes(self.out) + self.acc.to_bytes((self.n + 7) // 8,
                                                   "little")


def _ll_code(v):
    if v < 16:
        return v
    i = 16
    while i + 1 < len(_LL_BASE) and _LL_BASE[i + 1] <= v:
        i += 1
    return i


def _ml_code(v):
    if v < 35:
        return v - 3
    i = 32
    while i + 1 < len(_ML_BASE) and _ML_BASE[i + 1] <= v:
        i += 1
    return i


# ---- FSE table descriptions (RFC 8878 §4.1.1) -----------------------------
#
# Described tables replace the predefined distributions with ones
# fitted to the block's actual code statistics; the description is a
# FORWARD bitstream (4-bit accuracy log, then variable-width
# normalized counts with 2-bit zero-run repeats), mirrored off
# zstd.cpp's fse_parse.


class _FwdBitWriter(_BitWriter):
    """Forward LSB-first writer (table descriptions are read forward,
    unlike the backward sequence bitstreams): same accumulator as
    _BitWriter, but finish() pads plainly — no sentinel bit."""

    def finish(self) -> bytes:
        if self.n:
            self.out.append(self.acc & 0xFF)
            self.acc = 0
            self.n = 0
        return bytes(self.out)


def _fse_normalize(freqs: dict, log: int, nsyms: int):
    """Normalize symbol counts to sum exactly 2**log, every present
    symbol >= 1 — a valid (if not always optimal) zstd table."""
    size = 1 << log
    total = sum(freqs.values())
    norm = [0] * nsyms
    scaled = {}
    for s, c in freqs.items():
        scaled[s] = max(1, c * size // total)
    excess = sum(scaled.values()) - size
    if excess > 0:
        # trim from the largest counts (keeps every present >= 1)
        for s in sorted(scaled, key=lambda s: -scaled[s]):
            if excess <= 0:
                break
            cut = min(excess, scaled[s] - 1)
            scaled[s] -= cut
            excess -= cut
        if excess > 0:
            return None                 # log too small for this set
    elif excess < 0:
        # give the deficit to the most frequent symbol
        top = max(scaled, key=lambda s: (freqs[s], -s))
        scaled[top] -= excess
    for s, c in scaled.items():
        norm[s] = c
    return norm


def _fse_write_desc(norm, log: int) -> bytes:
    """Serialize a normalized table: the exact inverse of zstd.cpp's
    fse_parse (libzstd FSE_writeNCount layout)."""
    size = 1 << log
    w = _FwdBitWriter()
    w.push(log - 5, 4)
    remaining = size + 1
    threshold = size
    nbits = log + 1
    sym = 0
    last = max(s for s, c in enumerate(norm) if c) \
        if any(norm) else 0
    while remaining > 1 and sym <= last:
        count = norm[sym]
        sym += 1
        mx = (2 * threshold - 1) - remaining
        remaining -= -count if count < 0 else count
        value = count + 1               # -1 encodes "less than 1"
        if value >= threshold:
            value += mx
        w.push(value, nbits - 1 if value < mx else nbits)
        if count == 0:
            # the decoder always reads one 2-bit zero-run field after
            # a zero count (rep==3 chains further fields)
            run = 0
            while sym <= last and norm[sym] == 0:
                run += 1
                sym += 1
            r = run
            while True:
                w.push(min(r, 3), 2)
                if r < 3:
                    break
                r -= 3
        while remaining < threshold:
            nbits -= 1
            threshold >>= 1
    if remaining != 1:
        return b""                      # invalid normalization
    return w.finish()


class _FwdBitReader:
    """Forward LSB-first reader for table descriptions."""

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0
        self.limit = len(data) * 8

    def read(self, width: int) -> int:
        if self.pos + width > self.limit:
            raise ValueError("zstd: table description over-read")
        lo = self.pos
        self.pos += width
        byte0 = lo >> 3
        span = (width + (lo & 7) + 7) >> 3
        acc = int.from_bytes(self.data[byte0:byte0 + span], "little")
        return (acc >> (lo & 7)) & ((1 << width) - 1)

    def peek(self, width: int) -> int:
        save = self.pos
        try:
            v = self.read(width)
        finally:
            self.pos = save
        return v

    def bytes_used(self) -> int:
        return (self.pos + 7) >> 3


def _fse_parse_py(data: bytes, maxlog: int, maxsym: int):
    """Python twin of zstd.cpp fse_parse: FSE table description ->
    ((symbol, nb, new, by_sym), log, bytes consumed)."""
    bits = _FwdBitReader(data)
    log = bits.read(4) + 5
    if log > maxlog:
        raise ValueError("zstd: accuracy log too large")
    size = 1 << log
    remaining = size + 1
    threshold = size
    nbits = log + 1
    norm = [0] * (maxsym + 1)
    sym = 0
    prev_zero = False
    while remaining > 1 and sym <= maxsym:
        if prev_zero:
            while True:
                rep = bits.read(2)
                sym += rep
                if sym > maxsym + 1:
                    raise ValueError("zstd: zero-run past symbol cap")
                if rep != 3:
                    break
            prev_zero = False
            continue
        mx = (2 * threshold - 1) - remaining
        if bits.peek(nbits - 1) < mx:
            count = bits.read(nbits - 1)
        else:
            count = bits.read(nbits)
            if count >= threshold:
                count -= mx
        count -= 1
        remaining -= -count if count < 0 else count
        if remaining < 1 or sym > maxsym:
            raise ValueError("zstd: bad table description")
        norm[sym] = count
        sym += 1
        prev_zero = count == 0
        while remaining < threshold:
            nbits -= 1
            threshold >>= 1
    if remaining != 1:
        raise ValueError("zstd: bad table description")
    table = _fse_decode_table(tuple(norm[:sym]), log, cache=False)
    return table, log, bits.bytes_used()


# ---- Huffman literal encoding ---------------------------------------------
#
# Canonical code per the decoder's table construction (zstd.cpp
# huf_build): table ranges are assigned weight-ascending (longest
# codes first), symbol-ascending within a weight, so a symbol's code
# is its range start shifted down by 2^(weight-1).  Lengths come from
# package-merge (optimal length-limited, Kraft-complete by
# construction).  The tree ships as the DIRECT 4-bit weight
# description (RFC 8878 §4.2.1.1), which caps the describable symbol
# range at 128 — literals with higher bytes fall back to raw/RLE
# rather than growing FSE-compressed-weights machinery.

_HUF_MAX_BITS = 11


def _package_merge(freqs: dict, limit: int) -> dict:
    """Optimal length-limited prefix code: symbol -> code length
    (1..limit), Kraft sum exactly 1.  Classic package-merge: L-1
    rounds of pair-and-merge; a symbol's length = how many of the
    first 2n-2 packages contain it."""
    items = sorted((c, (s,)) for s, c in freqs.items())
    packages = list(items)
    for _ in range(limit - 1):
        paired = [
            (packages[i][0] + packages[i + 1][0],
             packages[i][1] + packages[i + 1][1])
            for i in range(0, len(packages) - 1, 2)
        ]
        packages = sorted(items + paired)
    lengths: dict = {}
    for _, syms in packages[: 2 * len(items) - 2]:
        for s in syms:
            lengths[s] = lengths.get(s, 0) + 1
    return lengths


def _huf_fse_weights(weights: List[int]):
    """FSE-compressed Huffman weight description (RFC 8878
    §4.2.1.2): header byte (total compressed size < 128) + FSE table
    description + backward two-state interleaved bitstream.  This is
    what lifts the direct description's 128-symbol cap, so literals
    with high bytes (binary payloads) still get Huffman.  Returns
    None when it doesn't apply or doesn't beat alternatives; the
    result is verified by decode simulation (stream termination by
    over-read has edge cases when transition widths hit zero)."""
    n = len(weights)
    if n < 2:
        return None
    hist: dict = {}
    for wt in weights:
        hist[wt] = hist.get(wt, 0) + 1
    # weights are 0..12, so 13 distinct values at most — any log >= 5
    # fits every present symbol
    log = max(5, min(6, (n - 1).bit_length() - 2 if n > 4 else 5))
    norm = _fse_normalize(hist, log, max(hist) + 1)
    if norm is None:
        return None
    desc = _fse_write_desc(norm, log)
    if not desc:
        return None
    enc1 = _FseEnc(tuple(norm), log, cache=False)
    enc2 = _FseEnc(tuple(norm), log, cache=False)
    c1 = weights[0::2]                  # stream 1: even positions
    c2 = weights[1::2]                  # stream 2: odd positions
    if not c2:
        return None
    enc1.start(c1[-1])
    enc2.start(c2[-1])
    bits1 = [enc1.prev(c) for c in reversed(c1[:-1])]
    bits2 = [enc2.prev(c) for c in reversed(c2[:-1])]
    w = _BitWriter()
    i1 = i2 = 0
    for j in range(n - 3, -1, -1):      # transitions, last written first
        if j % 2 == 0:
            w.push(*bits1[i1])
            i1 += 1
        else:
            w.push(*bits2[i2])
            i2 += 1
    w.push(enc2.state, log)             # decoder reads s1 then s2
    w.push(enc1.state, log)
    stream = w.finish()
    total = len(desc) + len(stream)
    if total >= 128:
        return None
    blob = bytes([total]) + desc + stream
    return blob if _huf_fse_weights_decode(blob) == weights else None


def _huf_fse_weights_decode(blob: bytes):
    """Decode-sim twin of zstd.cpp's FSE-weights branch of huf_parse;
    also the fallback decoder's parse path.  Returns the weight list
    or None on malformed input."""
    try:
        hbyte = blob[0]
        area = blob[1:1 + hbyte]
        if len(area) != hbyte:
            return None
        (sym, nb, new, _), log, used = _fse_parse_py(area, 6, 255)
        bits = _BitReader(area[used:])
        s1 = bits.read(log)
        s2 = bits.read(log)
    except (ValueError, IndexError):
        return None
    out: List[int] = []
    cur, oth = s1, s2
    while True:
        if len(out) >= 255:
            return None
        out.append(sym[cur])
        try:
            ns = new[cur] + bits.read(nb[cur])
        except ValueError:              # over-read ends the stream:
            out.append(sym[oth])        # flush the OTHER state
            return out
        cur, oth = oth, ns              # update, then swap streams


def _huf_plan(literals: bytes):
    """Code plan for Huffman-coding `literals`: (lengths, exact
    stream bits, tree-description bytes, freqs), or None when Huffman
    can't apply.  Cheap relative to encoding — Counter counts in C
    and package-merge works on <=256 symbols — so it doubles as the
    size ESTIMATE that gates whether a full encode is worth doing.
    The tree-size term uses the direct form; the FSE weight form
    (chosen at encode time when smaller) only shrinks it."""
    n = len(literals)
    if n < 32:
        return None                     # header+tree overhead dominates
    freqs = dict(_Counter(literals))
    if len(freqs) < 2:
        return None                     # caller's RLE path
    max_sym = max(freqs)                # tree-size estimate (direct
    lengths = _package_merge(freqs, _HUF_MAX_BITS)  # form; FSE often
                                                    # beats it)
    bits = sum(freqs[s] * lengths[s] for s in freqs)
    return lengths, bits, 1 + (max_sym + 1) // 2, freqs


def _huf_estimate(plan, n: int):
    """Estimated Huffman-section size in bytes for a plan over n
    literals (slight overcount: per-stream sentinel/padding assumed
    worst-case), or None."""
    if plan is None:
        return None
    _, bits, tree, _ = plan
    if n <= 1023:
        return 3 + tree + (bits + 1 + 7) // 8
    return 5 + tree + 6 + bits // 8 + 4


def _huf_codes(lengths: dict):
    """Canonical codes for a length assignment, per the decoder's
    table construction (huf_build): weight-ascending ranges, symbol-
    ascending within a weight."""
    maxbits = max(lengths.values())
    codes = {}
    pos = 0
    for w in range(1, maxbits + 1):
        ln = maxbits + 1 - w
        for s in sorted(lengths):
            if lengths[s] == ln:
                codes[s] = (pos >> (w - 1), ln)
                pos += 1 << (w - 1)
    assert pos == 1 << maxbits          # Kraft-complete by construction
    return codes, maxbits


def _huf_section_bytes(literals: bytes, codes: dict, tree: bytes,
                       ltype: int):
    """Assemble one Huffman literals section (type 2 with a tree, or
    type 3 treeless with ``tree=b""``): header + tree + backward
    stream(s); None when it doesn't fit its header formats or doesn't
    pay."""
    n = len(literals)

    def enc_stream(chunk):
        w = _BitWriter()
        for b in reversed(chunk):
            c, ln = codes[b]
            w.push(c, ln)
        return w.finish()

    if n <= 1023:                       # 1 stream, 10-bit sizes
        stream = enc_stream(literals)
        comp = len(tree) + len(stream)
        if comp >= n or comp > 1023:
            return None
        head = (ltype | (n << 4) | (comp << 14)).to_bytes(3, "little")
        return head + tree + stream
    per = (n + 3) // 4                  # 4 streams + 6-byte jump table
    chunks = [literals[0:per], literals[per:2 * per],
              literals[2 * per:3 * per], literals[3 * per:]]
    if not chunks[3]:
        return None                     # stream 4 must be non-empty
    streams = [enc_stream(c) for c in chunks]
    if any(len(s) > 0xFFFF for s in streams[:3]):
        return None
    jump = struct.pack("<HHH", *(len(s) for s in streams[:3]))
    comp = len(tree) + 6 + sum(len(s) for s in streams)
    if comp >= n:
        return None
    if n <= 16383 and comp <= 16383:    # size_format 2: 14-bit sizes
        head = (ltype | (2 << 2) | (n << 4) | (comp << 18)).to_bytes(
            4, "little")
    else:                               # size_format 3: 18-bit sizes
        head = (ltype | (3 << 2) | (n << 4) | (comp << 22)).to_bytes(
            5, "little")
    return head + tree + jump + b"".join(streams)


def _huf_literals_section(literals: bytes, plan=None, prev=None):
    """Huffman literals section — (bytes, tree_info) where tree_info
    is ("fresh", lengths) for a type-2 section (the decoder keeps its
    tree for later treeless reuse), "treeless" for type 3, or the
    pair (None, None) when Huffman can't be used or doesn't pay.
    ``prev`` is the (lengths) of the frame's last shipped tree: when
    it covers this section's bytes and codes them more cheaply than
    a fresh tree + description, the section ships TREELESS."""
    n = len(literals)
    if plan is None:
        plan = _huf_plan(literals)
    if plan is None:
        return None, None
    lengths, fresh_bits, _, freqs = plan
    max_sym = max(lengths)
    codes, maxbits = _huf_codes(lengths)
    nw = max_sym                        # weights 0..max_sym-1; last inferred
    weights = [maxbits + 1 - lengths[s] if s in lengths else 0
               for s in range(nw)]
    tree = None
    if nw <= 128:                       # direct 4-bit description
        packed = bytearray([127 + nw])
        for i in range(0, nw, 2):
            packed.append((weights[i] << 4)
                          | (weights[i + 1] if i + 1 < nw else 0))
        tree = bytes(packed)
    if tree is None or len(tree) > 5:
        # an FSE weight blob is never under ~5 bytes (header + table
        # description + two init states), so tiny direct trees skip
        # the encode + decode-simulation cost outright
        fse_tree = _huf_fse_weights(weights)
        if fse_tree is not None and (tree is None
                                     or len(fse_tree) < len(tree)):
            tree = fse_tree
    # choose by ESTIMATE first, then encode only the winner (the
    # per-byte bit-pushing dominates encode cost — building both
    # sections would double it on exactly the stable-distribution
    # workload treeless targets); fall back to the loser only if the
    # winner's section doesn't fit its header formats
    prev_bits = None
    if prev is not None and all(s in prev for s in freqs):
        prev_bits = sum(freqs[s] * prev[s] for s in freqs)
    fresh_total = (len(tree) * 8 + fresh_bits) if tree is not None \
        else None

    def fresh_section():
        if tree is None:
            return None, None
        sec = _huf_section_bytes(literals, codes, tree, 2)
        return (sec, ("fresh", lengths)) if sec is not None \
            else (None, None)

    def treeless_section():
        if prev_bits is None:
            return None, None
        pcodes, _ = _huf_codes(prev)
        sec = _huf_section_bytes(literals, pcodes, b"", 3)
        return (sec, "treeless") if sec is not None else (None, None)

    if prev_bits is not None and (fresh_total is None
                                  or prev_bits < fresh_total):
        best, info = treeless_section()
        if best is None:
            best, info = fresh_section()
    else:
        best, info = fresh_section()
        if best is None:
            best, info = treeless_section()
    return best, info


def _lit_section(literals: bytes, plan=None, prev=None):
    """Smallest literals section: raw, RLE, or Huffman-compressed
    (fresh tree or treeless reuse of ``prev``).  Returns
    (bytes, tree_info) — tree_info as _huf_literals_section (None for
    raw/RLE sections, which don't touch the decoder's tree)."""
    ln = len(literals)
    if ln and ln == literals.count(literals[:1]):   # single repeated byte
        if ln < 32:
            return bytes([0x01 | (ln << 3)]) + literals[:1], None
        if ln < 4096:
            return (0x01 | 0x04 | (ln << 4)).to_bytes(2, "little") \
                + literals[:1], None
        return (0x01 | 0x0C | (ln << 4)).to_bytes(3, "little") \
            + literals[:1], None
    if ln < 32:
        raw = bytes([ln << 3]) + literals
    elif ln < 4096:
        raw = (0x04 | (ln << 4)).to_bytes(2, "little") + literals
    else:
        raw = (0x0C | (ln << 4)).to_bytes(3, "little") + literals
    huf, info = _huf_literals_section(literals, plan=plan, prev=prev)
    if huf is not None and len(huf) < len(raw):
        return huf, info
    return raw, None


def _table_bits(hist: dict, norm, log: int):
    """Estimated stream bits coding `hist` with `norm`, or None when
    the table doesn't cover every present symbol."""
    bits = 0
    for s, c in hist.items():
        np_ = norm[s] if s < len(norm) else 0
        np_ = 1 if np_ == -1 else np_
        if np_ <= 0:
            return None
        bits += c * (log - (np_.bit_length() - 1))
    return bits


def _seq_table_choice(hist: dict, predef_norm, predef_log: int,
                      maxlog: int, nsyms: int, prev=None):
    """Pick the cheapest coding for one sequence-code stream:
    (mode, norm, log, desc) with mode 0 predefined / 1 RLE /
    2 FSE-described / 3 Repeat (reuse `prev`, the table the decoder
    currently holds for this slot — zero description bytes).
    Estimates bits as log - floor(log2(count))."""
    opts = []
    if len(hist) == 1:
        sym = next(iter(hist))
        rle = [0] * (sym + 1)
        rle[sym] = 1                    # log-0 single-entry table
        opts.append((8, 1, tuple(rle), 0, bytes([sym])))
    bits_p = _table_bits(hist, predef_norm, predef_log)
    if bits_p is not None:
        opts.append((bits_p, 0, predef_norm, predef_log, b""))
    total = sum(hist.values())
    log = max((total - 1).bit_length() - 2,
              (len(hist) - 1).bit_length())
    log = max(5, min(maxlog, log))
    while (1 << log) < len(hist) and log < maxlog:
        log += 1
    norm = _fse_normalize(hist, log, nsyms)
    desc = _fse_write_desc(norm, log) if norm is not None else b""
    if desc:
        bits_d = _table_bits(hist, norm, log)
        if bits_d is not None:
            opts.append((len(desc) * 8 + bits_d, 2, tuple(norm), log,
                         desc))
    if prev is not None:
        bits_r = _table_bits(hist, prev[0], prev[1])
        if bits_r is not None:          # ties go to earlier options
            opts.append((bits_r, 3, prev[0], prev[1], b""))
    if not opts:
        return 0, predef_norm, predef_log, b""
    _, mode, n, lg, d = min(opts, key=lambda o: o[0])
    return mode, n, lg, d


_LZ_WINDOW = 1 << 19                    # cross-block match range
_LZ_TABLE_CAP = 600_000                 # > window's max distinct
                                        # 4-grams (524288), so in-window
                                        # history is NEVER evicted —
                                        # which candidate to drop would
                                        # otherwise be an unknowable
                                        # bet; ~60 MB peak dict


def _find_sequences(buf: bytes, start: int = 0, end: int = -1,
                    table=None):
    """Greedy LZ77 over buf[start:end] with a 4-gram table that the
    CALLER persists across a frame's blocks — matches may reach up to
    _LZ_WINDOW bytes back into prior blocks (cross-block window
    matches; every decoder resolves them against the frame window,
    and a single-segment frame's window is its whole content).
    Returns ([(lit_len, match_len, offset)], literals,
    tail_literals)."""
    if end < 0:
        end = len(buf)
    if table is None:
        table = {}
    seqs = []
    lits = bytearray()
    i = start
    anchor = start
    while i + 4 <= end:
        key = buf[i:i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is None or i - cand > _LZ_WINDOW:
            i += 1
            continue
        length = 4
        while i + length < end and buf[cand + length] == buf[i + length]:
            length += 1
        lits += buf[anchor:i]
        seqs.append((i - anchor, length, i - cand))
        i += length
        anchor = i
    return seqs, bytes(lits), bytes(buf[anchor:end])


def _commit_lit(tstate, info) -> None:
    """Mirror the decoder's literal-tree state: a shipped type-2
    section replaces the frame tree; treeless/raw/RLE leave it."""
    if tstate is not None and isinstance(info, tuple) \
            and info[0] == "fresh":
        tstate["huf"] = info[1]


def _compress_block(data: bytes, start: int = 0, end: int = -1,
                    rep=None, table=None, tstate=None):
    """One compressed block body (literals + sequences sections), or
    None when neither sequences nor literal compression pay.  With no
    sequences the block can still compress via its literals section
    alone (Huffman/RLE + a zero sequence count).

    ``rep`` is the frame's 3-slot repeat-offset history (RFC 8878
    §3.1.1.5, persists across the frame's blocks); it is mutated ONLY
    when the sequence-coded body is actually returned — the
    literals-only and raw fallbacks execute no sequences.  ``table``
    is the frame-persistent LZ77 4-gram table enabling cross-block
    matches (see _find_sequences); the block itself is
    data[start:end]."""
    if end < 0:
        end = len(data)
    block = data[start:end]
    if table is not None and len(table) > _LZ_TABLE_CAP:
        # bound memory: drop out-of-window entries.  The cap exceeds
        # the window's maximum distinct-4-gram count, so this always
        # retains the FULL in-window history (the defensive tail-trim
        # is unreachable unless the constants drift apart).
        fresh = {k: p for k, p in table.items()
                 if start - p <= _LZ_WINDOW}
        table.clear()
        if len(fresh) > _LZ_TABLE_CAP:
            fresh = dict(sorted(fresh.items(),
                                key=lambda kv: kv[1])[-_LZ_TABLE_CAP:])
        table.update(fresh)
    seqs, lits, tail = _find_sequences(data, start, end, table)
    nseq = len(seqs)
    if nseq >= 0x7F00:
        return None
    literals = lits + tail
    ts = tstate if tstate is not None else {}
    lhead, linfo = _lit_section(literals, prev=ts.get("huf"))
    if not nseq:                        # literals ARE the whole block
        body = lhead + b"\x00"
        if len(body) < len(block):
            _commit_lit(tstate, linfo)  # compressed block: its type-2
            return body                 # tree becomes the frame tree
        return None
    if nseq < 128:
        shead = bytes([nseq])
    else:
        shead = bytes([128 + (nseq >> 8), nseq & 0xFF])
    nrep = list(rep) if rep is not None else [1, 4, 8]
    codes = []
    ofvs = []
    for ll_len, m_len, offset in seqs:
        # repeat-offset codes: ofv 1-3 reference the history (shifted
        # when ll == 0, where "same as last" is unreachable by design
        # — the match would just have been longer)
        if ll_len != 0:
            if offset == nrep[0]:
                ofv = 1
            elif offset == nrep[1]:
                ofv = 2
            elif offset == nrep[2]:
                ofv = 3
            else:
                ofv = offset + 3
        else:
            if offset == nrep[1]:
                ofv = 1
            elif offset == nrep[2]:
                ofv = 2
            elif offset == nrep[0] - 1 and offset >= 1:
                ofv = 3
            else:
                ofv = offset + 3
        # history update mirrors the decoder exactly
        if ofv > 3:
            nrep = [offset, nrep[0], nrep[1]]
        else:
            idx = ofv - 1 + (1 if ll_len == 0 else 0)
            if idx == 1:
                nrep = [offset, nrep[0], nrep[2]]
            elif idx >= 2:
                nrep = [offset, nrep[0], nrep[1]]
        ofvs.append(ofv)
        codes.append((_ll_code(ll_len), ofv.bit_length() - 1,
                      _ml_code(m_len)))
    # per-table coding choice fitted to this block's statistics:
    # predefined distributions, RLE (one distinct code), or a
    # described table (RFC 8878 §4.1.1) when the fitted table +
    # description beat the predefined bit cost
    hists: List[dict] = [{}, {}, {}]
    for triple in codes:
        for t, c in enumerate(triple):
            hists[t][c] = hists[t].get(c, 0) + 1
    ll_m, ll_norm, ll_log, ll_desc = _seq_table_choice(
        hists[0], _LL_NORM, 6, 9, 36, prev=ts.get("ll"))
    of_m, of_norm, of_log, of_desc = _seq_table_choice(
        hists[1], _OF_NORM, 5, 8, 32, prev=ts.get("of"))
    ml_m, ml_norm, ml_log, ml_desc = _seq_table_choice(
        hists[2], _ML_NORM, 6, 9, 53, prev=ts.get("ml"))
    shead += bytes([(ll_m << 6) | (of_m << 4) | (ml_m << 2)])
    shead += ll_desc + of_desc + ml_desc        # LL, OF, ML order
    ll = _FseEnc(ll_norm, ll_log, cache=ll_m == 0)
    of = _FseEnc(of_norm, of_log, cache=of_m == 0)
    ml = _FseEnc(ml_norm, ml_log, cache=ml_m == 0)
    w = _BitWriter()
    for i in range(nseq - 1, -1, -1):
        lc, oc, mc = codes[i]
        ll_len, m_len, offset = seqs[i]
        if i == nseq - 1:
            ll.start(lc)
            of.start(oc)
            ml.start(mc)
        else:
            # decoder reads transitions LL,ML,OF after symbol i's
            # extras; reversed write order: OF, ML, LL
            w.push(*of.prev(oc))
            w.push(*ml.prev(mc))
            w.push(*ll.prev(lc))
        # decoder reads extras OF,ML,LL; reversed: LL, ML, OF
        w.push(ll_len - _LL_BASE[lc], _LL_BITS[lc])
        w.push(m_len - _ML_BASE[mc], _ML_BITS[mc])
        w.push(ofvs[i] - (1 << oc), oc)
    # decoder reads init states LL,OF,ML; reversed: ML, OF, LL
    # (an RLE table has log 0: its state reads zero bits)
    w.push(ml.state, ml_log)
    w.push(of.state, of_log)
    w.push(ll.state, ll_log)
    body = lhead + shead + w.finish()
    # on short-match-dense data (small alphabets) a greedy LZ77
    # sequence costs more bits than Huffman-coding its bytes, so a
    # literals-only block can beat the sequence-coded one.  The cheap
    # exact-size estimate gates the second whole-block Huffman pass:
    # the common LZ-compressible case (sequence body a tiny fraction
    # of the block) never pays for it.
    plan = _huf_plan(block)
    est = _huf_estimate(plan, len(block))
    if est is not None and est + 1 < len(body):
        fsec, finfo = _lit_section(block, plan=plan, prev=ts.get("huf"))
        flat = fsec + b"\x00"
        if len(flat) < len(body):
            # literals-only block: no sequences execute; rep and the
            # sequence tables stay untouched, but a shipped type-2
            # literal tree still becomes the frame tree
            if len(flat) < len(block):
                _commit_lit(tstate, finfo)
                return flat
            return None
    if len(body) < len(block):
        if rep is not None:
            rep[:] = nrep               # commit: this body ships
        if tstate is not None:
            # the decoder's per-slot last-used tables (Repeat_Mode
            # reuses them next block) — only sequence-coded bodies
            # touch them
            tstate["ll"] = (ll_norm, ll_log)
            tstate["of"] = (of_norm, of_log)
            tstate["ml"] = (ml_norm, ml_log)
        _commit_lit(tstate, linfo)
        return body
    return None


class _BitReader:
    """Python twin of zstd.cpp's BackBits: the stream as one little-
    endian bit sequence, read from the top; the last byte's highest
    set bit is the sentinel.  Reads index the byte buffer directly —
    shifting one whole-stream int per read is quadratic on long
    streams."""

    def __init__(self, data: bytes):
        if not data or data[-1] == 0:
            raise ValueError("zstd: bad bitstream end")
        self.data = data
        self.pos = (len(data) - 1) * 8 + data[-1].bit_length() - 1

    def read(self, width: int) -> int:
        self.pos -= width
        if self.pos < 0:
            raise ValueError("zstd: bitstream over-read")
        lo = self.pos
        byte0 = lo >> 3
        span = (width + (lo & 7) + 7) >> 3
        acc = int.from_bytes(self.data[byte0:byte0 + span], "little")
        return (acc >> (lo & 7)) & ((1 << width) - 1)

    def peek(self, width: int) -> int:
        """Bits [pos-width, pos) zero-padded below position 0 —
        Huffman decoding peeks maxBits even when fewer remain; only
        CONSUMING past the start is an error (zstd.cpp BackBits)."""
        lo = self.pos - width
        start = max(0, lo)
        byte0 = start >> 3
        span = ((self.pos + 7) >> 3) - byte0
        acc = int.from_bytes(self.data[byte0:byte0 + span], "little")
        acc >>= start - (byte0 << 3)
        if lo < 0:
            acc <<= -lo
        return acc & ((1 << width) - 1)

    def consume(self, width: int) -> None:
        self.pos -= width
        if self.pos < 0:
            raise ValueError("zstd: bitstream over-read")

    def done(self) -> bool:
        return self.pos == 0


def _huf_parse_py(body: bytes):
    """Huffman tree description -> (symbol, nbBits, log, header bytes
    consumed); mirrors zstd.cpp huf_parse/huf_build.  Handles BOTH
    forms our encoder emits: direct 4-bit weights (hbyte >= 128) and
    FSE-compressed weights (hbyte < 128)."""
    if not body:
        raise ValueError("zstd: empty tree description")
    hbyte = body[0]
    if hbyte < 128:                     # FSE-compressed weights
        if hbyte == 0 or 1 + hbyte > len(body):
            raise ValueError("zstd: truncated tree description")
        weights = _huf_fse_weights_decode(body[:1 + hbyte])
        if weights is None:
            raise ValueError("zstd: bad FSE weight stream")
        used = 1 + hbyte
    else:                               # direct 4-bit weights
        nw = hbyte - 127
        used = 1 + (nw + 1) // 2
        if used > len(body):
            raise ValueError("zstd: truncated tree description")
        weights = []
        for i in range(nw):
            b = body[1 + (i >> 1)]
            weights.append(b & 0x0F if i & 1 else b >> 4)
    total = sum(1 << (w - 1) for w in weights if w)
    if total == 0:
        raise ValueError("zstd: empty Huffman weights")
    maxbits = total.bit_length()
    rest = (1 << maxbits) - total
    if maxbits > 12 or rest == 0 or rest & (rest - 1):
        raise ValueError("zstd: bad Huffman weights")
    weights.append(rest.bit_length())
    size = 1 << maxbits
    sym = bytearray(size)
    nb = bytearray(size)
    pos = 0
    for w in range(1, maxbits + 1):
        for s, ws in enumerate(weights):
            if ws != w:
                continue
            cnt = 1 << (w - 1)
            nbv = maxbits + 1 - w
            for _ in range(cnt):
                sym[pos] = s
                nb[pos] = nbv
                pos += 1
    if pos != size:
        raise ValueError("zstd: bad Huffman weights")
    return sym, nb, maxbits, used


def _huf_stream_py(sym, nb, log, data: bytes, count: int) -> bytes:
    bits = _BitReader(data)
    out = bytearray()
    for _ in range(count):
        idx = bits.peek(log)
        out.append(sym[idx])
        bits.consume(nb[idx])
    if not bits.done():
        raise ValueError("zstd: Huffman stream not consumed")
    return bytes(out)


def _py_block_decode(body: bytes, rep=None, fstate=None,
                     window=None, wbase: int = 0) -> bytes:
    """Toolchain-less block decode — by round 5 this covers the FULL
    non-dictionary format (Huffman literals with direct or FSE
    weights, treeless reuse, all four sequence-table modes, repeat
    offsets, cross-block matches), so foreign (libzstd/Java-producer)
    frames decode without the native module too.  ``fstate`` carries
    the frame-persistent Huffman table and last-used sequence tables;
    ``rep`` the repeat-offset history; ``window`` is the CALLER's
    whole-frame output buffer with the frame starting at ``wbase`` —
    indexed in place for cross-block matches, never copied (a
    per-block snapshot would make large-frame decode quadratic)."""
    if rep is None:
        rep = [1, 4, 8]                 # standalone-block decode
    if fstate is None:
        fstate = {}
    # prior bytes this frame = window[wbase:len(window)]; len(window)
    # is the absolute position where this block's output begins
    prior_len = len(window) - wbase if window is not None else 0
    if not body:
        raise ValueError("zstd: empty block")
    ltype = body[0] & 3
    sf = (body[0] >> 2) & 3
    if ltype >= 2:                      # Huffman-compressed / treeless
        if sf <= 1:
            if len(body) < 3:
                raise ValueError("zstd: truncated literals header")
            regen = (body[0] >> 4) | ((body[1] & 0x3F) << 4)
            comp = (body[1] >> 6) | (body[2] << 2)
            off = 3
        elif sf == 2:
            if len(body) < 4:
                raise ValueError("zstd: truncated literals header")
            regen = (body[0] >> 4) | (body[1] << 4) | ((body[2] & 3) << 12)
            comp = (body[2] >> 2) | (body[3] << 6)
            off = 4
        else:
            if len(body) < 5:
                raise ValueError("zstd: truncated literals header")
            regen = ((body[0] >> 4) | (body[1] << 4)
                     | ((body[2] & 0x3F) << 12))
            comp = (body[2] >> 6) | (body[3] << 2) | (body[4] << 10)
            off = 5
        if regen > _BLOCK_MAX or off + comp > len(body):
            raise ValueError("zstd: bad literals section")
        area = body[off:off + comp]
        if ltype == 2:
            sym, nb, log, used = _huf_parse_py(area)
            area = area[used:]
            fstate["huf"] = (sym, nb, log)
        else:                           # treeless: reuse the frame's
            if "huf" not in fstate:     # last Huffman table
                raise ValueError("zstd: treeless literals before any "
                                 "tree")
            sym, nb, log = fstate["huf"]
        if sf == 0:                     # single stream
            lits = _huf_stream_py(sym, nb, log, area, regen)
        else:                           # 4 streams, 6-byte jump table
            if len(area) < 6:
                raise ValueError("zstd: truncated jump table")
            s1 = area[0] | (area[1] << 8)
            s2 = area[2] | (area[3] << 8)
            s3 = area[4] | (area[5] << 8)
            s4 = len(area) - 6 - s1 - s2 - s3
            if s4 <= 0:
                raise ValueError("zstd: bad jump table")
            per = (regen + 3) // 4
            last = regen - 3 * per
            if last < 0:
                raise ValueError("zstd: bad stream split")
            q = area[6:]
            lits = (_huf_stream_py(sym, nb, log, q[:s1], per)
                    + _huf_stream_py(sym, nb, log, q[s1:s1 + s2], per)
                    + _huf_stream_py(sym, nb, log,
                                     q[s1 + s2:s1 + s2 + s3], per)
                    + _huf_stream_py(sym, nb, log, q[s1 + s2 + s3:], last))
        off += comp
    else:
        if sf in (0, 2):
            regen, off = body[0] >> 3, 1
        elif sf == 1:
            regen, off = (body[0] >> 4) | (body[1] << 4), 2
        else:
            regen = (body[0] >> 4) | (body[1] << 4) | (body[2] << 12)
            off = 3
        if regen > _BLOCK_MAX:
            raise ValueError("zstd: literals exceed block maximum")
        if ltype == 0:
            lits = body[off:off + regen]
            off += regen
        else:                           # RLE
            lits = body[off:off + 1] * regen
            off += 1
    if len(lits) != regen:
        raise ValueError("zstd: truncated literals")
    b0 = body[off]
    off += 1
    if b0 == 0:
        if off != len(body):
            raise ValueError("zstd: trailing bytes after literals")
        return lits
    if b0 < 128:
        nseq = b0
    elif b0 < 255:
        nseq = ((b0 - 128) << 8) + body[off]
        off += 1
    else:
        nseq = (body[off] | (body[off + 1] << 8)) + 0x7F00
        off += 2
    modes = body[off]
    off += 1

    def seq_table(slot, mode, predef_norm, predef_log, maxlog, maxsym):
        nonlocal off
        if mode == 0:
            t = (*_fse_decode_table(predef_norm, predef_log)[:3],
                 predef_log)
        elif mode == 1:                 # RLE: log-0 single-entry table
            sym = body[off]
            off += 1
            if sym > maxsym:
                raise ValueError("zstd: RLE symbol out of range")
            t = (bytes([sym]), bytes([0]), [0], 0)
        elif mode == 2:                 # FSE-described
            (sym, nb, new, _), log, used = _fse_parse_py(
                body[off:], maxlog, maxsym)
            off += used
            t = (sym, nb, new, log)
        else:                           # repeat: the frame's last-used
            t = fstate.get(slot)        # table of ANY kind (libzstd)
            if t is None:
                raise ValueError("zstd: repeat mode before any table")
        fstate[slot] = t
        return t

    ll_sym, ll_nb, ll_new, ll_log = seq_table(
        "ll", (modes >> 6) & 3, _LL_NORM, 6, 9, 35)
    of_sym, of_nb, of_new, of_log = seq_table(
        "of", (modes >> 4) & 3, _OF_NORM, 5, 8, 31)
    ml_sym, ml_nb, ml_new, ml_log = seq_table(
        "ml", (modes >> 2) & 3, _ML_NORM, 6, 9, 52)
    bits = _BitReader(body[off:])
    ll_s = bits.read(ll_log)
    of_s = bits.read(of_log)
    ml_s = bits.read(ml_log)
    out = bytearray()
    lit_pos = 0
    for i in range(nseq):
        oc = of_sym[of_s]
        ofv = (1 << oc) + (bits.read(oc) if oc else 0)
        mc = ml_sym[ml_s]
        mlen = _ML_BASE[mc] + bits.read(_ML_BITS[mc])
        lc = ll_sym[ll_s]
        llen = _LL_BASE[lc] + bits.read(_LL_BITS[lc])
        if ofv > 3:
            offset = ofv - 3
            rep[:] = [offset, rep[0], rep[1]]
        else:                           # RFC 8878 §3.1.1.5 resolution
            idx = ofv - 1 + (1 if llen == 0 else 0)
            if idx == 0:
                offset = rep[0]
            elif idx == 1:
                offset = rep[1]
                rep[:] = [offset, rep[0], rep[2]]
            elif idx == 2:
                offset = rep[2]
                rep[:] = [offset, rep[0], rep[1]]
            else:                       # idx 3: rep[0] - 1
                if rep[0] <= 1:
                    raise ValueError("zstd: bad repeat offset")
                offset = rep[0] - 1
                rep[:] = [offset, rep[0], rep[1]]
            if offset == 0:
                raise ValueError("zstd: zero offset")
        if i + 1 < nseq:
            ll_s = ll_new[ll_s] + bits.read(ll_nb[ll_s])
            ml_s = ml_new[ml_s] + bits.read(ml_nb[ml_s])
            of_s = of_new[of_s] + bits.read(of_nb[of_s])
        if lit_pos + llen > len(lits):
            raise ValueError("zstd: literals exhausted")
        out += lits[lit_pos:lit_pos + llen]
        lit_pos += llen
        if len(out) + mlen > _BLOCK_MAX:
            # spec Block_Maximum_Size, enforced INSIDE the loop: a
            # crafted sequence stream regenerates ~128 KB per ~3 input
            # bytes, so a post-hoc cap would still be a memory/CPU bomb
            raise ValueError("zstd: block exceeds maximum size")
        src = len(out) - offset
        if src >= 0:
            if offset >= mlen:          # non-overlapping: one slice
                out += out[src:src + mlen]
            else:
                for _ in range(mlen):
                    out.append(out[-offset])
        else:                           # match reaches into PRIOR
            if -src > prior_len:        # blocks of this frame
                raise ValueError("zstd: match offset beyond window")
            take = min(mlen, -src)      # the prior-resident part:
            start = len(window) + src   # absolute index in the frame
            out += window[start:start + take]
            rest = mlen - take
            if rest:                    # tail continues at in-block
                if offset >= rest:      # position 0 (src + take == 0)
                    out += out[0:rest]
                else:
                    for _ in range(rest):
                        out.append(out[-offset])
    if not bits.done():
        raise ValueError("zstd: sequence bitstream not consumed")
    out += lits[lit_pos:]
    return bytes(out)


def compress_frame(data: bytes) -> bytes:
    """One zstd frame: single-segment, declared content size; blocks
    are compressed (greedy LZ77 + predefined-FSE sequences +
    raw/RLE/Huffman literal sections — decodable by every zstd
    implementation) with raw-block fallback per 128 KB block when
    compression doesn't pay."""
    n = len(data)
    if n < 256:
        fhd, fcs = 0x20, struct.pack("<B", n)
    elif n < 65536 + 256:
        fhd, fcs = 0x60, struct.pack("<H", n - 256)
    elif n < 1 << 32:
        fhd, fcs = 0xA0, struct.pack("<I", n)
    else:
        fhd, fcs = 0xE0, struct.pack("<Q", n)
    out: List[bytes] = [struct.pack("<I", _MAGIC), bytes([fhd]), fcs]
    if n == 0:
        out.append(b"\x01\x00\x00")              # last empty raw block
        return b"".join(out)
    rep = [1, 4, 8]                     # frame repeat-offset history
    table: dict = {}                    # frame LZ77 table: cross-block
    tstate: dict = {}                   # decoder's last-used seq tables
    for i in range(0, n, _BLOCK_MAX):   # matches up to _LZ_WINDOW back
        blk = data[i:i + _BLOCK_MAX]
        last = 1 if i + _BLOCK_MAX >= n else 0
        if blk.count(blk[0]) == len(blk):
            # whole block one repeated byte: RLE block type (4 bytes
            # total).  Executes no sequences and parses no tables, so
            # rep/tstate stay untouched — but the LZ table must still
            # index these positions or a later block can't match into
            # this run
            _find_sequences(data, i, i + len(blk), table)
            bh = (len(blk) << 3) | 0x02 | last
            out.append(struct.pack("<I", bh)[:3])
            out.append(blk[:1])
            continue
        body = _compress_block(data, i, i + len(blk), rep, table,
                               tstate)
        if body is None:
            bh = (len(blk) << 3) | last          # type 0 = raw
            out.append(struct.pack("<I", bh)[:3])
            out.append(blk)
        else:
            bh = (len(body) << 3) | 0x04 | last  # type 2 = compressed
            out.append(struct.pack("<I", bh)[:3])
            out.append(body)
    return b"".join(out)
