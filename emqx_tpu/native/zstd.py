"""Zstandard: ctypes front for zstd.cpp (RFC 8878 decoder) plus a
store-mode frame writer, wired as Kafka record-batch codec 4
(SURVEY.md §2.4 — the zstd-erlang/NIF analog).

Posture mirrors the snappy/lz4 modules, with one honest asymmetry:

* **decode** is the full format (Huffman literals, FSE sequences,
  repeat offsets, checksums) in ``zstd.cpp`` — the Kafka FETCH side,
  where the broker must accept whatever a Java producer emitted;
* **encode** emits store-mode frames (raw blocks, single-segment,
  declared content size) from pure Python — valid zstd that ANY
  consumer decodes, at ratio 1.0.  Hand-rolling the FSE/Huffman
  *encoder* is not worth its surface for a producer option the
  operator can simply set to ``snappy``/``lz4``/``gzip`` for real
  ratio; the seam is ``compress_frame``.

Interop against system libzstd (both directions) is proven in
``tests/test_zstd.py``.  Without a toolchain ``available()`` is False
and the Kafka fetch path keeps its previous skip-with-offset-advance
behavior for zstd batches.
"""

from __future__ import annotations

import ctypes
import struct
from typing import List

from .build import load_library

__all__ = ["available", "compress_frame", "decompress_frame"]

_MAGIC = 0xFD2FB528
_BLOCK_MAX = 1 << 17            # spec Block_Maximum_Size ceiling
_MAX_OUTPUT = 256 << 20         # same hostile-input cap as lz4/snappy

_lib = None
_loaded = False


def _load():
    global _lib, _loaded
    if not _loaded:
        _loaded = True
        lib = load_library("zstd")
        if lib is not None:
            lib.zstd_decompress.restype = ctypes.c_int64
            lib.zstd_decompress.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char), ctypes.c_int64]
            lib.zstd_content_size.restype = ctypes.c_int64
            lib.zstd_content_size.argtypes = [
                ctypes.c_char_p, ctypes.c_int64]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def decompress_frame(data: bytes) -> bytes:
    """Decode a (possibly multi-)frame zstd stream.  Full decode needs
    the native decoder; without a toolchain, a pure-Python fallback
    still decodes STORE-MODE frames (raw/RLE blocks — everything
    ``compress_frame`` emits), so a bridge's own production always
    round-trips.  Raises RuntimeError for entropy-coded frames when no
    native decoder exists (caller skips the batch), ValueError on
    corrupt/unsupported input."""
    lib = _load()
    if lib is None:
        return _py_store_decompress(data)
    hint = lib.zstd_content_size(data, len(data))
    if hint >= 0:
        cap = min(_MAX_OUTPUT, hint + _BLOCK_MAX)
    else:
        cap = min(_MAX_OUTPUT, max(1 << 20, len(data) * 8))
    while True:
        dst = ctypes.create_string_buffer(max(1, cap))
        n = lib.zstd_decompress(data, len(data), dst, cap)
        if n >= 0:
            return dst.raw[:n]
        if n == -2 and cap < _MAX_OUTPUT:        # grow and retry
            cap = min(_MAX_OUTPUT, cap * 4)
            continue
        if n == -3:
            raise ValueError("zstd: dictionary frames unsupported")
        raise ValueError("zstd: corrupt frame")


def _py_store_decompress(data: bytes) -> bytes:
    """Toolchain-less fallback: decode frames whose blocks are all
    raw/RLE (store mode).  A compressed block means the frame needs
    the native decoder -> RuntimeError, which the Kafka fetch path
    maps to skip-with-offset-advance.  Content checksums are NOT
    verified here (no xxh64 without the native module); frame sizes
    still are."""
    try:
        return _py_store_walk(data)
    except IndexError:
        # short reads past the end must surface as the same corrupt-
        # input error class the native path raises (the Kafka fetch
        # loop classifies on it)
        raise ValueError("zstd: truncated frame")


def _py_store_walk(data: bytes) -> bytes:
    out = bytearray()
    pos = 0
    n = len(data)
    while pos < n:
        if pos + 4 > n:
            raise ValueError("zstd: truncated magic")
        magic = int.from_bytes(data[pos:pos + 4], "little")
        if (magic & 0xFFFFFFF0) == 0x184D2A50:       # skippable frame
            if pos + 8 > n:
                raise ValueError("zstd: truncated skippable frame")
            pos += 8 + int.from_bytes(data[pos + 4:pos + 8], "little")
            if pos > n:
                raise ValueError("zstd: truncated skippable frame")
            continue
        if magic != _MAGIC:
            raise ValueError("zstd: bad magic")
        pos += 4
        if pos >= n:
            raise ValueError("zstd: truncated frame header")
        fhd = data[pos]
        pos += 1
        if fhd & 0x08:
            raise ValueError("zstd: reserved FHD bit")
        single = (fhd >> 5) & 1
        if not single:
            pos += 1                                 # window descriptor
        dict_bytes = (0, 1, 2, 4)[fhd & 3]
        if dict_bytes and any(data[pos:pos + dict_bytes]):
            raise ValueError("zstd: dictionary frames unsupported")
        pos += dict_bytes
        fcs_flag = fhd >> 6
        fcs_bytes = (1 if single else 0, 2, 4, 8)[fcs_flag]
        fcs = int.from_bytes(data[pos:pos + fcs_bytes], "little") \
            + (256 if fcs_bytes == 2 else 0) if fcs_bytes else None
        pos += fcs_bytes
        frame_base = len(out)
        while True:
            if pos + 3 > n:
                raise ValueError("zstd: truncated block header")
            bh = int.from_bytes(data[pos:pos + 3], "little")
            pos += 3
            last, btype, bsize = bh & 1, (bh >> 1) & 3, bh >> 3
            if btype == 0:                           # raw
                if pos + bsize > n:
                    raise ValueError("zstd: truncated raw block")
                out += data[pos:pos + bsize]
                pos += bsize
            elif btype == 1:                         # RLE
                if pos + 1 > n or bsize > _BLOCK_MAX:
                    raise ValueError("zstd: bad RLE block")
                out += data[pos:pos + 1] * bsize
                pos += 1
            else:
                raise RuntimeError(
                    "zstd: compressed frame needs the native decoder")
            if len(out) > _MAX_OUTPUT:
                raise ValueError("zstd: output exceeds cap")
            if last:
                break
        if fhd & 0x04:                               # checksum present
            pos += 4                                 # not verified here
        if fcs is not None and len(out) - frame_base != fcs:
            raise ValueError("zstd: content size mismatch")
    return bytes(out)


def compress_frame(data: bytes) -> bytes:
    """One store-mode zstd frame: single-segment, declared content
    size, raw blocks (ratio 1.0 — see module docstring)."""
    n = len(data)
    if n < 256:
        fhd, fcs = 0x20, struct.pack("<B", n)
    elif n < 65536 + 256:
        fhd, fcs = 0x60, struct.pack("<H", n - 256)
    elif n < 1 << 32:
        fhd, fcs = 0xA0, struct.pack("<I", n)
    else:
        fhd, fcs = 0xE0, struct.pack("<Q", n)
    out: List[bytes] = [struct.pack("<I", _MAGIC), bytes([fhd]), fcs]
    if n == 0:
        out.append(b"\x01\x00\x00")              # last empty raw block
        return b"".join(out)
    for i in range(0, n, _BLOCK_MAX):
        blk = data[i:i + _BLOCK_MAX]
        last = 1 if i + _BLOCK_MAX >= n else 0
        bh = (len(blk) << 3) | last              # type 0 = raw
        out.append(struct.pack("<I", bh)[:3])
        out.append(blk)
    return b"".join(out)
