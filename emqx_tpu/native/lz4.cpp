// LZ4 block codec + xxHash32 — the lz4-erlang/NIF analog for the Kafka
// bridge's codec-3 record batches (SURVEY.md §2.4).
//
// Independent implementation of the PUBLIC LZ4 block format
// (token = literal-length nibble | match-length nibble, 255-extension
// bytes, 2-byte little-endian match offsets, min-match 4) and of
// xxHash32 (the frame header/content checksum).  The LZ4 FRAME layout
// (magic 0x184D2204, FLG/BD/HC, block stream, endmark) is byte
// plumbing and lives in lz4.py; only the block codec and the hash are
// hot.
//
// Exported (extern "C", caller-allocated buffers):
//   lz4_max_compressed_length(n)          -> worst-case dst size
//   lz4_compress(src,n,dst,cap)           -> compressed size, -1 on cap
//   lz4_decompress(src,n,dst,cap)         -> decoded size (<=cap), -1
//                                            on corrupt/overflow; exact-
//                                            size checks live in Python
//                                            (the frame format omits
//                                            per-block sizes)
//   lz4_xxh32(buf,n,seed)                 -> uint32
#include <cstdint>
#include <cstring>

namespace {

inline uint32_t load32(const uint8_t* p) {
    uint32_t v; std::memcpy(&v, p, 4); return v;
}

constexpr int kHashBits = 14;
constexpr size_t kTabSize = size_t(1) << kHashBits;

inline uint32_t hash4(uint32_t v) {
    return (v * 2654435761u) >> (32 - kHashBits);
}

inline uint8_t* put_len(uint8_t* op, size_t len) {   // 255-extensions
    while (len >= 255) { *op++ = 255; len -= 255; }
    *op++ = uint8_t(len);
    return op;
}

}  // namespace

extern "C" {

int64_t lz4_max_compressed_length(int64_t n) {
    return n + n / 255 + 16;
}

int64_t lz4_compress(const uint8_t* src, int64_t srclen,
                     uint8_t* dst, int64_t dstcap) {
    if (srclen < 0 || dstcap < lz4_max_compressed_length(srclen))
        return -1;
    const size_t n = size_t(srclen);
    uint8_t* op = dst;
    size_t ip = 0, anchor = 0;
    // format end rules: the last 5 bytes are literals; a match may not
    // start within the last 12 bytes
    if (n > 12) {
        static thread_local uint32_t* table = nullptr;
        if (!table) table = new uint32_t[kTabSize];
        std::memset(table, 0, kTabSize * sizeof(uint32_t));
        const size_t mflimit = n - 12;
        ip = 1;
        while (ip <= mflimit) {
            uint32_t h = hash4(load32(src + ip));
            size_t cand = table[h];          // stores pos+1 (0 = empty)
            table[h] = uint32_t(ip + 1);
            if (!cand || ip + 1 - cand > 65535 ||
                load32(src + cand - 1) != load32(src + ip)) {
                ++ip;
                continue;
            }
            size_t ref = cand - 1;
            // extend match forward (bounded by the 5-byte end rule)
            size_t len = 4;
            const size_t matchlimit = n - 5;
            while (ip + len < matchlimit && src[ref + len] == src[ip + len])
                ++len;
            // emit [token][lit ext][literals][offset][match ext]
            size_t lit = ip - anchor;
            uint8_t* token = op++;
            if (lit >= 15) {
                *token = 0xF0;
                op = put_len(op, lit - 15);
            } else {
                *token = uint8_t(lit << 4);
            }
            std::memcpy(op, src + anchor, lit);
            op += lit;
            uint16_t off = uint16_t(ip - ref);
            *op++ = uint8_t(off);
            *op++ = uint8_t(off >> 8);
            size_t ml = len - 4;             // stored match len
            if (ml >= 15) {
                *token |= 0x0F;
                op = put_len(op, ml - 15);
            } else {
                *token |= uint8_t(ml);
            }
            ip += len;
            anchor = ip;
        }
    }
    // trailing literals
    size_t lit = n - anchor;
    uint8_t* token = op++;
    if (lit >= 15) {
        *token = 0xF0;
        op = put_len(op, lit - 15);
    } else {
        *token = uint8_t(lit << 4);
    }
    std::memcpy(op, src + anchor, lit);
    op += lit;
    return op - dst;
}

// `start` bytes of already-decoded history occupy dst[0:start] (the
// LZ4 frame format's block-LINKED mode lets matches reach back into
// the previous blocks); output begins at dst[start], return value is
// the number of NEW bytes.  start=0 == plain block decode.
int64_t lz4_decompress_hist(const uint8_t* src, int64_t srclen,
                            uint8_t* dst, int64_t cap, int64_t start) {
    if (srclen < 0 || cap < 0 || start < 0 || start > cap) return -1;
    const size_t n = size_t(srclen), w = size_t(cap);
    size_t ip = 0, op = size_t(start);
    while (ip < n) {
        uint8_t token = src[ip++];
        size_t lit = token >> 4;
        if (lit == 15) {
            uint8_t b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                lit += b;
            } while (b == 255);
        }
        if (ip + lit > n || op + lit > w) return -1;
        std::memcpy(dst + op, src + ip, lit);
        ip += lit;
        op += lit;
        if (ip >= n) break;                  // last sequence: literals only
        if (ip + 2 > n) return -1;
        size_t off = src[ip] | (size_t(src[ip + 1]) << 8);
        ip += 2;
        if (off == 0 || off > op) return -1;
        size_t ml = (token & 0x0F);
        if (ml == 15) {
            uint8_t b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                ml += b;
            } while (b == 255);
        }
        ml += 4;
        if (op + ml > w) return -1;
        if (off >= ml) {
            std::memmove(dst + op, dst + op - off, ml);
            op += ml;
        } else {
            for (size_t i = 0; i < ml; ++i, ++op)
                dst[op] = dst[op - off];
        }
    }
    return int64_t(op) - start;  // caller checks exactness if it applies
}

int64_t lz4_decompress(const uint8_t* src, int64_t srclen,
                       uint8_t* dst, int64_t cap) {
    return lz4_decompress_hist(src, srclen, dst, cap, 0);
}

// ---- xxHash32 -------------------------------------------------------------

uint32_t lz4_xxh32(const uint8_t* p, int64_t len, uint32_t seed) {
    constexpr uint32_t P1 = 2654435761u, P2 = 2246822519u,
                       P3 = 3266489917u, P4 = 668265263u, P5 = 374761393u;
    auto rotl = [](uint32_t x, int r) {
        return (x << r) | (x >> (32 - r));
    };
    const uint8_t* end = p + len;
    uint32_t h;
    if (len >= 16) {
        uint32_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed,
                 v4 = seed - P1;
        const uint8_t* limit = end - 16;
        do {
            v1 = rotl(v1 + load32(p) * P2, 13) * P1; p += 4;
            v2 = rotl(v2 + load32(p) * P2, 13) * P1; p += 4;
            v3 = rotl(v3 + load32(p) * P2, 13) * P1; p += 4;
            v4 = rotl(v4 + load32(p) * P2, 13) * P1; p += 4;
        } while (p <= limit);
        h = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    } else {
        h = seed + P5;
    }
    h += uint32_t(len);
    while (p + 4 <= end) {
        h = rotl(h + load32(p) * P3, 17) * P4;
        p += 4;
    }
    while (p < end) {
        h = rotl(h + (*p++) * P5, 11) * P1;
    }
    h ^= h >> 15; h *= P2;
    h ^= h >> 13; h *= P3;
    h ^= h >> 16;
    return h;
}

}  // extern "C"
