"""ctypes wrapper for the native incremental NFA (``nfa.cpp``).

``NativeNfa`` mirrors the mutation/drain surface of
:class:`emqx_tpu.ops.incremental.IncrementalNfa` (the semantics oracle;
property-tested equivalent in tests/test_native_nfa.py) at 10M-filter
scale: bulk build in seconds, O(filter) add/remove, dirty-row delta
drain for the device twin, host-side authoritative match for fail-open.

Falls back to ``None`` when the toolchain can't build the .so — callers
use the Python IncrementalNfa below ~1M filters.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .build import load_library

# int32s per cuckoo bucket row — MUST match nfa.cpp's BUCKET_SLOTS*4
# and ops/compiler.py's BUCKET_SLOTS; drift would size the fill buffers
# wrong and let the C side write past them (verified at construction,
# see NativeNfa.__init__)
_ROW = 8


def _check_row() -> None:
    from ..ops.compiler import BUCKET_SLOTS

    if _ROW != 4 * BUCKET_SLOTS:
        raise RuntimeError(
            f"native/nfa.py _ROW={_ROW} out of sync with "
            f"compiler.BUCKET_SLOTS={BUCKET_SLOTS} (expected "
            f"{4 * BUCKET_SLOTS}); update BOTH plus nfa.cpp")

__all__ = ["NativeNfa", "available"]

_lib = None
_checked = False


def _load():
    global _lib, _checked
    if _checked:
        return _lib
    _checked = True
    lib = load_library("nfa")
    if lib is None:
        return None
    lib.nfa_new.restype = ctypes.c_void_p
    lib.nfa_new.argtypes = [ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                            ctypes.c_uint64]
    lib.nfa_free.argtypes = [ctypes.c_void_p]
    lib.nfa_add.restype = ctypes.c_int32
    lib.nfa_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
    lib.nfa_remove.restype = ctypes.c_int32
    lib.nfa_remove.argtypes = lib.nfa_add.argtypes
    lib.nfa_bulk_add.restype = ctypes.c_int64
    lib.nfa_bulk_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int64]
    lib.nfa_intern.restype = ctypes.c_int32
    lib.nfa_intern.argtypes = lib.nfa_add.argtypes
    lib.nfa_bulk_intern.restype = ctypes.c_int64
    lib.nfa_bulk_intern.argtypes = lib.nfa_bulk_add.argtypes
    lib.nfa_grow_edges_to.restype = ctypes.c_int64
    lib.nfa_grow_edges_to.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.nfa_aid_of.restype = ctypes.c_int32
    lib.nfa_aid_of.argtypes = lib.nfa_add.argtypes
    lib.nfa_alloc_alias.restype = ctypes.c_int32
    lib.nfa_alloc_alias.argtypes = lib.nfa_add.argtypes
    lib.nfa_free_alias.restype = ctypes.c_int32
    lib.nfa_free_alias.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.nfa_match_topic.restype = ctypes.c_int32
    lib.nfa_match_topic.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.nfa_sizes.argtypes = [ctypes.c_void_p,
                              ctypes.POINTER(ctypes.c_int64)]
    lib.nfa_fill_tables.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.nfa_vocab_fill.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.nfa_accept_get.restype = ctypes.c_int32
    lib.nfa_accept_get.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                   ctypes.c_char_p, ctypes.c_int32]
    lib.nfa_set_device_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.nfa_mark_resized.argtypes = [ctypes.c_void_p]
    lib.nfa_delta_sizes.argtypes = lib.nfa_sizes.argtypes
    lib.nfa_delta_fill.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class _AcceptView:
    """aid→filter sequence view over the native accepts vector."""

    __slots__ = ("_nfa",)

    def __init__(self, nfa: "NativeNfa") -> None:
        self._nfa = nfa

    def __getitem__(self, aid: int) -> Optional[str]:
        if aid < 0 or aid >= len(self):
            # a real IndexError: sequence semantics (including the
            # legacy iteration protocol) must terminate
            raise IndexError(aid)
        return self._nfa.accept_get(aid)

    def __len__(self) -> int:
        return int(self._nfa._sizes()[4])


class NativeNfa:
    """Handle-owning wrapper; see module docstring."""

    def __init__(self, depth: int = 8, state_bucket: int = 1024,
                 edge_bucket: int = 64, seed: int = 0xE709) -> None:
        _check_row()
        lib = _load()
        if lib is None:
            raise RuntimeError("native nfa library unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.nfa_new(depth, state_bucket,
                                              edge_bucket, seed))
        self.depth = depth
        # live vocab view: same dict OBJECT updated in place (append-only,
        # id order) so encode_batch's per-table encoder cache and its
        # push-incremental interning both work unchanged
        self._vocab: Dict[str, int] = {}

    def close(self) -> None:
        if self._h:
            self._lib.nfa_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    # -- mutation ----------------------------------------------------------

    def add(self, flt: str) -> bool:
        b = flt.encode()
        r = self._lib.nfa_add(self._h, b, len(b))
        if r < 0:
            raise ValueError(
                f"filter {flt!r} invalid (deeper than table depth, or "
                "'#' not in final position)"
            )
        return bool(r)

    def remove(self, flt: str) -> bool:
        b = flt.encode()
        return bool(self._lib.nfa_remove(self._h, b, len(b)))

    def bulk_add(self, filters: Sequence[str]) -> int:
        """Add many filters in one native call (the 10M-scale build path).
        Invalid lines ('#' not final / too deep) are skipped, not
        truncate-inserted; the return counts filters actually added.
        Filters containing '\\n' (legal in MQTT) can't ride the
        newline-framed bulk path and fall back to individual adds."""
        plain = [f for f in filters if "\n" not in f]
        odd = [f for f in filters if "\n" in f]
        blob = "\n".join(plain).encode()
        n = int(self._lib.nfa_bulk_add(self._h, blob, len(blob)))
        for f in odd:
            try:
                n += 1 if self.add(f) else 0
            except ValueError:
                pass
        # warm probe: the first few mutations after a large bulk absorb a
        # one-off allocator consolidation stall (measured ~200 ms at 2M
        # filters); pay it here, not on a live subscribe
        for i in range(4):
            probe = f"\x01warm/{i}".encode()
            self._lib.nfa_add(self._h, probe, len(probe))
            self._lib.nfa_remove(self._h, probe, len(probe))
        if n > 100_000:
            # absorb the one-off post-bulk allocator stall (~200 ms of
            # glibc consolidation measured at 2M filters) here rather
            # than on the first live delta: exercise the flush path AND
            # a few heap allocations of delta-buffer size, then re-flag
            # resized so any attached consumer still performs the full
            # upload the bulk requires
            self.flush()
            for _ in range(4):
                np.empty((4096, _ROW), np.int32)
                np.empty((4096, 4), np.int32)
            self._lib.nfa_mark_resized(self._h)
        return n

    def intern(self, word: str) -> int:
        """Intern ``word`` into the native vocab WITHOUT adding a
        filter; returns its id.  Ids assign append-only (size+1), so
        replaying one word sequence into several tables keeps their
        vocabs identical — the multichip shard subtables share an
        encode vocab this way."""
        b = word.encode()
        wid = int(self._lib.nfa_intern(self._h, b, len(b)))
        # keep the live dict view in lockstep (append-only invariant)
        if word not in self._vocab:
            self._vocab[word] = wid
        return wid

    def bulk_intern(self, words: Sequence[str]) -> int:
        """Intern many words in id order with one native call (the
        segment-restore path; NUL framing — words may contain '\\n',
        never NUL)."""
        blob = "\x00".join(words).encode()
        n = int(self._lib.nfa_bulk_intern(self._h, blob, len(blob)))
        for w in words:
            if w not in self._vocab:
                self._vocab[w] = len(self._vocab) + 1
        return n

    def grow_edges_to(self, hb_target: int) -> int:
        """Grow the cuckoo edge table until Hb >= ``hb_target`` (the
        multichip common-Hb restack: lookups probe modulo the table
        size, so stacked shards must share one real bucket count).
        Marks the table resized — the consumer re-uploads in full."""
        return int(self._lib.nfa_grow_edges_to(self._h, int(hb_target)))

    # -- introspection -----------------------------------------------------

    def _sizes(self) -> np.ndarray:
        out = np.zeros(11, np.int64)
        self._lib.nfa_sizes(self._h, out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)))
        return out

    @property
    def n_filters(self) -> int:
        return int(self._sizes()[5])

    @property
    def n_states(self) -> int:
        return int(self._sizes()[2])

    @property
    def epoch(self) -> int:
        return int(self._sizes()[8])

    @property
    def aid_reuses(self) -> int:
        return int(self._sizes()[10])

    def shape_key(self) -> Tuple[int, int, int]:
        s = self._sizes()
        return (int(s[0]), int(s[1]), self.depth)

    def memory_bytes(self) -> Dict[str, int]:
        """Device-array footprint (the HBM math for BASELINE.md)."""
        s = self._sizes()
        return {
            "node_tab": int(s[0]) * 4 * 4,
            "edge_tab": int(s[1]) * _ROW * 4,
            "n_states": int(s[2]),
            "n_edges": int(s[3]),
        }

    # -- table export ------------------------------------------------------

    def tables(self):
        """Current arrays in kernel order: (node_tab, edge_tab, seeds)."""
        s = self._sizes()
        node_tab = np.empty((int(s[0]), 4), np.int32)
        edge_tab = np.empty((int(s[1]), _ROW), np.int32)
        seeds = np.empty(2, np.int32)
        self._lib.nfa_fill_tables(self._h, _i32p(node_tab), _i32p(edge_tab),
                                  _i32p(seeds))
        return node_tab, edge_tab, seeds

    @property
    def vocab(self) -> Dict[str, int]:
        """Word → id map (id 0 reserved UNKNOWN).  The native vocab is
        append-only; this refreshes the SAME dict in place when it grew."""
        s = self._sizes()
        n = int(s[6])
        if len(self._vocab) != n:
            buf = ctypes.create_string_buffer(int(s[7]) + 1)
            self._lib.nfa_vocab_fill(self._h, buf)
            # NUL-separated: words may legally contain '\n' but never NUL
            words = buf.raw[: max(0, int(s[7]) - 1)].decode().split("\x00")
            for i in range(len(self._vocab), n):
                self._vocab[words[i]] = i + 1
        return self._vocab

    def accept_get(self, aid: int) -> Optional[str]:
        buf = ctypes.create_string_buffer(1024)
        n = self._lib.nfa_accept_get(self._h, aid, buf, 1024)
        return buf.raw[:n].decode() if n >= 0 else None

    def aid_of(self, flt: str) -> int:
        b = flt.encode()
        return int(self._lib.nfa_aid_of(self._h, b, len(b)))

    def alloc_alias(self, flt: str) -> int:
        """Accept id with no trie states (too-deep filters) — same
        contract as IncrementalNfa.alloc_alias."""
        b = flt.encode()
        return int(self._lib.nfa_alloc_alias(self._h, b, len(b)))

    def free_alias(self, aid: int) -> None:
        self._lib.nfa_free_alias(self._h, aid)

    @property
    def accept_filters(self) -> "_AcceptView":
        """Read-only aid→filter view (len/indexing); backed by the
        native accepts vector, so no 10M-string Python list."""
        return _AcceptView(self)

    def match_host(self, topic: str, cap: int = 4096) -> List[int]:
        b = topic.encode()
        out = np.empty(cap, np.int32)
        n = self._lib.nfa_match_topic(self._h, b, len(b), _i32p(out), cap)
        if n > cap:  # extremely wide match: retry with exact size
            out = np.empty(n, np.int32)
            n = self._lib.nfa_match_topic(self._h, b, len(b), _i32p(out), n)
        return out[:n].tolist()

    # -- device delta feed -------------------------------------------------

    def set_device_epoch(self, epoch: int) -> None:
        self._lib.nfa_set_device_epoch(self._h, epoch)
        self._device_epoch = epoch

    # attribute-style twin of IncrementalNfa.device_epoch so DeviceNfa
    # drives either table implementation unchanged
    @property
    def device_epoch(self) -> Optional[int]:
        return getattr(self, "_device_epoch", None)

    @device_epoch.setter
    def device_epoch(self, epoch: Optional[int]) -> None:
        # None = no consumer (-2); -1 = attached, nothing acked yet
        self.set_device_epoch(-2 if epoch is None else int(epoch))

    def flush(self):
        """Drain dirty rows as an ``NfaDelta`` (same contract as the
        Python IncrementalNfa.flush: after a resize the consumer must
        re-upload full tables)."""
        from ..ops.incremental import NfaDelta

        hdr = np.zeros(4, np.int64)
        self._lib.nfa_delta_sizes(self._h, hdr.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)))
        ns, nb, resized, epoch = (int(x) for x in hdr)
        state_idx = np.empty(ns, np.int32)
        state_rows = np.empty((ns, 4), np.int32)
        bucket_idx = np.empty(nb, np.int32)
        bucket_rows = np.empty((nb, _ROW), np.int32)
        self._lib.nfa_delta_fill(self._h, _i32p(state_idx), _i32p(state_rows),
                                 _i32p(bucket_idx), _i32p(bucket_rows))
        return NfaDelta(
            epoch=epoch, resized=bool(resized),
            state_idx=state_idx, state_rows=state_rows,
            bucket_idx=bucket_idx, bucket_rows=bucket_rows,
        )
