"""Load generator — the `emqtt_bench` analog (SURVEY.md §2.3: the
reference's baseline driver, a separate repo driven from CI).

Three scenarios, CLI-compatible in spirit with emqtt_bench:

* ``conn`` — CONNECT storm: N clients at a target connect rate.
* ``sub``  — N subscribers over a topic pattern (``%i`` = client index).
* ``pub``  — N publishers at a per-client message rate / payload size;
  reports throughput + end-to-end latency percentiles when a matching
  ``sub`` group runs in-process.

Programmatic API (used by perf tests): :func:`run_scenario` returns a
stats dict; ``python -m emqx_tpu.bench_client pub -h HOST ...`` prints it.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import struct
import time
from typing import Any, Dict, List, Optional

from .client import Client
from .mqtt import frame as F
from .mqtt import packet as P

__all__ = ["run_scenario", "BenchStats", "LeanSub", "main"]


class BenchStats:
    def __init__(self) -> None:
        self.connected = 0
        self.connect_failures = 0
        self.sent = 0
        self.received = 0
        self.duplicates = 0   # DUP-flagged PUBLISHes seen by subscribers
        self.latencies_us: List[float] = []
        self.t0 = time.perf_counter()

    def summary(self) -> Dict[str, Any]:
        dt = max(time.perf_counter() - self.t0, 1e-9)
        lat = sorted(self.latencies_us)

        def pct(p: float) -> Optional[float]:
            if not lat:
                return None
            return round(lat[min(int(len(lat) * p), len(lat) - 1)], 1)

        return {
            "duration_s": round(dt, 3),
            "connected": self.connected,
            "connect_failures": self.connect_failures,
            "sent": self.sent,
            "received": self.received,
            "send_rate": round(self.sent / dt, 1),
            "recv_rate": round(self.received / dt, 1),
            "duplicates": self.duplicates,
            "latency_us": {
                "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
                "max": lat[-1] if lat else None, "n": len(lat),
            },
        }


def _topic_of(pattern: str, i: int) -> str:
    return pattern.replace("%i", str(i))


async def _quiesce(stats: "BenchStats", idle_s: float = 0.25,
                   deadline_s: float = 30.0) -> None:
    """Wait until delivery stops progressing before cancelling the
    drainers: QoS1 windowed subscribers keep draining the broker-side
    queued backlog via their acks after publishers stop, and cutting
    that tail short would undercount `received` (delivery_ratio < 1
    for messages the broker still delivers)."""
    last = -1
    deadline = time.perf_counter() + deadline_s
    while time.perf_counter() < deadline:
        await asyncio.sleep(idle_s)
        if stats.received == last:
            return
        last = stats.received


class LeanSub:
    """Minimal counting subscriber for broker-capacity A/Bs.

    A full :class:`Client` pays ~4 Python frames, an ``InboundMessage``
    and an ``asyncio.Queue`` hop per received PUBLISH — at fan-out 8+
    the harness outweighs the broker under test and every path measures
    the same loadgen ceiling.  This subscriber handshakes through the
    real codec (CONNECT/SUBSCRIBE via :func:`frame.serialize`), then
    counts PUBLISH frames with an inline fixed-header scanner and
    samples e2e latency from every ``sample``-th payload timestamp, so
    the receive side costs ~1 frame per TCP read instead of per message.

    With ``qos=1`` it subscribes at QoS1 and keeps a live acknowledged
    window: every QoS1 PUBLISH is PUBACKed (all acks for one TCP read
    coalesce into ONE write — the windowed-consumer shape), and
    DUP-flagged redeliveries are counted in ``stats.duplicates``.

    With ``qos=2`` it runs the full exactly-once receiver state machine
    inline: QoS2 grants answer PUBREC, inbound PUBRELs answer PUBCOMP —
    again one coalesced ack write per TCP read.
    """

    def __init__(self, clientid: str, host: str, port: int,
                 sample: int = 16, qos: int = 0) -> None:
        self.clientid = clientid
        self.host = host
        self.port = port
        self.sample = sample
        self.qos = qos
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._parser = F.Parser()

    async def _read_pkt(self, want: int):
        while True:
            data = await self._reader.read(65536)
            if not data:
                raise ConnectionError("closed during handshake")
            for pkt in self._parser.feed(data):
                if pkt.type == want:
                    return pkt

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        from .transport.connection import set_nodelay
        set_nodelay(self._writer.get_extra_info("socket"))
        self._writer.write(F.serialize(P.Connect(
            proto_ver=4, clientid=self.clientid, clean_start=True,
            keepalive=0)))
        pkt = await asyncio.wait_for(self._read_pkt(P.CONNACK), 10.0)
        if pkt.reason_code != 0:
            raise ConnectionError(f"CONNACK refused rc={pkt.reason_code}")

    async def subscribe(self, flt: str, qos: Optional[int] = None) -> None:
        q = self.qos if qos is None else qos
        self._writer.write(F.serialize(P.Subscribe(
            packet_id=1, topic_filters=[(flt, {"qos": q})])))
        await asyncio.wait_for(self._read_pkt(P.SUBACK), 10.0)

    async def drain(self, stats: "BenchStats") -> None:
        """Count PUBLISH frames until cancelled/EOF; other packet types
        are skipped by remaining-length.  QoS1-granted publishes are
        PUBACKed with one coalesced write per TCP read."""
        reader = self._reader
        writer = self._writer
        buf = b""
        recv = 0
        dups = 0
        sample = self.sample
        unpack_from = struct.unpack_from
        perf = time.perf_counter
        lat = stats.latencies_us
        try:
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    return
                mv = buf + data if buf else data
                i, n = 0, len(mv)
                now = perf()
                ack = bytearray()
                while n - i >= 2:
                    b1 = mv[i]
                    rl = mv[i + 1]
                    j = i + 2
                    if rl & 0x80:       # multi-byte remaining length
                        rl &= 0x7F
                        shift = 7
                        while True:
                            if j >= n:
                                rl = -1
                                break
                            b = mv[j]
                            j += 1
                            rl |= (b & 0x7F) << shift
                            if not (b & 0x80):
                                break
                            shift += 7
                        if rl < 0:
                            break
                    if j + rl > n:
                        break
                    if (b1 & 0xF0) == 0x30:
                        recv += 1
                        if b1 & 0x08:       # DUP: broker retry fired
                            dups += 1
                        off = j + 2 + ((mv[j] << 8) | mv[j + 1])
                        if b1 & 0x06:       # qos>0: packet id follows topic
                            # QoS1 grant → PUBACK; QoS2 grant → PUBREC
                            # (phase 1 of the exactly-once receiver)
                            ack += (b"\x40\x02" if (b1 & 0x06) == 0x02
                                    else b"\x50\x02")
                            ack += mv[off:off + 2]  # echo the packet id
                            off += 2
                        if recv % sample == 0 and j + rl - off >= 8:
                            (t_send,) = unpack_from("<d", mv, off)
                            lat.append((now - t_send) * 1e6)
                    elif b1 == 0x62:        # PUBREL → answer PUBCOMP
                        ack += b"\x70\x02" + mv[j:j + 2]
                    i = j + rl
                if ack:
                    writer.write(bytes(ack))
                stats.received += recv
                stats.duplicates += dups
                recv = 0
                dups = 0
                buf = mv[i:] if i < n else b""
        except (asyncio.CancelledError, ConnectionError):
            stats.received += recv
            stats.duplicates += dups

    async def disconnect(self) -> None:
        try:
            self._writer.write(F.serialize(P.Disconnect()))
            self._writer.close()
        except Exception:
            pass


class LeanPub(LeanSub):
    """Minimal pipelined-QoS1/2 publisher: one pre-built PUBLISH frame
    template per client, patched in place (packet id + payload
    timestamp) per message, with PUBACKs counted by the same inline
    scanner — the publish side of the broker-capacity A/B costs two
    ``pack_into`` and one write per message instead of a dataclass,
    a serializer pass and a pending-future per message.

    With ``qos=2`` it drives the exactly-once sender flow: PUBRECs are
    answered with one coalesced PUBREL burst per TCP read, and the
    window advances on PUBCOMP."""

    async def run(self, topic: str, payload_size: int, inflight: int,
                  end: float, stats: "BenchStats", qos: int = 1) -> None:
        tb = topic.encode()
        rl = 2 + len(tb) + 2 + max(payload_size, 8)
        head = bytes([0x32 if qos == 1 else 0x34]) + F._enc_varint(
            rl) + struct.pack(">H", len(tb)) + tb
        pid_off = len(head)
        ts_off = pid_off + 2
        frame = bytearray(head + b"\x00" * (2 + 8)
                          + b"x" * (max(payload_size, 8) - 8))
        writer = self._writer
        pack_into = struct.pack_into
        perf = time.perf_counter
        self._acked = 0
        self._ack_evt = asyncio.Event()
        ack_task = asyncio.ensure_future(self._ack_loop())
        sent = 0
        pid = 0
        try:
            while perf() < end:
                if sent - self._acked >= inflight:
                    self._ack_evt.clear()
                    try:
                        await asyncio.wait_for(
                            self._ack_evt.wait(), timeout=5.0)
                    except (asyncio.TimeoutError, TimeoutError):
                        return  # broker stalled: stop offering
                    continue
                pid = (pid % 65535) + 1
                pack_into(">H", frame, pid_off, pid)
                pack_into("<d", frame, ts_off, perf())
                writer.write(bytes(frame))
                sent += 1
                stats.sent += 1
                if not sent % inflight:
                    await asyncio.sleep(0)  # loop fairness between refills
            # drain outstanding acks so sent≈acked at summary time
            t_end = perf() + 5.0
            while self._acked < sent and perf() < t_end:
                self._ack_evt.clear()
                try:
                    await asyncio.wait_for(self._ack_evt.wait(),
                                           timeout=t_end - perf())
                except (asyncio.TimeoutError, TimeoutError):
                    break
        finally:
            ack_task.cancel()

    async def _ack_loop(self) -> None:
        reader = self._reader
        writer = self._writer
        buf = b""
        try:
            while True:
                data = await reader.read(1 << 16)
                if not data:
                    return
                mv = buf + data if buf else data
                i, n = 0, len(mv)
                rel = bytearray()
                while n - i >= 2:
                    rl = mv[i + 1]
                    j = i + 2
                    if rl & 0x80:
                        rl &= 0x7F
                        shift = 7
                        while True:
                            if j >= n:
                                rl = -1
                                break
                            b = mv[j]
                            j += 1
                            rl |= (b & 0x7F) << shift
                            if not (b & 0x80):
                                break
                            shift += 7
                        if rl < 0:
                            break
                    if j + rl > n:
                        break
                    b1 = mv[i] & 0xF0
                    if b1 == 0x40 or b1 == 0x70:  # PUBACK / PUBCOMP
                        self._acked += 1
                    elif b1 == 0x50:              # PUBREC → PUBREL burst
                        rel += b"\x62\x02" + mv[j:j + 2]
                    i = j + rl
                if rel:
                    writer.write(bytes(rel))
                self._ack_evt.set()
                buf = mv[i:] if i < n else b""
        except (asyncio.CancelledError, ConnectionError):
            pass


async def _connect_group(
    n: int,
    host: str,
    port: int,
    prefix: str,
    rate: float,
    stats: BenchStats,
    **client_kw,
) -> List[Client]:
    """Connect n clients, pacing to `rate` conns/s (0 = unpaced)."""
    clients: List[Client] = []
    interval = 1.0 / rate if rate > 0 else 0.0
    next_at = time.perf_counter()
    for i in range(n):
        if interval:
            now = time.perf_counter()
            if now < next_at:
                await asyncio.sleep(next_at - now)
            next_at += interval
        c = Client(clientid=f"{prefix}{i}", host=host, port=port, **client_kw)
        try:
            await c.connect()
            stats.connected += 1
            clients.append(c)
        except Exception:
            stats.connect_failures += 1
    return clients


async def run_scenario(
    scenario: str,
    host: str = "127.0.0.1",
    port: int = 1883,
    count: int = 10,
    rate: float = 0.0,          # conn: conns/s; pub: msgs/s per client
    topic: str = "bench/%i",
    qos: int = 0,
    payload_size: int = 64,
    duration: float = 5.0,      # pub/sub run length (s)
    messages: int = 0,          # pub: fixed message count per client (0 = by duration)
    subscribers: int = 0,       # pub: also start in-process subscribers for e2e latency
    clean_start: bool = True,
    inflight: int = 0,          # pub qos1: pipelined-ack window (0 = serial)
    sub_topic: Optional[str] = None,  # pub: subscriber filter pattern
                                      # (defaults to `topic`; "bench/#"
                                      # turns the pairwise workload into
                                      # an n_sub-way fan-out)
    sub_qos: Optional[int] = None,    # pub: subscriber granted QoS
    lean_subs: bool = False,          # pub: LeanSub counting subscribers
    lean_pubs: bool = False,          # pub: LeanPub template publishers
                                      # (qos1 + inflight window only)
    callback_subs: bool = False,      # pub: full Clients delivering via
                                      # on_message callback (no queue
                                      # hop / drain task per message)
) -> Dict[str, Any]:
    stats = BenchStats()

    if scenario == "conn":
        clients = await _connect_group(
            count, host, port, "bench_conn_", rate, stats,
            clean_start=clean_start, keepalive=300,
        )
        out = stats.summary()
        await asyncio.gather(*(c.disconnect() for c in clients))
        return out

    if scenario == "sub":
        clients = await _connect_group(
            count, host, port, "bench_sub_", rate, stats, keepalive=300
        )
        await asyncio.gather(
            *(c.subscribe(_topic_of(topic, i), qos=qos)
              for i, c in enumerate(clients))
        )
        end = time.perf_counter() + duration

        async def drain(c: Client):
            while True:
                left = end - time.perf_counter()
                if left <= 0:
                    return
                try:
                    msgs = await c.recv_many(timeout=left)
                except (asyncio.TimeoutError, TimeoutError):
                    return
                stats.received += len(msgs)
                now = time.perf_counter()
                for m in msgs:
                    if len(m.payload) >= 8:
                        (t_send,) = struct.unpack_from("<d", m.payload)
                        stats.latencies_us.append((now - t_send) * 1e6)

        await asyncio.gather(*(drain(c) for c in clients))
        out = stats.summary()
        await asyncio.gather(*(c.disconnect() for c in clients))
        return out

    if scenario == "pub":
        subs: List[Any] = []
        if subscribers:
            stopic = sub_topic if sub_topic is not None else topic
            sqos = sub_qos if sub_qos is not None else qos
            if lean_subs and sqos in (0, 1, 2):
                for i in range(subscribers):
                    s = LeanSub(f"bench_psub_{i}", host, port, qos=sqos)
                    try:
                        await s.connect()
                        stats.connected += 1
                        subs.append(s)
                    except Exception:
                        stats.connect_failures += 1
                await asyncio.gather(
                    *(s.subscribe(_topic_of(stopic, i))
                      for i, s in enumerate(subs))
                )
                drainers = [asyncio.ensure_future(s.drain(stats))
                            for s in subs]
            elif callback_subs:
                # full protocol clients, but deliveries land in an
                # on_message callback: counting + latency sampling
                # happen inline at parse time — no InboundMessage
                # queue hop or drain-task wakeup per message
                lat = stats.latencies_us
                unpack_from = struct.unpack_from
                perf = time.perf_counter

                def on_msg(m):
                    stats.received += 1
                    if m.dup:
                        stats.duplicates += 1
                    if len(m.payload) >= 8:
                        (t_send,) = unpack_from("<d", m.payload)
                        lat.append((perf() - t_send) * 1e6)

                subs = await _connect_group(
                    subscribers, host, port, "bench_psub_", 0.0, stats,
                    keepalive=300, on_message=on_msg,
                )
                await asyncio.gather(
                    *(c.subscribe(_topic_of(stopic, i), qos=sqos)
                      for i, c in enumerate(subs))
                )
                drainers = []
            else:
                subs = await _connect_group(
                    subscribers, host, port, "bench_psub_", 0.0, stats,
                    keepalive=300,
                )
                await asyncio.gather(
                    *(c.subscribe(_topic_of(stopic, i), qos=sqos)
                      for i, c in enumerate(subs))
                )

                async def drain(c: Client):
                    while True:
                        try:
                            msgs = await c.recv_many(timeout=duration + 5)
                        except (asyncio.TimeoutError, TimeoutError):
                            return
                        stats.received += len(msgs)
                        now = time.perf_counter()
                        for m in msgs:
                            if len(m.payload) >= 8:
                                (t_send,) = struct.unpack_from(
                                    "<d", m.payload)
                                stats.latencies_us.append(
                                    (now - t_send) * 1e6)

                drainers = [asyncio.ensure_future(drain(c)) for c in subs]

        if lean_pubs and qos in (1, 2) and inflight > 0 and not messages:
            lpubs: List[LeanPub] = []
            for i in range(count):
                lp = LeanPub(f"bench_pub_{i}", host, port)
                try:
                    await lp.connect()
                    stats.connected += 1
                    lpubs.append(lp)
                except Exception:
                    stats.connect_failures += 1
            end = time.perf_counter() + duration
            await asyncio.gather(
                *(lp.run(_topic_of(topic, i), payload_size, inflight,
                         end, stats, qos=qos)
                  for i, lp in enumerate(lpubs))
            )
            if subscribers:
                await _quiesce(stats)
                for d in drainers:
                    d.cancel()
            out = stats.summary()
            await asyncio.gather(
                *(c.disconnect() for c in lpubs + subs))
            return out

        pubs = await _connect_group(
            count, host, port, "bench_pub_", 0.0, stats, keepalive=300
        )
        pad = b"x" * max(payload_size - 8, 0)
        end = time.perf_counter() + duration
        interval = 1.0 / rate if rate > 0 else 0.0

        async def publish_loop(i: int, c: Client):
            sent = 0
            # stagger client phases across one interval: N aligned
            # clients would otherwise fire N-message bursts every
            # interval and the queueing delay would read as broker
            # latency (emqtt_bench staggers the same way)
            next_at = time.perf_counter() + (
                interval * i / max(1, count) if interval else 0.0)
            # pipelined QoS1 (emqtt_bench async-pub mode): the offered
            # rate stays on schedule while up to `inflight` PUBACKs ride
            # the wire, instead of serializing one RTT per message
            window: list = []
            pipelined = inflight > 0 and qos == 1
            while (messages and sent < messages) or (
                not messages and time.perf_counter() < end
            ):
                if interval:
                    now = time.perf_counter()
                    if now < next_at:
                        await asyncio.sleep(next_at - now)
                    next_at += interval
                payload = struct.pack("<d", time.perf_counter()) + pad
                if pipelined:
                    window.append(
                        c.publish_start(_topic_of(topic, i), payload))
                    if len(window) >= inflight:
                        await window.pop(0)
                else:
                    await c.publish(_topic_of(topic, i), payload, qos=qos)
                sent += 1
                stats.sent += 1
                if not interval:
                    await asyncio.sleep(0)  # yield: unpaced fairness
            for fut in window:
                try:
                    await fut
                except Exception:
                    pass

        await asyncio.gather(
            *(publish_loop(i, c) for i, c in enumerate(pubs))
        )
        if subscribers:
            # let the tail drain (until delivery quiesces), then stop
            await _quiesce(stats)
            for d in drainers:
                d.cancel()
        out = stats.summary()
        await asyncio.gather(*(c.disconnect() for c in pubs + subs))
        return out

    raise ValueError(f"unknown scenario {scenario!r}")


def main(argv: Optional[List[str]] = None) -> Dict[str, Any]:
    ap = argparse.ArgumentParser(prog="emqx_tpu.bench_client")
    ap.add_argument("scenario", choices=["conn", "sub", "pub"])
    ap.add_argument("-H", "--host", default="127.0.0.1")
    ap.add_argument("-p", "--port", type=int, default=1883)
    ap.add_argument("-c", "--count", type=int, default=10)
    ap.add_argument("-R", "--rate", type=float, default=0.0)
    ap.add_argument("-t", "--topic", default="bench/%i")
    ap.add_argument("-q", "--qos", type=int, default=0)
    ap.add_argument("-s", "--size", type=int, default=64)
    ap.add_argument("-d", "--duration", type=float, default=5.0)
    ap.add_argument("-n", "--messages", type=int, default=0)
    ap.add_argument("--subscribers", type=int, default=0)
    ap.add_argument("--inflight", type=int, default=0)
    a = ap.parse_args(argv)
    out = asyncio.run(
        run_scenario(
            a.scenario, host=a.host, port=a.port, count=a.count,
            rate=a.rate, topic=a.topic, qos=a.qos, payload_size=a.size,
            duration=a.duration, messages=a.messages,
            subscribers=a.subscribers, inflight=a.inflight,
        )
    )
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
