"""Load generator — the `emqtt_bench` analog (SURVEY.md §2.3: the
reference's baseline driver, a separate repo driven from CI).

Three scenarios, CLI-compatible in spirit with emqtt_bench:

* ``conn`` — CONNECT storm: N clients at a target connect rate.
* ``sub``  — N subscribers over a topic pattern (``%i`` = client index).
* ``pub``  — N publishers at a per-client message rate / payload size;
  reports throughput + end-to-end latency percentiles when a matching
  ``sub`` group runs in-process.

Programmatic API (used by perf tests): :func:`run_scenario` returns a
stats dict; ``python -m emqx_tpu.bench_client pub -h HOST ...`` prints it.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import struct
import time
from typing import Any, Dict, List, Optional

from .client import Client

__all__ = ["run_scenario", "BenchStats", "main"]


class BenchStats:
    def __init__(self) -> None:
        self.connected = 0
        self.connect_failures = 0
        self.sent = 0
        self.received = 0
        self.latencies_us: List[float] = []
        self.t0 = time.perf_counter()

    def summary(self) -> Dict[str, Any]:
        dt = max(time.perf_counter() - self.t0, 1e-9)
        lat = sorted(self.latencies_us)

        def pct(p: float) -> Optional[float]:
            if not lat:
                return None
            return round(lat[min(int(len(lat) * p), len(lat) - 1)], 1)

        return {
            "duration_s": round(dt, 3),
            "connected": self.connected,
            "connect_failures": self.connect_failures,
            "sent": self.sent,
            "received": self.received,
            "send_rate": round(self.sent / dt, 1),
            "recv_rate": round(self.received / dt, 1),
            "latency_us": {
                "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
                "max": lat[-1] if lat else None, "n": len(lat),
            },
        }


def _topic_of(pattern: str, i: int) -> str:
    return pattern.replace("%i", str(i))


async def _connect_group(
    n: int,
    host: str,
    port: int,
    prefix: str,
    rate: float,
    stats: BenchStats,
    **client_kw,
) -> List[Client]:
    """Connect n clients, pacing to `rate` conns/s (0 = unpaced)."""
    clients: List[Client] = []
    interval = 1.0 / rate if rate > 0 else 0.0
    next_at = time.perf_counter()
    for i in range(n):
        if interval:
            now = time.perf_counter()
            if now < next_at:
                await asyncio.sleep(next_at - now)
            next_at += interval
        c = Client(clientid=f"{prefix}{i}", host=host, port=port, **client_kw)
        try:
            await c.connect()
            stats.connected += 1
            clients.append(c)
        except Exception:
            stats.connect_failures += 1
    return clients


async def run_scenario(
    scenario: str,
    host: str = "127.0.0.1",
    port: int = 1883,
    count: int = 10,
    rate: float = 0.0,          # conn: conns/s; pub: msgs/s per client
    topic: str = "bench/%i",
    qos: int = 0,
    payload_size: int = 64,
    duration: float = 5.0,      # pub/sub run length (s)
    messages: int = 0,          # pub: fixed message count per client (0 = by duration)
    subscribers: int = 0,       # pub: also start in-process subscribers for e2e latency
    clean_start: bool = True,
    inflight: int = 0,          # pub qos1: pipelined-ack window (0 = serial)
) -> Dict[str, Any]:
    stats = BenchStats()

    if scenario == "conn":
        clients = await _connect_group(
            count, host, port, "bench_conn_", rate, stats,
            clean_start=clean_start, keepalive=300,
        )
        out = stats.summary()
        await asyncio.gather(*(c.disconnect() for c in clients))
        return out

    if scenario == "sub":
        clients = await _connect_group(
            count, host, port, "bench_sub_", rate, stats, keepalive=300
        )
        await asyncio.gather(
            *(c.subscribe(_topic_of(topic, i), qos=qos)
              for i, c in enumerate(clients))
        )
        end = time.perf_counter() + duration

        async def drain(c: Client):
            while True:
                left = end - time.perf_counter()
                if left <= 0:
                    return
                try:
                    m = await c.recv(timeout=left)
                except (asyncio.TimeoutError, TimeoutError):
                    return
                stats.received += 1
                if len(m.payload) >= 8:
                    (t_send,) = struct.unpack_from("<d", m.payload)
                    stats.latencies_us.append(
                        (time.perf_counter() - t_send) * 1e6
                    )

        await asyncio.gather(*(drain(c) for c in clients))
        out = stats.summary()
        await asyncio.gather(*(c.disconnect() for c in clients))
        return out

    if scenario == "pub":
        subs: List[Client] = []
        if subscribers:
            subs = await _connect_group(
                subscribers, host, port, "bench_psub_", 0.0, stats,
                keepalive=300,
            )
            await asyncio.gather(
                *(c.subscribe(_topic_of(topic, i), qos=qos)
                  for i, c in enumerate(subs))
            )

            async def drain(c: Client):
                while True:
                    try:
                        m = await c.recv(timeout=duration + 5)
                    except (asyncio.TimeoutError, TimeoutError):
                        return
                    stats.received += 1
                    if len(m.payload) >= 8:
                        (t_send,) = struct.unpack_from("<d", m.payload)
                        stats.latencies_us.append(
                            (time.perf_counter() - t_send) * 1e6
                        )

            drainers = [asyncio.ensure_future(drain(c)) for c in subs]

        pubs = await _connect_group(
            count, host, port, "bench_pub_", 0.0, stats, keepalive=300
        )
        pad = b"x" * max(payload_size - 8, 0)
        end = time.perf_counter() + duration
        interval = 1.0 / rate if rate > 0 else 0.0

        async def publish_loop(i: int, c: Client):
            sent = 0
            # stagger client phases across one interval: N aligned
            # clients would otherwise fire N-message bursts every
            # interval and the queueing delay would read as broker
            # latency (emqtt_bench staggers the same way)
            next_at = time.perf_counter() + (
                interval * i / max(1, count) if interval else 0.0)
            # pipelined QoS1 (emqtt_bench async-pub mode): the offered
            # rate stays on schedule while up to `inflight` PUBACKs ride
            # the wire, instead of serializing one RTT per message
            window: list = []
            pipelined = inflight > 0 and qos == 1
            while (messages and sent < messages) or (
                not messages and time.perf_counter() < end
            ):
                if interval:
                    now = time.perf_counter()
                    if now < next_at:
                        await asyncio.sleep(next_at - now)
                    next_at += interval
                payload = struct.pack("<d", time.perf_counter()) + pad
                if pipelined:
                    window.append(
                        c.publish_start(_topic_of(topic, i), payload))
                    if len(window) >= inflight:
                        await window.pop(0)
                else:
                    await c.publish(_topic_of(topic, i), payload, qos=qos)
                sent += 1
                stats.sent += 1
                if not interval:
                    await asyncio.sleep(0)  # yield: unpaced fairness
            for fut in window:
                try:
                    await fut
                except Exception:
                    pass

        await asyncio.gather(
            *(publish_loop(i, c) for i, c in enumerate(pubs))
        )
        if subscribers:
            # let the tail drain, then stop the drainers
            await asyncio.sleep(0.2)
            for d in drainers:
                d.cancel()
        out = stats.summary()
        await asyncio.gather(*(c.disconnect() for c in pubs + subs))
        return out

    raise ValueError(f"unknown scenario {scenario!r}")


def main(argv: Optional[List[str]] = None) -> Dict[str, Any]:
    ap = argparse.ArgumentParser(prog="emqx_tpu.bench_client")
    ap.add_argument("scenario", choices=["conn", "sub", "pub"])
    ap.add_argument("-H", "--host", default="127.0.0.1")
    ap.add_argument("-p", "--port", type=int, default=1883)
    ap.add_argument("-c", "--count", type=int, default=10)
    ap.add_argument("-R", "--rate", type=float, default=0.0)
    ap.add_argument("-t", "--topic", default="bench/%i")
    ap.add_argument("-q", "--qos", type=int, default=0)
    ap.add_argument("-s", "--size", type=int, default=64)
    ap.add_argument("-d", "--duration", type=float, default=5.0)
    ap.add_argument("-n", "--messages", type=int, default=0)
    ap.add_argument("--subscribers", type=int, default=0)
    ap.add_argument("--inflight", type=int, default=0)
    a = ap.parse_args(argv)
    out = asyncio.run(
        run_scenario(
            a.scenario, host=a.host, port=a.port, count=a.count,
            rate=a.rate, topic=a.topic, qos=a.qos, payload_size=a.size,
            duration=a.duration, messages=a.messages,
            subscribers=a.subscribers, inflight=a.inflight,
        )
    )
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
