"""Per-client session state: subscriptions, delivery, QoS 0/1/2 flows.

Behavioral reference: ``apps/emqx/src/emqx_session.erl`` [U] (SURVEY.md
§2.1): subscriptions map, inflight window for unacked QoS1/2, message
queue for deferred deliveries, ``awaiting_rel`` for inbound QoS2
exactly-once, packet-id allocation, retry with DUP, session expiry.

The session is a pure state machine: methods return the packets the
caller (channel/connection layer) must send, never performing IO.

Outbound QoS flows::

    QoS1: deliver → PUBLISH(pid) inflight → puback(pid) → done
    QoS2: deliver → PUBLISH(pid) inflight → pubrec(pid) → PUBREL(pid)
          → pubcomp(pid) → done

Inbound QoS2 (exactly-once)::

    recv PUBLISH(pid): awaiting_rel[pid] (dedup) → reply PUBREC
    recv PUBREL(pid):  release → reply PUBCOMP
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .inflight import Inflight, InflightFullError
from .message import Message
from .mqueue import MQueue

__all__ = ["SubOpts", "Session", "Publish", "MAX_PACKET_ID"]

MAX_PACKET_ID = 0xFFFF


@dataclass(frozen=True)
class SubOpts:
    """MQTT subscription options (v5 §3.8.3.1)."""

    qos: int = 0
    nl: bool = False    # No Local
    rap: bool = False   # Retain As Published
    rh: int = 0         # Retain Handling (0/1/2)
    share: Optional[str] = None  # $share group name
    subid: Optional[int] = None  # Subscription Identifier


@dataclass
class Publish:
    """An outbound PUBLISH the connection layer must send."""

    pid: Optional[int]   # None for QoS0
    msg: Message


class Session:
    def __init__(
        self,
        clientid: str,
        clean_start: bool = True,
        max_inflight: int = 32,
        max_awaiting_rel: int = 100,
        retry_interval: float = 30.0,
        await_rel_timeout: float = 300.0,
        expiry_interval: float = 0.0,
        mqueue: Optional[MQueue] = None,
        max_mqueue_len: Optional[int] = None,
    ) -> None:
        self.clientid = clientid
        self.clean_start = clean_start
        self.connected = True  # False while the client is away (resumable)
        self.created_at = time.time()
        self.subscriptions: Dict[str, SubOpts] = {}
        self.inflight = Inflight(max_inflight)
        if mqueue is None:
            mqueue = (
                MQueue(max_len=max_mqueue_len)
                if max_mqueue_len is not None else MQueue()
            )
        self.mqueue = mqueue
        self.awaiting_rel: Dict[int, float] = {}  # inbound QoS2 pids
        self.max_awaiting_rel = max_awaiting_rel
        self.retry_interval = retry_interval
        self.await_rel_timeout = await_rel_timeout
        self.expiry_interval = expiry_interval
        self._next_pid = 0
        # counter table (broker.metrics), set by Broker.open_session;
        # sessions built directly in tests run unmetered
        self.metrics = None
        # cross-loop guard (transport/shards.py): when the owning
        # connection lives on a shard loop this holds the channel's
        # RLock, and every main-loop toucher (fanout deliver, direct
        # delivery) takes it; None (the default) keeps the single-loop
        # paths lock-free
        self.mutex = None

    # ------------------------------------------------------------------
    # subscriptions
    # ------------------------------------------------------------------

    def subscribe(self, flt: str, opts: SubOpts) -> bool:
        """Returns True if this is a new subscription (vs an upgrade)."""
        is_new = flt not in self.subscriptions
        self.subscriptions[flt] = opts
        return is_new

    def unsubscribe(self, flt: str) -> bool:
        return self.subscriptions.pop(flt, None) is not None

    # ------------------------------------------------------------------
    # packet ids
    # ------------------------------------------------------------------

    def next_packet_id(self) -> int:
        """1..65535, skipping ids still inflight (emqx wraps the same way).

        Raises :class:`InflightFullError` when the id space is saturated
        instead of spinning the full 1..65535 range looking for a free
        slot that cannot exist — callers treat it as window backpressure
        (queue the message) rather than a crash.
        """
        inflight = self.inflight
        if len(inflight) >= MAX_PACKET_ID:
            raise InflightFullError("packet-id space exhausted")
        contains = inflight.contains
        for _ in range(MAX_PACKET_ID):
            self._next_pid = (self._next_pid % MAX_PACKET_ID) + 1
            if not contains(self._next_pid):
                return self._next_pid
        raise InflightFullError("no free packet id after one wrap")

    def alloc_packet_ids(self, k: int) -> List[int]:
        """Allocate ``k`` free packet ids in ONE wrap/skip scan.

        The batched delivery path reserves a (usually contiguous) run of
        ids per admitted batch instead of re-entering the wrap loop per
        message.  Raises :class:`InflightFullError` up front when fewer
        than ``k`` ids are free; otherwise one pass over at most the
        full id cycle finds them (each returned id is distinct — the
        scan ends before any position repeats)."""
        if k <= 0:
            return []
        inflight = self.inflight
        if MAX_PACKET_ID - len(inflight) < k:
            raise InflightFullError(
                f"{k} packet ids requested, "
                f"{MAX_PACKET_ID - len(inflight)} free")
        out: List[int] = []
        pid = self._next_pid
        contains = inflight.contains
        append = out.append
        while len(out) < k:
            pid = (pid % MAX_PACKET_ID) + 1
            if not contains(pid):
                append(pid)
        self._next_pid = pid
        return out

    # ------------------------------------------------------------------
    # outbound delivery
    # ------------------------------------------------------------------

    def deliver(self, msgs: List[Message]) -> Tuple[List[Publish], List[Message]]:
        """Accept routed messages for this session.

        Returns (to_send, dropped): QoS0 always sends; QoS1/2 send while
        the inflight window has room, else queue; queue overflow drops.

        Called once per session per batch by the fanout pipeline, so the
        common whole-batch cases (all-QoS0 to a connected client, client
        away) take amortized bulk paths instead of the per-message loop.
        """
        if not self.connected:
            # client away: everything queues (QoS0 subject to the
            # mqueue's store_qos0 policy) and drains on resume
            return [], self.mqueue.insert_many(msgs)
        if all(m.qos == 0 for m in msgs):
            # fanout hot path: no window/queue bookkeeping.  A QoS0
            # Publish (pid None) is never retried or mutated, so every
            # session fanning out the same routed Message shares ONE
            # Publish object, cached on the message like its wire bytes.
            out = []
            append = out.append
            for m in msgs:
                d = m.__dict__
                p = d.get("_pub0")
                if p is None:
                    p = d["_pub0"] = Publish(None, m)
                append(p)
            return out, []
        # batched QoS1/2 admission: ONE id-run allocation + ONE bulk
        # inflight insert (single timestamp) for however many messages
        # the window has room for right now; the rest queue, exactly as
        # the per-message loop decided
        out: List[Publish] = []
        dropped: List[Message] = []
        inflight = self.inflight
        mqueue = self.mqueue
        n12 = sum(1 for m in msgs if m.qos != 0)
        admit = min(n12, self._window_room())
        pids = self.alloc_packet_ids(admit)
        entries: List[Tuple[int, Any]] = []
        i = 0
        for msg in msgs:
            if msg.qos == 0:
                out.append(Publish(None, msg))
                continue
            if i < admit:
                pid = pids[i]
                i += 1
                entries.append((pid, ("publish", msg)))
                out.append(Publish(pid, msg))
            else:
                victim = mqueue.insert(msg)
                if victim is not None:
                    dropped.append(victim)
        self._admit(entries)
        return out, dropped

    def _window_room(self) -> int:
        """Free inflight slots right now, bounded by the free packet-id
        space (an unbounded window still cannot outgrow 1..65535)."""
        inflight = self.inflight
        room = MAX_PACKET_ID - len(inflight)
        if inflight.max_size > 0:
            room = min(room, inflight.max_size - len(inflight))
        return max(0, room)

    def _admit(self, entries: List[Tuple[int, Any]]) -> None:
        if not entries:
            return
        self.inflight.insert_many(entries)
        if self.metrics is not None and len(entries) > 1:
            self.metrics.inc("broker.inflight.batch_admitted", len(entries))

    def _dequeue(self) -> List[Publish]:
        # expire first so drops are accounted in mqueue.dropped (and
        # visible via Session.info()) like every other drop path
        self.mqueue.filter_expired()
        room = self._window_room()
        if room <= 0 or self.mqueue.is_empty():
            return []
        msgs: List[Message] = []
        pop = self.mqueue.pop
        while len(msgs) < room:
            msg = pop()
            if msg is None:
                break
            msgs.append(msg)
        pids = self.alloc_packet_ids(len(msgs))
        self._admit([(pid, ("publish", m)) for pid, m in zip(pids, msgs)])
        return [Publish(pid, m) for pid, m in zip(pids, msgs)]

    def puback(self, pid: int) -> Tuple[Optional[Message], List[Publish]]:
        """QoS1 ack.  Returns (acked message | None, next publishes)."""
        item = self.inflight.lookup(pid)
        if item is None or item[0] != "publish":
            return None, []
        self.inflight.delete(pid)
        return item[1], self._dequeue()

    def puback_batch(self, pids: List[int]) -> Tuple[List[Message], List[Publish]]:
        """A burst of QoS1 acks in one call: every pid releases its
        window slot first (unknown / wrong-state pids ignored, exactly
        like :meth:`puback`), then ONE :meth:`_dequeue` refills the
        freed room — one id-run allocation and one bulk inflight insert
        instead of a full ack→refill cycle per packet.  Returns
        (acked messages, next publishes); refill order matches the
        sequential per-ack order (mqueue FIFO)."""
        inflight = self.inflight
        acked: List[Message] = []
        for pid in pids:
            item = inflight.lookup(pid)
            if item is None or item[0] != "publish":
                continue
            inflight.delete(pid)
            acked.append(item[1])
        return acked, (self._dequeue() if acked else [])

    def pubrec(self, pid: int) -> bool:
        """QoS2 phase 1 ack; caller must send PUBREL(pid).  False if the
        pid is unknown (protocol error — reply with reason 0x92)."""
        item = self.inflight.lookup(pid)
        if item is None or item[0] != "publish":
            return False
        # keep the slot (pid stays allocated) but drop the payload
        self.inflight.update(pid, ("pubrel", None))
        return True

    def pubrec_batch(self, pids: List[int]) -> List[bool]:
        """A run of QoS2 phase-1 acks in one call: the known pids make
        ONE bulk ``publish`` → ``pubrel`` inflight transition.  Returns
        per-pid verdicts in order, exactly what sequential
        :meth:`pubrec` calls would have said (a duplicate pid in the
        run is False the second time — the slot already transitioned)."""
        inflight = self.inflight
        lookup = inflight.lookup
        out: List[bool] = []
        known: List[int] = []
        seen: set = set()
        for pid in pids:
            item = lookup(pid)
            if item is None or item[0] != "publish" or pid in seen:
                out.append(False)
            else:
                known.append(pid)
                seen.add(pid)
                out.append(True)
        if known:
            inflight.update_many(known, ("pubrel", None))
            if self.metrics is not None and len(pids) > 1:
                self.metrics.inc("broker.qos2.batch")
        return out

    def pubcomp(self, pid: int) -> Tuple[bool, List[Publish]]:
        """QoS2 completion.  Returns (known?, next publishes)."""
        item = self.inflight.lookup(pid)
        if item is None or item[0] != "pubrel":
            return False, []
        self.inflight.delete(pid)
        return True, self._dequeue()

    def pubcomp_batch(self, pids: List[int]) -> Tuple[int, List[Publish]]:
        """A run of QoS2 completions: every known pid releases its
        window slot first, then ONE :meth:`_dequeue` refills the freed
        room (one id-run allocation + one bulk insert), mirroring
        :meth:`puback_batch`.  Returns (known count, next publishes)."""
        inflight = self.inflight
        known = 0
        for pid in pids:
            item = inflight.lookup(pid)
            if item is None or item[0] != "pubrel":
                continue
            inflight.delete(pid)
            known += 1
        if known and self.metrics is not None and len(pids) > 1:
            self.metrics.inc("broker.qos2.batch")
        return known, (self._dequeue() if known else [])

    def retry(self, now: Optional[float] = None) -> List[Tuple[int, str, Optional[Message]]]:
        """Unacked items older than retry_interval, for re-send with DUP.

        Returns [(pid, kind, msg|None)]: kind 'publish' → resend
        PUBLISH(dup), kind 'pubrel' → resend PUBREL.  Peek + commit in
        one step — callers that can observe the resend write failing
        use :meth:`retry_peek` / :meth:`retry_commit` instead, so a
        dead transport doesn't burn a DUP clone (and reset the age
        clock) for a resend that never reached the wire."""
        entries = self.retry_peek(now)
        self.retry_commit(entries, now)
        out: List[Tuple[int, str, Optional[Message]]] = []
        for pid, kind, msg in entries:
            if kind == "publish":
                item = self.inflight.lookup(pid)
                if item is not None:
                    msg = item[1]    # the committed DUP clone
            out.append((pid, kind, msg))
        return out

    def retry_peek(
        self, now: Optional[float] = None
    ) -> List[Tuple[int, str, Optional[Message]]]:
        """Due entries WITHOUT mutating session state: no clone stored,
        no age-clock touch.  ``msg`` is the stored message as-is (DUP
        flag only set if a previous retry committed a clone); the
        caller renders the resend with DUP regardless and calls
        :meth:`retry_commit` once the write went through."""
        out = []
        lookup = self.inflight.lookup
        for pid in self.inflight.older_than(self.retry_interval, now):
            kind, msg = lookup(pid)
            out.append((pid, kind, msg))
        return out

    def retry_commit(
        self,
        entries: List[Tuple[int, str, Optional[Message]]],
        now: Optional[float] = None,
    ) -> None:
        """Commit a peeked retry batch after the resend flush succeeded:
        store the DUP clone and reset the age clock (one resend per
        retry_interval).  Entries acked between peek and commit are
        skipped."""
        inflight = self.inflight
        for pid, kind, msg in entries:
            cur = inflight.lookup(pid)
            if cur is None:
                continue
            # only store the clone while the slot is still in the
            # peeked phase — an ack that transitioned it (publish →
            # pubrel) between peek and commit must not be clobbered
            if kind == "publish" and cur[0] == "publish" \
                    and msg is not None and not msg.dup:
                inflight.update(pid, (kind, msg.clone(dup=True)))
            inflight.touch(pid, now)

    # ------------------------------------------------------------------
    # inbound QoS2
    # ------------------------------------------------------------------

    def publish_qos2(self, pid: int, msg: Message) -> str:
        """Register an inbound QoS2 PUBLISH.

        Returns 'ok' (new, broker must route it), 'dup' (already awaiting
        release — do NOT re-route), or 'full' (awaiting_rel overflow —
        reply reason 0x97 quota exceeded)."""
        if pid in self.awaiting_rel:
            return "dup"
        if len(self.awaiting_rel) >= self.max_awaiting_rel:
            return "full"
        self.awaiting_rel[pid] = time.time()
        return "ok"

    def pubrel_received(self, pid: int) -> bool:
        """Inbound PUBREL; caller replies PUBCOMP.  False if unknown
        (reply reason 0x92 packet-id-not-found)."""
        return self.awaiting_rel.pop(pid, None) is not None

    def pubrel_received_batch(self, pids: List[int]) -> List[bool]:
        """A run of inbound PUBRELs released in one call (the receiver
        side of a QoS2 publish burst); per-pid verdicts in order."""
        pop = self.awaiting_rel.pop
        out = [pop(pid, None) is not None for pid in pids]
        if self.metrics is not None and len(pids) > 1:
            self.metrics.inc("broker.qos2.batch")
        return out

    def expire_awaiting_rel(self, now: Optional[float] = None) -> List[int]:
        now = now if now is not None else time.time()
        stale = [
            pid for pid, ts in self.awaiting_rel.items()
            if now - ts >= self.await_rel_timeout
        ]
        for pid in stale:
            del self.awaiting_rel[pid]
        return stale

    # ------------------------------------------------------------------
    # takeover / resume (emqx_cm protocol, SURVEY.md §3.2)
    # ------------------------------------------------------------------

    def pending_messages(self) -> List[Message]:
        """Undelivered state for migration (cross-node takeover): unacked
        inflight publishes first (insertion order — pid order breaks when
        the counter wraps), then the queued backlog."""
        out: List[Message] = []
        for _pid, _ts, (kind, val) in self.inflight.items():
            if kind == "publish" and val is not None:
                out.append(val)
        out.extend(self.mqueue.to_list())
        return out

    def pending_count(self) -> int:
        return len(self.mqueue) + len(self.inflight)

    def resume_publishes(self) -> List[Publish]:
        """On reconnect: re-send inflight (DUP) then drain the queue."""
        out: List[Publish] = []
        for pid, _, (kind, msg) in list(self.inflight.items()):
            if kind == "publish" and msg is not None:
                msg = msg.clone(dup=True)
                self.inflight.update(pid, (kind, msg))
                out.append(Publish(pid, msg))
        out.extend(self._dequeue())
        return out

    def info(self) -> Dict[str, Any]:
        return {
            "clientid": self.clientid,
            "clean_start": self.clean_start,
            "created_at": self.created_at,
            "subscriptions_cnt": len(self.subscriptions),
            "inflight_cnt": len(self.inflight),
            "mqueue_len": len(self.mqueue),
            "mqueue_dropped": self.mqueue.dropped,
            "awaiting_rel_cnt": len(self.awaiting_rel),
        }
