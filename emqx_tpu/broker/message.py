"""Broker message record.

Behavioral reference: ``apps/emqx/src/emqx_message.erl`` [U] (SURVEY.md
§2.1) — id/qos/from/flags/headers/topic/payload/timestamp record plus the
expiry helpers used by retainer/delayed/session.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

__all__ = ["Message", "make_message"]

_seq = itertools.count()


def _guid() -> int:
    """Monotone-ish 64-bit message id: ms timestamp << 20 | seq."""
    return (time.time_ns() // 1_000_000) << 20 | (next(_seq) & 0xFFFFF)


@dataclass
class Message:
    id: int
    qos: int
    sender: Optional[str]       # clientid ('from' in the reference)
    topic: str
    payload: bytes
    retain: bool = False
    dup: bool = False
    headers: Dict[str, Any] = field(default_factory=dict)
    properties: Dict[str, Any] = field(default_factory=dict)  # MQTT5 props
    timestamp: float = field(default_factory=time.time)

    def expiry_interval(self) -> Optional[float]:
        v = self.properties.get("Message-Expiry-Interval")
        return float(v) if v is not None else None

    def is_expired(self, now: Optional[float] = None) -> bool:
        exp = self.expiry_interval()
        if exp is None:
            return False
        return (now if now is not None else time.time()) > self.timestamp + exp

    def with_qos(self, qos: int) -> "Message":
        # hot path: QoS already effective for most deliveries — no copy
        return self if qos == self.qos else self.clone(qos=qos)

    def clone(self, **kw) -> "Message":
        # dataclasses.replace() re-runs __init__ + field introspection —
        # measured as the dominant cost of wide fan-outs.  A __dict__
        # copy is ~4x cheaper; derived copies must not inherit per-object
        # caches keyed on the ORIGINAL's fields: the serialized-wire
        # cache (transport layer) and the shared QoS0 Publish
        # (Session.deliver) both go stale on any field change.
        m = Message.__new__(Message)
        d = dict(self.__dict__)
        d.pop("_wire", None)
        d.pop("_wire1", None)  # QoS1/2 wire template (transport layer)
        d.pop("_pub0", None)
        d.update(kw)
        m.__dict__ = d
        return m


def make_message(
    sender: Optional[str],
    topic: str,
    payload: bytes,
    qos: int = 0,
    retain: bool = False,
    properties: Optional[Dict[str, Any]] = None,
) -> Message:
    return Message(
        id=_guid(),
        qos=qos,
        sender=sender,
        topic=topic,
        payload=payload,
        retain=retain,
        properties=properties or {},
    )
