"""Cluster route table: topic filter → destination set.

Behavioral reference: ``apps/emqx/src/emqx_router.erl``
(``match_routes/1``, ``do_add_route/2``, ``do_delete_route/2``) and
``emqx_router_helper.erl`` nodedown cleanup [U] — reference mount empty,
see SURVEY.md.

Design mirrors the reference's split:

* **exact** (wildcard-free) filters live in a hash map — O(1) lookup per
  publish, never touch the trie;
* **wildcard** filters live in a :class:`FilterTrie` plus a map
  filter → destinations.

A *destination* is opaque to the router (the reference stores node names;
we store node ids or local subscriber group ids).  ``cleanup_routes``
implements the router-helper's purge of a dead node's routes.

The router is the **source of truth** the device NFA mirror is built from:
every mutation bumps ``epoch`` and appends to a bounded delta log that the
snapshot/delta compiler (``emqx_tpu.ops.compiler``) consumes.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, Hashable, Iterable, List, NamedTuple, Optional, Set, Tuple

from .. import topic as T
from .trie import FilterTrie

__all__ = ["Route", "RouteDelta", "Router"]


class Route(NamedTuple):
    filter: str
    dest: Hashable


class RouteDelta(NamedTuple):
    """One mutation of the route table, for mirror delta-sync."""

    epoch: int
    op: str  # 'add' | 'del'
    filter: str
    dest: Hashable


class Router:
    def __init__(self, delta_log_cap: int = 65536) -> None:
        self._exact: Dict[str, Set[Hashable]] = {}
        self._wild: Dict[str, Set[Hashable]] = {}
        self._trie = FilterTrie()
        self._dest_filters: Dict[Hashable, Set[str]] = {}  # reverse index
        self.epoch: int = 0
        self._deltas: Deque[RouteDelta] = deque(maxlen=delta_log_cap)
        # mutation listeners (device-mirror wake-ups); called synchronously
        # after every epoch bump with the new epoch
        self.listeners: List = []

    # ------------------------------------------------------------------
    # mutation (emqx_router:do_add_route / do_delete_route)
    # ------------------------------------------------------------------

    def add_route(self, flt: str, dest: Hashable) -> bool:
        """Register ``dest`` for ``flt``.  Returns True if the (filter,
        dest) pair is new."""
        table = self._wild if T.wildcard(flt) else self._exact
        dests = table.get(flt)
        if dests is None:
            dests = table[flt] = set()
            if table is self._wild:
                self._trie.insert(flt)
        if dest in dests:
            return False
        dests.add(dest)
        self._dest_filters.setdefault(dest, set()).add(flt)
        self._bump("add", flt, dest)
        return True

    def delete_route(self, flt: str, dest: Hashable) -> bool:
        table = self._wild if T.wildcard(flt) else self._exact
        dests = table.get(flt)
        if dests is None or dest not in dests:
            return False
        dests.discard(dest)
        if not dests:
            del table[flt]
            if table is self._wild:
                self._trie.delete(flt)
        df = self._dest_filters.get(dest)
        if df is not None:
            df.discard(flt)
            if not df:
                del self._dest_filters[dest]
        self._bump("del", flt, dest)
        return True

    def cleanup_routes(self, dest: Hashable) -> int:
        """Purge every route owned by ``dest`` (nodedown handling in
        emqx_router_helper).  Returns the number purged."""
        flts = list(self._dest_filters.get(dest, ()))
        for flt in flts:
            self.delete_route(flt, dest)
        return len(flts)

    def _bump(self, op: str, flt: str, dest: Hashable) -> None:
        self.epoch += 1
        self._deltas.append(RouteDelta(self.epoch, op, flt, dest))
        for fn in self.listeners:
            fn(self.epoch)

    # ------------------------------------------------------------------
    # lookup (emqx_router:match_routes — THE hot path)
    # ------------------------------------------------------------------

    def match_routes(self, name: str) -> List[Route]:
        """All (filter, dest) routes whose filter matches concrete topic
        ``name``: exact hash hit + wildcard trie walk."""
        out: List[Route] = []
        dests = self._exact.get(name)
        if dests:
            out.extend(Route(name, d) for d in dests)
        for flt in self._trie.match(name):
            for d in self._wild[flt]:
                out.append(Route(flt, d))
        return out

    def routes_with_wild(
        self, name: str, wild_filters: Iterable[str]
    ) -> List[Route]:
        """Assemble routes from the exact map plus an externally-computed
        wildcard filter list (the device matcher's answer) — the consume
        side of the TPU publish hint (SURVEY.md §3.4 hot path)."""
        out: List[Route] = []
        dests = self._exact.get(name)
        if dests:
            out.extend(Route(name, d) for d in dests)
        for flt in wild_filters:
            for d in self._wild.get(flt, ()):
                out.append(Route(flt, d))
        return out

    def match_dests(self, name: str) -> Set[Hashable]:
        out: Set[Hashable] = set()
        dests = self._exact.get(name)
        if dests:
            out |= dests
        for flt in self._trie.match(name):
            out |= self._wild[flt]
        return out

    def has_route(self, flt: str, dest: Hashable) -> bool:
        table = self._wild if T.wildcard(flt) else self._exact
        return dest in table.get(flt, ())

    # ------------------------------------------------------------------
    # introspection / mirror sync
    # ------------------------------------------------------------------

    def topics(self) -> List[str]:
        return list(self._exact) + list(self._wild)

    def wildcard_filters(self) -> List[str]:
        return list(self._wild)

    def route_count(self) -> int:
        return sum(len(v) for v in self._exact.values()) + sum(
            len(v) for v in self._wild.values()
        )

    def routes_of(self, flt: str) -> Set[Hashable]:
        table = self._wild if T.wildcard(flt) else self._exact
        return set(table.get(flt, ()))

    def deltas_since(self, epoch: int) -> Optional[List[RouteDelta]]:
        """Deltas after ``epoch``, or None if the log no longer reaches back
        that far (caller must full-resnapshot — the mria
        bootstrap-then-replay-rlog pattern, SURVEY.md §5.4).

        O(requested span), not O(log): epochs are contiguous (every
        ``_bump`` appends exactly one delta), so the tail is located by
        index — the per-publish freshness proof must never walk the
        whole 65k-cap deque."""
        n = self.epoch - epoch
        if n <= 0:
            return []
        ln = len(self._deltas)
        if n > ln:
            return None
        if n == ln:
            return list(self._deltas)
        import itertools

        return list(itertools.islice(self._deltas, ln - n, ln))
