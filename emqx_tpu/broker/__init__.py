"""Host control plane: the authoritative broker state and MQTT semantics.

Subscription tables here are the source of truth; the device NFA in
``emqx_tpu.ops`` is an eventually-consistent mirror (SURVEY.md §2.2 mria
notes, §5.4).
"""

from .trie import FilterTrie, TopicTrie
from .router import Route, RouteDelta, Router

__all__ = ["FilterTrie", "TopicTrie", "Route", "RouteDelta", "Router"]
