"""Host control plane: the authoritative broker state and MQTT semantics.

Subscription tables here are the source of truth; the device NFA in
``emqx_tpu.ops`` is an eventually-consistent mirror (SURVEY.md §2.2 mria
notes, §5.4).
"""

from .trie import FilterTrie, TopicTrie
from .router import Route, RouteDelta, Router
from .message import Message, make_message
from .hooks import Hooks, HOOK_POINTS, OK, STOP
from .mqueue import MQueue
from .inflight import Inflight, InflightFullError
from .session import MAX_PACKET_ID, Publish, Session, SubOpts
from .shared_sub import STRATEGIES, SharedSub
from .broker import Broker, DeliverResult
from .fanout import FanoutPipeline
from .cm import ConnectionManager
from .channel import Channel
from .admission import Admission
from .banned import Banned, BanEntry
from .flapping import Flapping
from .limiter import LimiterGroup, TokenBucket
from .olp import Olp

__all__ = [
    "FilterTrie", "TopicTrie", "Route", "RouteDelta", "Router",
    "Message", "make_message", "Hooks", "HOOK_POINTS", "OK", "STOP",
    "MQueue", "Inflight", "InflightFullError",
    "MAX_PACKET_ID", "Publish", "Session", "SubOpts",
    "STRATEGIES", "SharedSub", "Broker", "DeliverResult", "FanoutPipeline",
    "ConnectionManager", "Channel",
    "Admission",
    "Banned", "BanEntry", "Flapping", "LimiterGroup", "TokenBucket", "Olp",
]
