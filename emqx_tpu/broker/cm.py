"""Connection manager: clientid registry + session takeover protocol.

Behavioral reference: ``apps/emqx/src/emqx_cm.erl``, ``emqx_cm_registry``,
``emqx_cm_locker`` [U] (SURVEY.md §2.1, §3.2):

* one live channel per clientid; a new CONNECT with the same clientid
  either **discards** (clean_start) or **takes over** (resume) the old
  session, and the old channel is told to close with
  ``SESSION_TAKEN_OVER``;
* per-clientid critical section (the locker) — single-threaded here, but
  the API shape (``open_session`` returning the displaced channel) is
  what the cluster layer serializes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from .broker import Broker
from .session import Session

__all__ = ["ConnectionManager"]


class ConnectionManager:
    def __init__(self, broker: Broker) -> None:
        self.broker = broker
        self._channels: Dict[str, Any] = {}  # clientid -> channel handle

    def open_session(
        self, clientid: str, clean_start: bool, channel: Any, **session_kw
    ) -> Tuple[Session, bool, Optional[Any]]:
        """Returns (session, session_present, displaced_channel)."""
        old_chan = self._channels.get(clientid)
        if old_chan is not None and not clean_start:
            # clean_start discards instead — broker fires session.discarded;
            # takeover and discard are mutually exclusive outcomes
            self.broker.hooks.run("session.takenover", (clientid,))
        sess, present = self.broker.open_session(
            clientid, clean_start=clean_start, **session_kw
        )
        self._channels[clientid] = channel
        return sess, present, old_chan

    def register_channel(self, clientid: str, channel: Any) -> None:
        self._channels[clientid] = channel

    def unregister_channel(self, clientid: str, channel: Any) -> None:
        """Only the owning channel may unregister (a displaced channel
        closing late must not evict its successor)."""
        if self._channels.get(clientid) is channel:
            del self._channels[clientid]

    def lookup_channel(self, clientid: str) -> Optional[Any]:
        return self._channels.get(clientid)

    def kick(self, clientid: str) -> Optional[Any]:
        """Forcibly displace a client (mgmt API / banned)."""
        chan = self._channels.pop(clientid, None)
        self.broker.close_session(clientid, discard=True)
        return chan

    def connection_count(self) -> int:
        """Live (currently connected) channels only."""
        return len(self._channels)

    def total_connection_count(self) -> int:
        """Live channels plus disconnected persistent sessions — the
        reference's ``connections.count`` includes sessions whose
        transport dropped but whose state is retained, while
        ``live_connections.count`` is connected-only."""
        ids = set(self._channels)
        ids.update(self.broker.sessions)
        return len(ids)

    def all_clientids(self):
        return list(self._channels)
