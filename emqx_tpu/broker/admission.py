"""Batched admission plane: vectorized anomaly screening + quarantine
ladder + fail-open degradation.

Behavioral reference: the P4-pipeline paper (PAPERS.md, arxiv
2601.07536) pushes MQTT security screening and anomaly mitigation into
the dataplane at line rate.  The analog here is a **batched scoring
stage on the ingest path**: the per-connection token buckets
(``broker/limiter.py``) and disconnect-count bans (``broker/flapping.py``)
gate *volume*; nothing before this module scored *behavior*, so a
CONNECT storm or a topic-scan flood browned out honest clients right
alongside the attackers.

Dataflow::

    ingest seams ──O(1) notes──▶ per-client counter rows (numpy slabs)
                                        │  admission.score child,
                                        ▼  one vectorized pass / tick
                                EWMA feature rows ──▶ score = Σ wᵢ·fᵢ/tᵢ
                                        │
                                        ▼  hysteresis (hold/decay ticks)
              quarantine ladder: 0 observe → 1 throttle (TokenBucket)
                  → 2 quarantine (QoS0-shed) → 3 temp-ban (Banned)

* **O(1) accumulation.**  Every seam call (`note_connect`,
  ``note_publish``, ...) is one row lookup + a few slab increments —
  no per-event allocation on the hot path.  Feature rows live in
  preallocated numpy arrays (``_counts``/``_feat``) with a free-list
  allocator, so the per-tick scoring pass is genuinely vectorized over
  ALL active clients: rates = counts/dt, EWMA fold, weighted score —
  three numpy expressions regardless of client count.
* **Distinct-topic fan** uses a 64-bit per-client sketch (one bit per
  ``hash(topic) & 63``): O(1) update, linear-counting estimate
  ``-m·ln(z/m)`` at tick time — a topic-scan flood saturates it while
  a telemetry client publishing one topic sets one bit.
* **Host-keyed storm rows**: CONNECT and auth-failure features
  accumulate on an ``ip:<peerhost>`` row ALONGSIDE the per-clientid
  row, so a distributed-clientid flood from one host concentrates on
  the host row instead of diluting across fresh per-client EWMAs.
  The ip ladder skips throttle/kick (no single channel to retune) and
  bottoms out at the peerhost temp-ban, which refuses the whole host
  at CONNACK.
* **Ladder hysteresis**: escalate one level after ``hold_ticks``
  consecutive ticks at or above the threshold, de-escalate after
  ``decay_ticks`` consecutive calm ticks — recovered clients climb
  back down, flapping around the threshold moves nobody.
* **Fail-open by construction**: the scorer runs as a supervised
  ``admission.score`` child.  A crash, kill, or injected fault clears
  every standing decision (shed set emptied, throttles restored),
  raises the ``admission_degraded`` alarm and lets the supervisor
  restart it — degradation means *less screening*, never a new drop
  path.  The first successful tick after recovery clears the alarm.
* **Zero-cost when off**: ``admission.enable`` off leaves
  ``broker.admission`` as ``None`` and every seam guards with one
  attribute load + identity test (the faultinject idiom) — no function
  call at all, spy-asserted by tests/test_admission.py.
* **Explainable**: every standing decision carries its feature row —
  ``ctl admission`` / ``GET /api/v5/admission`` show *why* a client is
  throttled, not just that it is.

Thread-safety: all state is main-loop-affine.  The one seam that fires
on shard loops — the frame-parse error path — appends to a deque
(atomic under the GIL) that the tick drains on the main loop.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import faultinject as _fi

log = logging.getLogger(__name__)

__all__ = ["Admission", "FEATURES", "LEVELS"]

#: feature-row column order (the explain surface names them verbatim)
FEATURES = (
    "connect_rate", "disconnect_rate", "malformed_rate",
    "auth_fail_rate", "publish_rate", "publish_bytes_rate", "topic_fan",
)
_N_FEAT = len(FEATURES)
# counter-slab columns 0..5 map to FEATURES 0..5; topic_fan comes from
# the per-row bit sketch, not a counter
_C_CONNECT, _C_DISCONNECT, _C_MALFORMED = 0, 1, 2
_C_AUTH_FAIL, _C_PUB, _C_BYTES = 3, 4, 5
_N_COUNT = 6

LEVELS = ("observe", "throttle", "quarantine", "ban")

_SKETCH_BITS = 64


class Admission:
    """The per-node admission plane (see module docstring).

    Ownership: constructed by the node when ``admission.enable`` is on,
    published as ``broker.admission`` (the seams' None-guard handle) and
    driven by the supervised ``admission.score`` child (:meth:`run`).
    """

    def __init__(
        self,
        banned: Any = None,
        alarms: Any = None,
        metrics: Any = None,
        flightrec: Any = None,
        olp: Any = None,
        tick_s: float = 1.0,
        fan_window: float = 1.0,
        alpha: float = 0.3,
        threshold: float = 1.0,
        clear_ratio: float = 0.5,
        hold_ticks: int = 2,
        decay_ticks: int = 5,
        throttle_rate: float = 50.0,
        restore_rate: float = 0.0,
        ban_time: float = 60.0,
        idle_expiry: float = 300.0,
        max_connect_rate: float = 2.0,
        max_malformed_rate: float = 1.0,
        max_auth_fail_rate: float = 1.0,
        max_publish_rate: float = 500.0,
        max_publish_bytes_rate: float = 4.0 * 1024 * 1024,
        max_topic_fan: float = 50.0,
        clock: Optional[Callable[[], float]] = None,
        wall: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], Any]] = None,
    ) -> None:
        self.banned = banned
        self.alarms = alarms
        self.metrics = metrics
        self.flightrec = flightrec
        self.olp = olp
        self.tick_s = tick_s
        # the distinct-topic sketch accumulates across ticks and folds
        # once per fan_window: "distinct topics per second" must count
        # NEW topics, not re-count one topic once per (possibly very
        # short) tick — at a 20 ms tick a single-topic client would
        # otherwise read as 50 distinct/s
        self.fan_window = max(fan_window, tick_s)
        self.alpha = alpha
        self.threshold = threshold
        self.clear_ratio = clear_ratio
        self.hold_ticks = hold_ticks
        self.decay_ticks = decay_ticks
        self.throttle_rate = throttle_rate
        # the configured per-connection message rate (limiter.max_
        # messages_rate) a de-escalated client is restored to; 0 =
        # unlimited, TokenBucket's own convention
        self.restore_rate = restore_rate
        self.ban_time = ban_time
        self.idle_expiry = idle_expiry
        # per-feature thresholds (per second); the score is the
        # weighted sum of feature/threshold ratios, so 1.0 ≈ one
        # dimension fully saturated.  Disconnect shares the connect
        # threshold (a storm flaps both identically).
        self._thresholds = np.array([
            max_connect_rate, max_connect_rate, max_malformed_rate,
            max_auth_fail_rate, max_publish_rate, max_publish_bytes_rate,
            max_topic_fan,
        ], dtype=np.float64)
        self._weights = np.ones(_N_FEAT, dtype=np.float64)
        self._clock = clock if clock is not None else time.monotonic
        # the Banned table keys expiry on wall time; the scorer's own
        # cadence is monotonic — both injectable (supervise.py idiom)
        self._wall = wall if wall is not None else time.time
        self._sleep = sleep if sleep is not None else asyncio.sleep

        # ladder action callbacks, wired by the node:
        #   throttle_cb(clientid, rate_or_None)  None = restore default
        #   kick_cb(clientid)                    close the live conn
        self.throttle_cb: Optional[Callable[[str, Optional[float]], Any]] \
            = None
        self.kick_cb: Optional[Callable[[str], Any]] = None

        # row storage: key -> slot; preallocated slabs grow by doubling
        cap = 256
        self._slots: Dict[str, int] = {}
        self._keys: List[Optional[str]] = [None] * cap
        self._free: List[int] = list(range(cap - 1, -1, -1))
        self._counts = np.zeros((cap, _N_COUNT), dtype=np.float64)
        self._feat = np.zeros((cap, _N_FEAT), dtype=np.float64)
        self._score = np.zeros(cap, dtype=np.float64)
        self._level = np.zeros(cap, dtype=np.int8)
        self._hold = np.zeros(cap, dtype=np.int32)   # consecutive hot
        self._calm = np.zeros(cap, dtype=np.int32)   # consecutive calm
        self._last_seen = np.zeros(cap, dtype=np.float64)
        self._since = np.zeros(cap, dtype=np.float64)  # level!=0 entry
        self._sketch: List[int] = [0] * cap

        # enforcement state the hot paths consult
        self._shed: set = set()           # clientids at level >= 2
        # shard-loop-safe ingress for the frame-parse error seam
        # (deque.append is atomic under the GIL; drained at tick)
        self._malformed_q: deque = deque()

        self._last_tick = self._clock()
        self._fan_started = self._last_tick
        self.ticks = 0
        self.degraded = False
        self.bans = 0
        self.shed_count = 0

    # ------------------------------------------------------------------
    # O(1) accumulation seams (main loop unless noted)
    # ------------------------------------------------------------------

    def _slot(self, key: str, now: Optional[float] = None) -> int:
        idx = self._slots.get(key)
        if idx is None:
            if not self._free:
                self._grow()
            idx = self._free.pop()
            self._slots[key] = idx
            self._keys[idx] = key
            self._counts[idx] = 0.0
            self._feat[idx] = 0.0
            self._score[idx] = 0.0
            self._level[idx] = 0
            self._hold[idx] = 0
            self._calm[idx] = 0
            self._since[idx] = 0.0
            self._sketch[idx] = 0
        self._last_seen[idx] = now if now is not None else self._clock()
        return idx

    def _grow(self) -> None:
        old = len(self._keys)
        new = old * 2
        self._keys.extend([None] * old)
        self._free.extend(range(new - 1, old - 1, -1))
        for name in ("_counts", "_feat", "_score", "_level", "_hold",
                     "_calm", "_last_seen", "_since"):
            arr = getattr(self, name)
            grown = np.zeros((new,) + arr.shape[1:], dtype=arr.dtype)
            grown[:old] = arr
            setattr(self, name, grown)
        self._sketch.extend([0] * old)

    # NOTE: every increment resolves the slot FIRST — ``_slot`` may
    # grow (and rebind) the slabs, and ``self._counts[self._slot(k)]``
    # would subscript the pre-grow array Python already loaded.

    def note_connect(self, clientid: str,
                     peerhost: Optional[str] = None) -> None:
        """CONNECT seam.  The storm features ALSO key on the ``ip:``
        peerhost row when the caller knows it: a distributed-clientid
        flood from one host spreads one connect per fresh row and
        never trips the per-client EWMA — the host row sums them.
        The ip ladder skips throttle/kick (no live channel to retune)
        and lands at the peerhost temp-ban."""
        i = self._slot(clientid)
        self._counts[i, _C_CONNECT] += 1.0
        if peerhost:
            j = self._slot(f"ip:{peerhost}")
            self._counts[j, _C_CONNECT] += 1.0

    def note_disconnect(self, clientid: str) -> None:
        i = self._slot(clientid)
        self._counts[i, _C_DISCONNECT] += 1.0

    def note_auth_failure(self, clientid: str,
                          peerhost: Optional[str] = None) -> None:
        i = self._slot(clientid)
        self._counts[i, _C_CONNECT] += 1.0
        self._counts[i, _C_AUTH_FAIL] += 1.0
        if peerhost:
            # credential stuffing rotates clientids freely; the source
            # host is the stable key (see note_connect)
            j = self._slot(f"ip:{peerhost}")
            self._counts[j, _C_CONNECT] += 1.0
            self._counts[j, _C_AUTH_FAIL] += 1.0

    def note_publish(self, clientid: Optional[str], topic: str,
                     nbytes: int, n: int = 1) -> None:
        if clientid is None:
            return
        i = self._slot(clientid)
        self._counts[i, _C_PUB] += float(n)
        self._counts[i, _C_BYTES] += float(nbytes)
        self._sketch[i] |= 1 << (hash(topic) & (_SKETCH_BITS - 1))

    def note_publish_batch(self, clientid: Optional[str],
                           pkts: List[Any]) -> None:
        """Publish-run ingest seam: one row lookup for the whole run."""
        if clientid is None or not pkts:
            return
        i = self._slot(clientid)
        self._counts[i, _C_PUB] += float(len(pkts))
        self._counts[i, _C_BYTES] += float(
            sum(len(p.payload) for p in pkts))
        s = self._sketch[i]
        for p in pkts:
            s |= 1 << (hash(p.topic) & (_SKETCH_BITS - 1))
        self._sketch[i] = s

    def note_malformed(self, clientid: Optional[str],
                       peername: Any) -> None:
        """Frame-parse error seam.  May be called from a SHARD loop
        (proto_conn._frame_error) — the deque append is the only
        cross-thread write, drained on the main loop at tick time.
        Pre-CONNECT errors key on the peer host."""
        if clientid is None:
            host = peername[0] if isinstance(peername, (tuple, list)) \
                and peername else peername
            if host is None:
                return
            key = f"ip:{host}"
        else:
            key = clientid
        self._malformed_q.append(key)

    # ------------------------------------------------------------------
    # enforcement surfaces (hot paths; None-guarded by the callers)
    # ------------------------------------------------------------------

    def shed_qos0(self, clientid: Optional[str]) -> bool:
        """True ⇒ drop this QoS0 publish (sender is quarantined).
        The common case — sender not quarantined — is one set lookup;
        the freshness check runs only for quarantined senders, so a
        hung (not crashed) scorer still fails open within 4 ticks."""
        if clientid not in self._shed:
            return False
        if self._clock() - self._last_tick > 4.0 * self.tick_s:
            return False  # stale decisions never drop traffic
        self.shed_count += 1
        if self.metrics is not None:
            self.metrics.inc("broker.admission.shed_qos0")
        return True

    # ------------------------------------------------------------------
    # the supervised scorer
    # ------------------------------------------------------------------

    async def run(self) -> None:
        """``admission.score`` child body: one vectorized scoring pass
        per tick.  Any exit — crash, kill, injected fault — fails open
        before the supervisor restarts it."""
        try:
            while True:
                await self._sleep(self.tick_s)
                if _fi._injector is not None:
                    act = _fi._injector.act("admission.score")
                    if act == "raise":
                        raise _fi.InjectedFault("admission.score")
                    if act == "delay":
                        await _fi._injector.pause()
                    elif act == "hang":
                        await _fi._injector.hang()
                self.score_tick()
        except asyncio.CancelledError:
            self._fail_open("killed")
            raise
        except Exception:
            self._fail_open("crashed")
            raise

    def _fail_open(self, why: str) -> None:
        """Degrade by screening LESS: every standing decision clears,
        traffic flows, the alarm tells the operator scoring is down.
        Idempotent — repeated crash/restart cycles re-enter cleanly."""
        log.warning("admission scorer %s: failing open "
                    "(decisions cleared, traffic unscreened)", why)
        self.degraded = True
        if self.metrics is not None:
            self.metrics.inc("broker.admission.fail_open")
        self._shed.clear()
        n = len(self._keys)
        for idx in range(n):
            if self._level[idx] > 0:
                key = self._keys[idx]
                self._level[idx] = 0
                self._hold[idx] = 0
                self._calm[idx] = 0
                if key is not None and self.throttle_cb is not None:
                    try:
                        self.throttle_cb(key, None)
                    except Exception:
                        log.debug("admission unthrottle failed for %r",
                                  key, exc_info=True)
        self._sync_gauges()
        if self.alarms is not None:
            self.alarms.activate(
                "admission_degraded",
                {"why": why},
                "admission scorer down; fail-open, traffic unscreened",
            )

    def _recovered(self) -> None:
        self.degraded = False
        if self.alarms is not None:
            self.alarms.deactivate("admission_degraded")

    # ------------------------------------------------------------------

    def score_tick(self, now: Optional[float] = None) -> None:
        """One vectorized pass over every active client: rates → EWMA
        features → weighted score → ladder transitions → eviction."""
        now = now if now is not None else self._clock()
        dt = max(now - self._last_tick, 1e-6)
        self._last_tick = now
        self.ticks += 1
        # drain the cross-thread malformed queue into the slabs
        q = self._malformed_q
        while q:
            try:
                key = q.popleft()
            except IndexError:  # raced a concurrent producer drain
                break
            i = self._slot(key, now)
            self._counts[i, _C_MALFORMED] += 1.0

        # -- the vectorized core: numpy expressions cover every row --
        n = len(self._keys)
        counts = self._counts[:n]
        feat = self._feat[:n]
        alpha = self.alpha
        rate_cols = feat[:, :_N_COUNT]
        np.multiply(rate_cols, 1.0 - alpha, out=rate_cols)
        rate_cols += alpha * (counts / dt)
        # topic fan folds on its OWN window: the sketch keeps
        # accumulating across ticks, then a linear-counting distinct
        # estimate per second folds in and the sketch resets (a
        # saturated sketch caps far above any sane threshold)
        fan_dt = now - self._fan_started
        if fan_dt >= self.fan_window:
            self._fan_started = now
            fan = np.zeros(n, dtype=np.float64)
            for idx in self._slots.values():
                bits = self._sketch[idx]
                if bits:
                    z = _SKETCH_BITS - bin(bits).count("1")
                    est = (_SKETCH_BITS * math.log(_SKETCH_BITS / z)
                           if z > 0 else float(_SKETCH_BITS) * 4.0)
                    fan[idx] = est / fan_dt
                    self._sketch[idx] = 0
            fan_col = feat[:, _N_COUNT]
            np.multiply(fan_col, 1.0 - alpha, out=fan_col)
            fan_col += alpha * fan
        # fresh-evidence mask BEFORE the counters reset: escalation
        # requires activity THIS tick, so a client that stopped freezes
        # at its level while the EWMA drains instead of marching to a
        # ban on stale memory — decay is reachable by construction
        active = counts.sum(axis=1) > 0.0
        counts[:] = 0.0
        scores = (feat / self._thresholds) @ self._weights
        self._score[:n] = scores

        # overload tightens the gate: under a live brownout the broker
        # cannot afford to watch an attacker for long — each brownout
        # stage lowers the effective threshold 25%
        threshold = self.threshold
        if self.olp is not None:
            level = self.olp.brownout_level()
            if level:
                threshold *= max(0.25, 1.0 - 0.25 * level)
        clear = threshold * self.clear_ratio

        hot = scores >= threshold
        calm = scores <= clear
        self._hold[:n] = np.where(
            hot, np.where(active, self._hold[:n] + 1, self._hold[:n]), 0)
        self._calm[:n] = np.where(calm, self._calm[:n] + 1, 0)
        self._transition(now, threshold)
        self._evict_idle(now)
        self._sync_gauges()
        if self.degraded:
            self._recovered()

    def _transition(self, now: float, threshold: float) -> None:
        """Apply ladder moves for rows whose hysteresis counters just
        crossed (python loop over the HANDFUL of crossing rows, not the
        population — the masks come from the vectorized pass)."""
        escalated_to_quarantine = False
        up = np.nonzero((self._hold >= self.hold_ticks)
                        & (self._level < 3))[0]
        for idx in up:
            key = self._keys[idx]
            if key is None:
                continue
            self._hold[idx] = 0
            self._calm[idx] = 0
            new = int(self._level[idx]) + 1
            self._level[idx] = new
            if self._since[idx] == 0.0:
                self._since[idx] = now
            log.warning(
                "admission: %r escalated to %s (score %.2f >= %.2f)",
                key, LEVELS[new], float(self._score[idx]), threshold)
            if new == 1:
                self._apply_throttle(key, self.throttle_rate)
            elif new == 2:
                self._shed.add(key)
                escalated_to_quarantine = True
            elif new == 3:
                self._ban(key, idx)
        down = np.nonzero((self._calm >= self.decay_ticks)
                          & (self._level > 0))[0]
        for idx in down:
            key = self._keys[idx]
            if key is None:
                continue
            self._calm[idx] = 0
            new = int(self._level[idx]) - 1
            self._level[idx] = new
            log.info("admission: %r de-escalated to %s", key, LEVELS[new])
            if new == 1:      # quarantine -> throttle: stop shedding
                self._shed.discard(key)
            elif new == 0:    # throttle -> observe: restore the bucket
                self._apply_throttle(key, None)
                self._since[idx] = 0.0
        if escalated_to_quarantine:
            # ladder escalations are operator events: alarm while any
            # client sits in quarantine, one flight-recorder dump per
            # tick at most (an attack wave must not write N files)
            if self.flightrec is not None:
                self.flightrec.dump("admission_escalation")
        if self.alarms is not None:
            if self._shed:
                self.alarms.activate(
                    "admission_quarantine",
                    {"clients": len(self._shed)},
                    "clients quarantined by the admission plane",
                )
            else:
                self.alarms.deactivate("admission_quarantine")

    def _apply_throttle(self, key: str, rate: Optional[float]) -> None:
        if key.startswith("ip:") or self.throttle_cb is None:
            return
        try:
            self.throttle_cb(key, rate)
        except Exception:
            log.debug("admission throttle(%r, %r) failed", key, rate,
                      exc_info=True)

    def _ban(self, key: str, idx: int) -> None:
        self.bans += 1
        if self.metrics is not None:
            self.metrics.inc("broker.admission.banned")
        if self.banned is not None:
            kind, who = ("peerhost", key[3:]) if key.startswith("ip:") \
                else ("clientid", key)
            self.banned.add(kind, who, duration=self.ban_time,
                            by="admission",
                            reason=f"admission score "
                                   f"{float(self._score[idx]):.2f}",
                            now=self._wall())
        self._apply_throttle(key, None)
        self._shed.discard(key)
        if self.kick_cb is not None and not key.startswith("ip:"):
            try:
                self.kick_cb(key)
            except Exception:
                log.debug("admission kick(%r) failed", key, exc_info=True)
        # the ban owns the client now; drop the row so a post-expiry
        # reconnect starts back at observe (climb-down by construction)
        self._drop(idx)

    def _drop(self, idx: int) -> None:
        key = self._keys[idx]
        if key is None:
            return
        self._shed.discard(key)
        del self._slots[key]
        self._keys[idx] = None
        self._level[idx] = 0
        self._free.append(idx)

    def _evict_idle(self, now: float) -> None:
        """Bound per-client state under reconnect churn: rows idle past
        ``idle_expiry`` with no standing decision are freed."""
        n = len(self._keys)
        stale = np.nonzero(
            (self._last_seen[:n] < now - self.idle_expiry)
            & (self._level[:n] == 0))[0]
        for idx in stale:
            self._drop(idx)

    def _sync_gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.set("broker.admission.tracked_clients",
                         len(self._slots))
        lv = self._level[:len(self._keys)]
        self.metrics.set("broker.admission.throttled",
                         int(np.count_nonzero(lv >= 1)))
        self.metrics.set("broker.admission.quarantined",
                         int(np.count_nonzero(lv >= 2)))

    # ------------------------------------------------------------------
    # explain surface (ctl admission / GET /api/v5/admission)
    # ------------------------------------------------------------------

    def explain(self, key: str) -> Optional[Dict[str, Any]]:
        idx = self._slots.get(key)
        if idx is None:
            return None
        return self._row(key, idx)

    def _row(self, key: str, idx: int) -> Dict[str, Any]:
        return {
            "clientid": key,
            "level": int(self._level[idx]),
            "level_name": LEVELS[int(self._level[idx])],
            "score": round(float(self._score[idx]), 4),
            "for_s": (round(self._clock() - self._since[idx], 3)
                      if self._since[idx] else None),
            "features": {
                name: round(float(self._feat[idx, f]), 4)
                for f, name in enumerate(FEATURES)
            },
        }

    def list_decisions(self, all_rows: bool = False,
                       limit: int = 256) -> List[Dict[str, Any]]:
        """Standing decisions (level > 0), worst score first; with
        ``all_rows`` every tracked client, for forensics."""
        rows = []
        for key, idx in self._slots.items():
            if all_rows or self._level[idx] > 0:
                rows.append(self._row(key, idx))
        rows.sort(key=lambda r: (-r["level"], -r["score"]))
        return rows[:limit]

    def clear(self, key: str) -> bool:
        """Operator override: lift a standing decision NOW (REST
        DELETE).  The feature row survives — a still-hostile client
        climbs right back."""
        idx = self._slots.get(key)
        if idx is None:
            return False
        if self._level[idx] > 0:
            self._shed.discard(key)
            self._apply_throttle(key, None)
            self._level[idx] = 0
            self._hold[idx] = 0
            self._calm[idx] = 0
            self._since[idx] = 0.0
            self._sync_gauges()
        return True

    def info(self) -> Dict[str, Any]:
        lv = self._level[:len(self._keys)]
        return {
            "enabled": True,
            "degraded": self.degraded,
            "ticks": self.ticks,
            "tracked_clients": len(self._slots),
            "throttled": int(np.count_nonzero(lv == 1)),
            "quarantined": int(np.count_nonzero(lv >= 2)),
            "bans": self.bans,
            "shed_qos0": self.shed_count,
            "tick_s": self.tick_s,
            "threshold": self.threshold,
        }

    # ------------------------------------------------------------------

    def attach(self, broker: Any) -> "Admission":
        """Publish the enforcement handle + register the lifecycle
        hooks (the lazily-registered idiom: hooks exist only while the
        plane is enabled, so the flag-off tree never dispatches them)."""
        broker.admission = self
        broker.hooks.add(
            "client.connected",
            lambda cid, info: self.note_connect(
                cid, (info or {}).get("peerhost")
                if isinstance(info, dict) else None),
            name="admission.connect",
        )
        broker.hooks.add(
            "client.disconnected",
            lambda cid, reason: self.note_disconnect(cid),
            name="admission.disconnect",
        )
        return self
