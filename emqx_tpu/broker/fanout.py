"""Batched publish→deliver fanout pipeline — the broker-side analog of
the kernel's micro-batching.

The device matcher sustains ~428k topics/s, but the per-message publish
path (``Broker.publish`` → ``_dispatch`` → ``_deliver_to`` →
``Session.deliver`` → ``emit``) walks 6+ Python frames *per subscriber
per message*, which caps broker e2e throughput two orders of magnitude
below the kernel (BENCH_r05 ``config1_broker_e2e`` vs ``tpu.topics_per_s``
— exactly the broker-side processing overhead MQTT+ (arXiv:1810.00773)
measures as dominant in enhanced brokers).  This pipeline amortizes that
walk over micro-batches:

* the channel **offers** hot-path publishes here (acks immediately —
  PUBACK means "broker took responsibility", not "delivered", so this is
  spec-faithful) and falls back to the per-message ``Broker.publish``
  whenever the pipeline refuses (disabled, low-rate bypass, overload);
* a drain loop collects up to ``max_batch`` messages per deadline
  window and resolves **all** routes for the batch in one
  :meth:`MatchService.prefetch_many` call — one kernel dispatch instead
  of one hint lookup per message — with the host trie serving per unique
  topic (not per message) on fallback;
* deliveries are grouped ``session → [messages]`` so ``Session.deliver``
  runs once per session per batch with amortized ``Publish``
  construction, sharing one zero-copy :class:`Message` (payload and all)
  across subscribers whenever no per-subscription transform applies;
* per-client sends flush in bulk: ONE ``emit``/``outbox_put`` per client
  per batch instead of one per message;
* shared-subscription routes batch per ``(group, filter)`` slice
  through :meth:`SharedSub.pick_batch` + ``Broker._dispatch_shared_batch``
  — ONE strategy call assigns members for the whole slice, producing
  the identical pick sequence (round-robin, sticky, ...) the
  per-message path would, with ack-aware per-message redispatch only
  when a picked member nacks.

**Shape-aware gate** (``shape_routes``): the chunk delivery stage feeds
an EWMA of observed fan-out legs per message back to ``offer()``.  When
the workload is ~1:1 (paired clients, no fan-out to amortize) the offer
refuses while idle — the per-message path with instant synchronous
delivery is as fast or faster there — and a probe message is admitted
every ``shape_probe_s`` so the estimate tracks workload changes.

**Adaptive serve-batch sizing** (BENCH_r05: batch 2048 → p99 105 ms vs
398 ms at 8192 at similar capacity): the batch bound follows the
observed arrival rate — a batch covers at most ``adapt_window_s`` of
arrivals, capped at ``max_batch`` — using the same windowed-rate
estimator as ``MatchService``'s adaptive bypass.  Below ``bypass_rate``
msg/s the pipeline refuses outright and the per-message path serves, so
single-client latency never pays the batching window.

Ordering per (client, topic) is preserved: the queue is FIFO, batches
process in order, and per-session grouping appends in message order.
The low-rate bypass only engages while the queue is empty and no batch
is in flight, so a bypassed message can never overtake a queued one.
The only exception is queue overload (``queue_cap``): refusal there
hands messages to the sync path ahead of the backlog — survival over
ordering, counted in ``broker.fanout.overflow``.

**Supervision + overload** (PR 3): with a :class:`~emqx_tpu.supervise.
Supervisor` attached, the drain loop runs as a permanent child — a
crash or injected kill restarts it (backoff + restart-intensity
escalation) instead of silently stopping delivery, and an un-drained
queue re-publishes through the sync path on supervised shutdown.  With
an :class:`~emqx_tpu.broker.olp.Olp` attached, sustained overload sheds
per policy at ``offer()``: QoS0 drops first (``broker.olp.shed_qos0``),
retained/delayed publishes defer until the overload clears
(``broker.olp.deferred``), QoS1/2 keep riding the inflight-window
backpressure — queues never grow unboundedly.

Fault containment: an accepted publish is never lost.  A raising
publish hook, route-planning failure, or delivery/emit callback error
falls back to the per-message path for the affected messages (fold-
skipping via ``Broker.publish_folded`` once the ``message.publish``
fold has run, so retainer/delayed/rewrite side effects never fire
twice) and the drain loop stays alive.  On ``stop()``, a batch
cancelled at an await point re-queues its unprocessed remainder so the
shutdown drain republishes it in order.  The delivery-stage fallback is
at-least-once: a leg already delivered before the error may duplicate.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from .. import faultinject as _fi
from .. import topic as T
from ..observe.flightrec import STAGES as _FR_STAGES
from .broker import DeliverResult
from .message import Message

log = logging.getLogger(__name__)

__all__ = ["FanoutPipeline"]

# packed flight-recorder stage ids (observe/flightrec.py STAGES)
_SID_QUEUE = _FR_STAGES.index("fanout_queue")
_SID_DELIVER = _FR_STAGES.index("deliver")
_SID_FLUSH = _FR_STAGES.index("flush")


class FanoutPipeline:
    def __init__(
        self,
        broker: Any,
        metrics: Any = None,
        match_service: Any = None,
        max_batch: int = 2048,
        min_batch: int = 8,
        window_s: float = 0.0005,
        adapt_window_s: float = 0.05,
        bypass_rate: float = 0.0,
        queue_cap: int = 65536,
        shape_routes: float = 0.0,
        shape_probe_s: float = 0.25,
        supervisor: Any = None,
        olp: Any = None,
        deferred_cap: int = 4096,
        hists: Any = None,
        e2e_per_leg_sample: int = 0,
        flightrec: Any = None,
    ) -> None:
        self.broker = broker
        self.metrics = metrics
        self.match_service = match_service
        self.supervisor = supervisor
        self.olp = olp
        self.deferred_cap = deferred_cap
        self.max_batch = max_batch
        self.min_batch = min_batch
        self.window_s = window_s
        self.adapt_window_s = adapt_window_s
        self.bypass_rate = bypass_rate
        self.queue_cap = queue_cap
        self.shape_routes = shape_routes
        self.shape_probe_s = shape_probe_s

        self._q: Deque[Message] = deque()
        # sender → count of their messages currently in pipeline
        # custody (queued, deferred, or mid-batch).  MQTT's ordering
        # guarantee is per publisher connection per topic, so a message
        # whose SENDER has nothing in flight can safely bypass to the
        # synchronous path even while other senders' messages are
        # queued — the key that lets the shape gate keep working under
        # sustained ~1:1 load (config1) instead of only while idle.
        self._pending_senders: Dict[Any, int] = {}
        # overload-deferred retained/delayed publishes: parked while the
        # Olp reports overload, re-queued when it clears (shed policy:
        # QoS0 drops first, retained/delayed defer, QoS1/2 ride the
        # window backpressure)
        self._deferred: Deque[Message] = deque()
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._child = None           # supervise.Child when supervised
        self._running = False
        self._busy = False  # a batch is mid-flight (prefetch await point)
        # arrival-rate window (mirrors MatchService._note_arrival)
        self._win_start = time.monotonic()
        self._win_count = 0
        self._last_rate = 0.0
        # shape gate state: EWMA of observed fan-out legs per message
        # (None until the first batch is measured) and the next probe
        # deadline that keeps the estimate fresh while bypassing
        self._avg_routes: Optional[float] = None
        self._shape_probe_at = 0.0
        # lifetime accounting (also mirrored into metrics when attached)
        self.batches = 0
        self.msgs = 0
        # stage-level latency observatory (observe/hist.py): direct
        # histogram references, None = zero-call recording sites.  All
        # four are written by the drain loop (main plane, one writer).
        self.hists = hists
        self._h_queue = self._h_deliver = None
        self._h_flush = self._h_e2e = self._h_e2e_leg = None
        # per-leg e2e sampling knob (obs.hist.e2e_per_leg_sample):
        # 0 = off (the leg histogram's recording site is zero-call,
        # spy-asserted), N = record every Nth delivery leg — the
        # per-subscriber skew signal without the per-delivery cost
        self.e2e_per_leg_sample = int(e2e_per_leg_sample)
        self._leg_ctr = 0
        if hists is not None:
            self._h_queue = hists.hist("obs.stage.fanout_queue")
            self._h_deliver = hists.hist("obs.stage.deliver")
            self._h_flush = hists.hist("obs.stage.flush")
            self._h_e2e = hists.hist("obs.e2e.publish_deliver")
            if self.e2e_per_leg_sample > 0:
                self._h_e2e_leg = hists.hist("obs.e2e.publish_deliver_leg")
        # queue-head arrival stamp for the fanout_queue span: set when
        # a message lands in an EMPTY queue, re-armed at each batch pop
        # — per-batch oldest-wait without a parallel timestamp deque
        # (deferred re-queues and cancel-requeues stay approximate)
        self._q_head_ns = 0
        self.flightrec = flightrec
        self._ring = (flightrec.ring("fanout")
                      if flightrec is not None else None)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        if self.supervisor is not None:
            # supervised: a crashed/killed drain loop restarts per
            # policy instead of silently stopping delivery; the drain
            # callback preserves the "accepted publishes never drop"
            # guarantee if the SUPERVISOR stops us (node shutdown)
            self._child = self.supervisor.start_child(
                "broker.fanout", self._run, restart="permanent",
                drain=self._drain_queue)
        else:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        """Stop draining; leftover queued messages take the per-message
        correctness path so shutdown never loses accepted publishes."""
        self._running = False
        if self._child is not None:
            await self._child.stop()   # runs _drain_queue after the task
            self._child = None
            return
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                log.debug("fanout drain task exit", exc_info=True)
            self._task = None
        self._drain_queue()

    def _drain_queue(self) -> None:
        """Republish everything still queued (and overload-deferred)
        through the synchronous per-message path.  Idempotent."""
        while self._deferred:
            self._q.append(self._deferred.popleft())
        while self._q:
            msg = self._q.popleft()
            self._untrack([msg])
            try:
                self.broker.publish(msg)
            except Exception:
                log.exception("fanout drain-on-stop publish failed")

    # ------------------------------------------------------------------
    # ingress
    # ------------------------------------------------------------------

    def _note_arrival(self) -> None:
        now = time.monotonic()
        dt = now - self._win_start
        if dt >= 0.05:
            self._last_rate = self._win_count / dt
            self._win_start = now
            self._win_count = 0
        self._win_count += 1

    def offer(self, msg: Message) -> bool:
        """Accept ``msg`` for batched fanout.  False → the caller must
        deliver via the per-message path (``Broker.publish``)."""
        if not self._running:
            return False
        T.validate(msg.topic, "name")  # parity with Broker.publish
        adm = self.broker.admission
        if adm is not None and msg.qos == 0 \
                and adm.shed_qos0(msg.sender):
            # admission quarantine (broker/admission.py): the batched
            # twin of the Broker.publish shed — consumed by policy,
            # never queued, mirroring the olp QoS0 shed below
            self.broker.hooks.run("message.dropped",
                                  (msg, "admission_shed"))
            return True
        self._note_arrival()
        olp = self.olp
        if olp is not None and olp.overloaded():
            # sustained overload (emqx_olp policy): shed QoS0 first,
            # defer retained/delayed, and let QoS1/2 ride the normal
            # queue — their backpressure is the inflight window
            # (InflightFullError → mqueue) rather than queue growth.
            if msg.retain or msg.topic.startswith("$delayed/"):
                if len(self._deferred) < self.deferred_cap:
                    self._deferred.append(msg)
                    self._track(msg)
                    if self.metrics is not None:
                        self.metrics.inc("broker.olp.deferred")
                    return True
                return False  # deferral full: sync path decides
            if msg.qos == 0:
                if self.metrics is not None:
                    self.metrics.inc("broker.olp.shed_qos0")
                self.broker.hooks.run("message.dropped", (msg, "olp_shed"))
                return True   # consumed: dropped by policy, not queued
        if len(self._q) >= self.queue_cap:
            # overload: shed to the sync path rather than grow unbounded
            if self.metrics is not None:
                self.metrics.inc("broker.fanout.overflow")
            return False
        if (
            self.bypass_rate > 0
            and not self._q
            and not self._busy
            and self._last_rate < self.bypass_rate
        ):
            # single-digit-rate publisher: the batching window would cost
            # more latency than it amortizes (same logic as the match
            # service's device bypass).  Safe for ordering: nothing is
            # queued or in flight that this message could overtake.
            if self.metrics is not None:
                self.metrics.inc("broker.fanout.bypass")
            return False
        if (
            self.shape_routes > 0
            and self._avg_routes is not None
            and self._avg_routes <= self.shape_routes
            and msg.sender not in self._pending_senders
        ):
            # shape gate: batching amortizes per-message cost across
            # fan-out legs; on ~1:1 paired-client shapes there is
            # nothing to amortize and the per-message path's instant
            # synchronous delivery wins.  Safe whenever this SENDER has
            # nothing in pipeline custody — MQTT orders per publisher
            # per topic, so other senders' queued messages cannot be
            # overtaken in any way the spec (or a subscriber) can
            # observe.  A probe message is still admitted every
            # shape_probe_s so the estimate notices when the workload
            # grows fan-out again.
            now2 = time.monotonic()
            if now2 >= self._shape_probe_at:
                self._shape_probe_at = now2 + self.shape_probe_s
            else:
                if self.metrics is not None:
                    self.metrics.inc("broker.fanout.shape_bypass")
                return False
        if self._h_queue is not None and not self._q:
            self._q_head_ns = time.perf_counter_ns()
        self._q.append(msg)
        self._track(msg)
        self._wake.set()
        return True

    def _track(self, msg: Message) -> None:
        d = self._pending_senders
        s = msg.sender
        d[s] = d.get(s, 0) + 1

    def _untrack(self, msgs: List[Message]) -> None:
        d = self._pending_senders
        for m in msgs:
            s = m.sender
            v = d.get(s)
            if v is not None:
                if v <= 1:
                    del d[s]
                else:
                    d[s] = v - 1

    def will_accept(self, headroom: int = 1) -> bool:
        """Side-effect-free preflight of :meth:`offer` for the
        publish-run ingest fast path: True only when the next
        ``headroom`` QoS1/2 offers are GUARANTEED to be accepted (and
        none would consume gate state like the shape probe).  False in
        every ambiguous case, so a bailing caller reproduces the
        per-message path byte-for-byte.  Only valid from the pipeline's
        own loop with no awaits between the check and the offers."""
        if not self._running:
            return False
        if self.olp is not None and self.olp.overloaded():
            return False
        if len(self._q) + headroom > self.queue_cap:
            return False
        idle = not self._q and not self._busy
        if self.bypass_rate > 0 and idle \
                and self._last_rate < self.bypass_rate:
            return False
        if self.shape_routes > 0 \
                and self._avg_routes is not None \
                and self._avg_routes <= self.shape_routes:
            # the shape gate may bypass per-sender at any queue depth
            return False
        return True

    def _batch_bound(self) -> int:
        """Arrival-rate-adaptive batch bound: one batch covers at most
        ``adapt_window_s`` of offered traffic, so flush time (and with it
        delivery p99) tracks load instead of the static cap."""
        by_rate = int(self._last_rate * self.adapt_window_s)
        return max(self.min_batch, min(self.max_batch, by_rate))

    # ------------------------------------------------------------------
    # drain loop
    # ------------------------------------------------------------------

    async def _run(self) -> None:
        if self._q or self._deferred:
            # supervisor restart mid-backlog: the previous run's wake
            # may have been consumed — never stall on a non-empty queue
            self._wake.set()
        while True:
            await self._wake.wait()
            self._wake.clear()
            if _fi._injector is not None:
                # chaos seam: BEFORE the batch pops, so a raise kills
                # the drain task without stranding popped messages
                act = _fi._injector.act("fanout.drain")
                if act == "raise":
                    raise _fi.InjectedFault("fanout.drain")
                if act == "delay":
                    await _fi._injector.pause()
            if self.olp is not None:
                self.olp.report(queue_depth=len(self._q))
                if self._deferred and not self.olp.overloaded():
                    # overload cleared: deferred retained/delayed
                    # publishes rejoin the batch queue
                    while self._deferred and len(self._q) < self.queue_cap:
                        self._q.append(self._deferred.popleft())
            if not self._q:
                continue
            if self.window_s > 0:
                # deadline batching: let concurrent publishes pile in
                await asyncio.sleep(self.window_s)
            bound = self._batch_bound()
            n = min(len(self._q), bound)
            popleft = self._q.popleft
            batch = [popleft() for _ in range(n)]
            if self._h_queue is not None:
                # fanout_queue span: oldest queue wait for this batch
                # (head stamp → pop), re-armed for the remaining queue
                now_ns = time.perf_counter_ns()
                head = self._q_head_ns
                if head:
                    self._h_queue.record(now_ns - head)
                    if self._ring is not None:
                        self._ring.push(_SID_QUEUE, head,
                                        now_ns - head, n)
                self._q_head_ns = now_ns if self._q else 0
            if self._q:
                self._wake.set()
            self._busy = True
            t0 = time.perf_counter()
            try:
                await self._process(batch)
            except asyncio.CancelledError:
                raise
            except Exception:
                # belt-and-braces: _process guards each stage itself, but
                # a bug here must never kill the drain task — offer()
                # would keep accepting (and the channel PUBACK-ing)
                # publishes that are never delivered
                log.exception("fanout batch processing failed")
                if self.metrics is not None:
                    self.metrics.inc("broker.fanout.errors")
            finally:
                self._busy = False
            if self.metrics is not None:
                m = self.metrics
                m.inc("broker.fanout.batches")
                m.inc("broker.fanout.msgs", n)
                m.set("broker.fanout.batch_size", n)
                m.set("broker.fanout.depth", len(self._q))
                m.inc(
                    "broker.fanout.flush_us",
                    int((time.perf_counter() - t0) * 1e6),
                )
            self.batches += 1
            self.msgs += n
            if self._deferred and (
                    self.olp is None or not self.olp.overloaded()):
                self._wake.set()   # re-queue deferred next iteration

    # loop-fairness bound: at most this many messages fan out per
    # synchronous stretch; between chunks the drain loop yields so
    # connection IO (reads, acks, other sessions' writes) keeps flowing
    # under large batches.  Grouping amortization saturates well below
    # this, so the chunking costs ~nothing.
    CHUNK = 256

    async def _process(self, batch: List[Message]) -> None:
        done = 0
        try:
            # batch-resolve device hints up front: ONE prefetch_many
            # kernel dispatch covers every unique topic in the batch, so
            # stage 2's device_match serves from fresh hints instead of
            # one per-publish prefetch (bounded by the service's
            # prefetch_timeout_s; failure → host trie serves)
            if self.match_service is not None:
                try:
                    # {topic: max qos} — the mapping iterates as the
                    # topic set AND carries the QoS the deadline serve
                    # plane's brownout stage-2 shed keys on
                    topic_qos: Dict[str, int] = {}
                    for m in batch:
                        q = topic_qos.get(m.topic)
                        if q is None or m.qos > q:
                            topic_qos[m.topic] = m.qos
                    await self.match_service.prefetch_many(topic_qos)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception(
                        "fanout prefetch_many failed (host trie serves)")
            for i in range(0, len(batch), self.CHUNK):
                self._process_chunk(batch[i:i + self.CHUNK])
                done = i + self.CHUNK
                if done < len(batch):
                    await asyncio.sleep(0)
        except asyncio.CancelledError:
            # stop() cancelled us at an await point.  Chunks are
            # synchronous, so everything from `done` on is untouched —
            # hand it back to the queue front (order preserved) for
            # stop()'s drain, honoring "accepted publishes never drop"
            self._q.extendleft(reversed(batch[done:]))
            raise

    def _plan_routes(self, topics) -> Dict[str, list]:
        broker = self.broker
        routes_of: Dict[str, list] = {}
        device_match = broker.device_match
        match_routes = broker.router.match_routes
        for t in topics:
            routes = device_match(t) if device_match is not None else None
            routes_of[t] = routes if routes is not None else match_routes(t)
        return routes_of

    def _fallback(self, msgs: List[Message], folded: bool) -> None:
        """Per-message fallback for a failed pipeline stage.  ``folded``
        selects ``publish_folded`` so messages whose ``message.publish``
        fold already ran don't fire retainer/delayed/rewrite twice."""
        broker = self.broker
        if self.metrics is not None:
            self.metrics.inc("broker.fanout.fallback", len(msgs))
        publish = broker.publish_folded if folded else broker.publish
        for m in msgs:
            try:
                publish(m)
            except Exception:
                log.exception("fanout fallback publish failed")

    def _process_chunk(self, batch: List[Message]) -> None:
        try:
            self._process_chunk_inner(batch)
        finally:
            # the chunk left pipeline custody (delivered, dropped or
            # fallen back) — its senders may shape-bypass again
            self._untrack(batch)

    def _process_chunk_inner(self, batch: List[Message]) -> None:
        broker = self.broker
        hooks = broker.hooks
        # -- stage 1: publish hooks (retainer/rewrite/delayed ride this
        # fold) — per message, identical to Broker.publish.  A raising
        # hook sends THAT message down the sync path (its fold re-runs,
        # same exposure as any sync retry); the rest stay batched.
        msgs: List[Message] = []
        for msg in batch:
            try:
                m = hooks.run_fold("message.publish", (), msg)
            except Exception:
                log.exception("publish fold failed; message falls back "
                              "to the per-message path")
                self._fallback([msg], folded=False)
                continue
            if m is None or m.headers.get("allow_publish") is False:
                continue
            msgs.append(m)
        if not msgs:
            return
        # -- stage 2: route resolution once per UNIQUE topic (device
        # hints parked by prefetch_many serve here; host trie
        # otherwise), not once per message.  Nothing is delivered yet
        # and every fold already ran, so failure falls back fold-skipping
        # per message — no duplicates, no double hook side effects.
        try:
            routes_of = self._plan_routes({m.topic for m in msgs})
        except Exception:
            log.exception("fanout planning failed; chunk falls back to "
                          "the per-message path")
            self._fallback(msgs, folded=True)
            return
        try:
            self._deliver_chunk(msgs, routes_of)
        except Exception:
            # stages 3–5 touch callbacks the broker doesn't guard
            # (session.deliver, shared picks, delivered/dropped taps,
            # emit).  Partial delivery may have happened, so the
            # fold-skipping re-dispatch can duplicate a leg (at-least-
            # once on this error path) — but accepted publishes are
            # never lost and the drain loop survives.
            log.exception("fanout delivery failed; chunk falls back to "
                          "the per-message path")
            self._fallback(msgs, folded=True)

    def _deliver_chunk(self, msgs: List[Message], routes_of: Dict[str, list]) -> None:
        broker = self.broker
        hooks = broker.hooks
        # -- stage 3: group (session → [messages]) and ($share group →
        # [messages]); cluster forwards keep per-message semantics
        plan: Dict[str, List[Message]] = {}
        shared_slices: Dict[Any, List[Message]] = {}  # (group, flt) → msgs
        fwd_legs = 0
        res = DeliverResult()  # shared-path sends + accounting
        effective = broker._effective
        subscribers = broker.subscribers
        node = broker.node
        for m in msgs:
            routes = routes_of[m.topic]
            if not routes:
                hooks.run("message.dropped", (m, "no_subscribers"))
                continue
            seen_shared = None
            for flt, dest in routes:
                if isinstance(dest, tuple):  # (group, node) shared route
                    group, _node = dest
                    if seen_shared is None:
                        seen_shared = set()
                    elif (group, flt) in seen_shared:
                        continue
                    seen_shared.add((group, flt))
                    bucket = shared_slices.get((group, flt))
                    if bucket is None:
                        bucket = shared_slices[(group, flt)] = []
                    bucket.append(m)
                elif dest == node:
                    sender = m.sender
                    eff_cache: Dict[Any, Message] = {}
                    for clientid, opts in subscribers.get(flt, {}).items():
                        if opts.nl and sender == clientid:
                            continue  # MQTT5 No-Local
                        # subscribers sharing identical SubOpts (the
                        # normal fan-out) share ONE effective message —
                        # one clone per distinct transform, not per leg
                        eff = eff_cache.get(opts)
                        if eff is None:
                            eff = eff_cache[opts] = effective(m, opts)
                        bucket = plan.get(clientid)
                        if bucket is None:
                            bucket = plan[clientid] = []
                        bucket.append(eff)
                elif broker.on_forward is not None:
                    if broker.on_forward(dest, flt, m):
                        res.matched += 1
                        fwd_legs += 1
        # -- stage 3.5: batched shared dispatch — ONE pick_batch per
        # ($share group, filter) covers its whole batch slice, with
        # per-message ack-aware redispatch only on nack
        for (group, flt), ms in shared_slices.items():
            broker._dispatch_shared_batch(group, flt, ms, res)
        # shape signal for the offer() gate: observed fan-out legs per
        # message this chunk (EWMA)
        self._note_shape(
            len(msgs),
            sum(len(b) for b in plan.values())
            + sum(len(b) for b in shared_slices.values())
            + fwd_legs,
        )
        # -- stage 4: one Session.deliver per session per batch
        out = res.publishes
        sessions = broker.sessions
        delivered_taps = hooks.has("message.delivered")
        bmetrics = broker.metrics
        h_e2e = self._h_e2e
        h_leg = self._h_e2e_leg
        t4 = time.perf_counter_ns() if self._h_deliver is not None else 0
        now_wall = (time.time()
                    if h_e2e is not None or h_leg is not None else 0.0)
        for clientid, effs in plan.items():
            sess = sessions.get(clientid)
            if sess is None:
                continue
            mu = sess.mutex
            if mu is None:
                sends, dropped = sess.deliver(effs)
            else:
                # shard-owned session (transport/shards.py): exclude
                # the owning shard loop's ack handling for the window
                # admission
                with mu:
                    sends, dropped = sess.deliver(effs)
            if sends:
                n_sends = len(sends)
                res.matched += n_sends
                if bmetrics is not None:
                    bmetrics.inc("messages.delivered", n_sends)
                if h_e2e is not None:
                    # publish→deliver e2e, SAMPLED once per session per
                    # chunk on the oldest leg (the legs of one deliver
                    # share a batch window, so per-leg recording would
                    # pay per-message cost for sub-window resolution);
                    # SlowSubs records per leg when enabled
                    h_e2e.record_s(now_wall - sends[0].msg.timestamp)
                if h_leg is not None:
                    # per-LEG variant, every Nth leg across chunks (the
                    # counter persists, so skewed fan-outs can't dodge
                    # the sampler by staying under N legs per session)
                    step = self.e2e_per_leg_sample
                    for p in sends:
                        self._leg_ctr += 1
                        if self._leg_ctr >= step:
                            self._leg_ctr = 0
                            h_leg.record_s(now_wall - p.msg.timestamp)
                bucket = out.get(clientid)
                if bucket is None:
                    out[clientid] = sends
                else:
                    bucket.extend(sends)
                if delivered_taps:
                    for p in sends:
                        hooks.run("message.delivered", (clientid, p.msg))
            for d in dropped:
                hooks.run("message.dropped", (d, "queue_full"))
        # -- stage 5: bulk flush — ONE emit per client per batch
        t5 = time.perf_counter_ns() if self._h_deliver is not None else 0
        emit = broker.emit
        for clientid, pubs in out.items():
            emit(clientid, pubs)
        if self._h_deliver is not None:
            t6 = time.perf_counter_ns()
            self._h_deliver.record(t5 - t4)
            self._h_flush.record(t6 - t5)
            if self._ring is not None:
                self._ring.push(_SID_DELIVER, t4, t5 - t4, len(msgs))
                self._ring.push(_SID_FLUSH, t5, t6 - t5, len(out))

    # ------------------------------------------------------------------

    def _note_shape(self, n_msgs: int, n_legs: int) -> None:
        if n_msgs <= 0:
            return
        r = n_legs / n_msgs
        a = self._avg_routes
        self._avg_routes = r if a is None else a * 0.8 + r * 0.2

    def depth(self) -> int:
        return len(self._q)

    def info(self) -> Dict[str, Any]:
        return {
            "running": self._running,
            "depth": len(self._q),
            "deferred": len(self._deferred),
            "batches": self.batches,
            "msgs": self.msgs,
            "batch_bound": self._batch_bound(),
            "last_rate": round(self._last_rate, 1),
            "avg_routes": (round(self._avg_routes, 2)
                           if self._avg_routes is not None else None),
        }
