"""Shared-subscription ($share/<group>/...) dispatch strategies.

Behavioral reference: ``apps/emqx/src/emqx_shared_sub.erl`` [U]
(SURVEY.md §2.1): per-(group, filter) member registry with pluggable
pick strategies — ``random``, ``round_robin``, ``sticky``,
``hash_clientid``, ``hash_topic``, ``local`` — plus ack-aware redispatch:
when a picked member nacks (session gone / inflight full with
drop-policy), the message is redispatched to another member.
"""

from __future__ import annotations

import hashlib
import random
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["SharedSub", "STRATEGIES"]

STRATEGIES = (
    "random", "round_robin", "sticky", "hash_clientid", "hash_topic", "local",
)


def _hash(s: str) -> int:
    return int.from_bytes(hashlib.blake2s(s.encode(), digest_size=8).digest(), "big")


class SharedSub:
    def __init__(self, strategy: str = "random", seed: Optional[int] = None) -> None:
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown strategy {strategy!r}")
        self.strategy = strategy
        self._rng = random.Random(seed)
        # (group, filter) -> ordered member list of (clientid, node)
        self._members: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        self._rr: Dict[Tuple[str, str], int] = {}
        self._sticky: Dict[Tuple[str, str], Tuple[str, str]] = {}

    # ------------------------------------------------------------------

    def subscribe(self, group: str, flt: str, clientid: str, node: str = "local") -> bool:
        key = (group, flt)
        members = self._members.setdefault(key, [])
        ent = (clientid, node)
        if ent in members:
            return False
        members.append(ent)
        return True

    def unsubscribe(self, group: str, flt: str, clientid: str, node: str = "local") -> bool:
        key = (group, flt)
        members = self._members.get(key)
        if not members:
            return False
        try:
            members.remove((clientid, node))
        except ValueError:
            return False
        if not members:
            del self._members[key]
            self._rr.pop(key, None)
            self._sticky.pop(key, None)
        elif self._sticky.get(key) == (clientid, node):
            del self._sticky[key]
        return True

    def members(self, group: str, flt: str) -> List[Tuple[str, str]]:
        return list(self._members.get((group, flt), ()))

    def groups(self) -> List[Tuple[str, str]]:
        return list(self._members)

    # ------------------------------------------------------------------

    def pick(
        self,
        group: str,
        flt: str,
        topic: str,
        sender: Optional[str] = None,
        local_node: str = "local",
        exclude: Sequence[Tuple[str, str]] = (),
        extra: Sequence[Tuple[str, str]] = (),
    ) -> Optional[Tuple[str, str]]:
        """Choose the member to receive a message on ``topic``.

        ``exclude`` supports ack-aware redispatch: members that already
        nacked this delivery.  ``extra`` adds candidates not in the local
        member table — the cluster layer passes remote nodes holding
        members of this group as ``("", node)`` markers, so strategies
        balance across the whole cluster (two-level pick: the remote
        node's own shared table chooses the concrete client there)."""
        key = (group, flt)
        members: Sequence[Tuple[str, str]] = self._members.get(key, ())
        if exclude or extra:
            # redispatch / cluster candidates: build the filtered view
            members = [m for m in members if m not in exclude]
            members += [m for m in extra
                        if m not in exclude and m not in members]
        # else: serve straight off the live list — one publish picks one
        # member, so the fanout hot path never allocates here
        if not members:
            return None
        s = self.strategy
        if s == "local":
            locals_ = [m for m in members if m[1] == local_node]
            pool = locals_ or members
            return pool[self._rng.randrange(len(pool))]
        if s == "random":
            return members[self._rng.randrange(len(members))]
        if s == "round_robin":
            i = self._rr.get(key, -1)
            i = (i + 1) % len(members)
            self._rr[key] = i
            return members[i]
        if s == "sticky":
            cur = self._sticky.get(key)
            if cur is not None and cur in members:
                return cur
            choice = members[self._rng.randrange(len(members))]
            self._sticky[key] = choice
            return choice
        if s == "hash_clientid":
            h = _hash(sender or "")
            return members[h % len(members)]
        if s == "hash_topic":
            return members[_hash(topic) % len(members)]
        raise AssertionError(s)

    def pick_batch(
        self,
        group: str,
        flt: str,
        keys: Sequence[Tuple[str, Optional[str]]],
        local_node: str = "local",
    ) -> List[Optional[Tuple[str, str]]]:
        """Choose a member per ``(topic, sender)`` key in ONE call.

        The fanout pipeline hands the whole batch slice for a
        ``(group, filter)`` here instead of one :meth:`pick` per
        message: strategy state (round-robin cursor, sticky choice, RNG
        stream) advances exactly as the equivalent per-message pick
        sequence would, so batched and unbatched dispatch assign the
        same members in the same order."""
        key = (group, flt)
        members = self._members.get(key, ())
        n = len(members)
        if not n:
            return [None] * len(keys)
        s = self.strategy
        if s == "round_robin":
            i = self._rr.get(key, -1)
            out: List[Optional[Tuple[str, str]]] = []
            for _ in keys:
                i = (i + 1) % n
                out.append(members[i])
            self._rr[key] = i
            return out
        if s == "sticky":
            cur = self._sticky.get(key)
            if cur is None or cur not in members:
                cur = members[self._rng.randrange(n)]
                self._sticky[key] = cur
            return [cur] * len(keys)
        if s == "random":
            rng = self._rng
            return [members[rng.randrange(n)] for _ in keys]
        if s == "local":
            locals_ = [m for m in members if m[1] == local_node]
            pool = locals_ or members
            rng = self._rng
            return [pool[rng.randrange(len(pool))] for _ in keys]
        if s == "hash_clientid":
            return [members[_hash(sender or "") % n] for _, sender in keys]
        if s == "hash_topic":
            return [members[_hash(topic) % n] for topic, _ in keys]
        raise AssertionError(s)

    def dispatch_with_ack(
        self,
        group: str,
        flt: str,
        topic: str,
        try_deliver,
        sender: Optional[str] = None,
        local_node: str = "local",
        extra: Sequence[Tuple[str, str]] = (),
        exclude: Sequence[Tuple[str, str]] = (),
    ) -> Optional[Tuple[str, str]]:
        """Pick members until ``try_deliver(member) -> bool`` accepts.

        Mirrors the reference's redispatch-on-nack loop; returns the
        member that accepted, or None if every member nacked.
        ``exclude`` seeds the tried list — the batched dispatch passes
        the member that already nacked this delivery."""
        tried: List[Tuple[str, str]] = list(exclude)
        while True:
            m = self.pick(group, flt, topic, sender, local_node,
                          exclude=tried, extra=extra)
            if m is None:
                return None
            if try_deliver(m):
                if self.strategy == "sticky":
                    self._sticky[(group, flt)] = m
                return m
            tried.append(m)
