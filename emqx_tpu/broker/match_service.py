"""In-process device matcher for THIS broker's own publish path.

Round 1 left the TPU matcher reachable only through the external exhook
sidecar; the broker's own ``Broker.publish`` always walked the host trie
(VERDICT.md weak item 4).  This service closes that gap:

* it mirrors the :class:`~emqx_tpu.broker.router.Router`'s **wildcard**
  filters into an :class:`IncrementalNfa`/:class:`DeviceNfa` pair by
  consuming the router's delta log (``deltas_since`` — the mria
  bootstrap-then-rlog pattern; a log gap triggers a full resnapshot),
  exact filters stay in the router's O(1) hash map;
* concurrent publishes are **micro-batched**: the connection layer's
  async intercept stage awaits :meth:`prefetch`, which rides a deadline
  batching loop into ONE kernel call, and parks the answer in an
  epoch-validated hint cache;
* the synchronous ``Broker.publish`` then consumes the hint via
  :meth:`hint_routes` (``Broker.device_match``) — if the hint is stale
  (router mutated since) or absent, publish falls back to the host trie
  unchanged, so correctness never depends on the device;
* per-row kernel spills fail open to the router's own trie
  (SURVEY.md §5.3), counted in ``tpu.match.fallback_host``.

Also co-batches the **rule engine**'s FROM filters (BASELINE config 3):
rules register their topic filters here under a separate id namespace,
and matched rule ids ride the same kernel call (see ``rule_filters``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .. import topic as T
from .trie import FilterTrie

log = logging.getLogger(__name__)

__all__ = ["MatchService"]


def _bucket(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class MatchService:
    """Device-backed topic matching for the broker's hot path."""

    def __init__(
        self,
        broker: Any,
        metrics: Any = None,
        depth: int = 8,
        batch_window_s: float = 0.0002,
        max_batch: int = 4096,
        debounce_s: float = 0.05,
        active_slots: int = 16,
        max_matches: int = 32,
        hint_cap: int = 65536,
    ) -> None:
        from ..ops import IncrementalNfa
        from ..ops.device_table import DeviceNfa

        self.broker = broker
        self.router = broker.router
        self.metrics = metrics
        self.depth = depth
        self.batch_window_s = batch_window_s
        self.max_batch = max_batch
        self.debounce_s = debounce_s
        self.hint_cap = hint_cap

        self.inc = IncrementalNfa(depth=depth)
        self.dev = DeviceNfa(
            self.inc, active_slots=active_slots, max_matches=max_matches,
            lazy=True,
        )
        self._ref: Dict[str, int] = {}     # wildcard filter -> route count
        self._deep: Dict[str, int] = {}    # too-deep filter -> alias aid
        self._deep_trie = FilterTrie()     # host match for too-deep filters
        self._rule_aid: Dict[str, int] = {}   # rule FROM filter -> alias? no:
        # rule filters compile as REAL NFA filters tagged by aid; a filter
        # used by both routing and rules shares one aid.  Maps aid->sets:
        self._aid_rules: Dict[int, Set[str]] = {}   # aid -> rule ids
        self._rule_refs: Dict[str, Dict[str, int]] = {}  # rule_id -> {flt: 1}
        self._routing_aids: Set[int] = set()

        self.ready = False
        self._seen_epoch = 0               # router delta-log position
        self._dirty = asyncio.Event()
        self._pending: List[Tuple[str, asyncio.Future]] = []
        self._batch_wake = asyncio.Event()
        self._hints: Dict[str, Tuple[int, List[str], List[str]]] = {}
        self._tasks: List[asyncio.Task] = []
        self._running = False

        self.router.listeners.append(self._on_router_mutation)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        self._bootstrap()
        self._tasks = [
            asyncio.ensure_future(self._sync_loop()),
            asyncio.ensure_future(self._batch_loop()),
        ]
        self._dirty.set()

    async def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        try:
            self.router.listeners.remove(self._on_router_mutation)
        except ValueError:
            pass

    # ------------------------------------------------------------------
    # mirror maintenance (event loop)
    # ------------------------------------------------------------------

    def _on_router_mutation(self, epoch: int) -> None:
        self._hints.clear()  # any cached answer may now be wrong
        self._dirty.set()

    def _add(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        self._ref[flt] = n + 1
        if n == 0:
            self._table_add(flt, routing=True)

    def _del(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        if n <= 1:
            self._ref.pop(flt, None)
            if n == 1:
                self._table_del(flt, routing=True)
        else:
            self._ref[flt] = n - 1

    def _table_add(self, flt: str, routing: bool) -> None:
        try:
            self.inc.add(flt)
            aid = self.inc.aid_of(flt)
        except ValueError:
            if flt in self._deep:
                aid = self._deep[flt]
            else:
                aid = self.inc.alloc_alias(flt)
                self._deep[flt] = aid
                self._deep_trie.insert(flt)
        if routing:
            self._routing_aids.add(aid)

    def _table_del(self, flt: str, routing: bool) -> None:
        aid = self._deep.get(flt)
        if aid is None:
            aid = self.inc.aid_of(flt)
        if aid < 0:
            return
        if routing:
            self._routing_aids.discard(aid)
        if aid in self._aid_rules and self._aid_rules[aid]:
            return  # rules still reference this filter
        if flt in self._deep:
            del self._deep[flt]
            self._deep_trie.delete(flt)
            self.inc.free_alias(aid)
        else:
            self.inc.remove(flt)

    def _bootstrap(self) -> None:
        """Full resnapshot from the router (cold start / delta-log gap)."""
        self._ref = {}
        for flt in self.router.wildcard_filters():
            self._ref[flt] = 1
            if self.inc.aid_of(flt) < 0 and flt not in self._deep:
                self._table_add(flt, routing=True)
            else:
                self._routing_aids.add(
                    self._deep.get(flt, self.inc.aid_of(flt))
                )
        self._seen_epoch = self.router.epoch

    def _drain_router(self) -> None:
        deltas = self.router.deltas_since(self._seen_epoch)
        if deltas is None:
            log.info("router delta log gap: full mirror resnapshot")
            # drop filters no longer routed, then re-add from scratch
            for flt in list(self._ref):
                self._table_del(flt, routing=True)
            self._bootstrap()
            return
        for d in deltas:
            if not T.wildcard(d.filter):
                continue  # exact filters stay in the router's hash map
            if d.op == "add":
                self._add(d.filter)
            else:
                self._del(d.filter)
        self._seen_epoch = self.router.epoch

    async def _sync_loop(self) -> None:
        while True:
            await self._dirty.wait()
            await asyncio.sleep(self.debounce_s)
            self._dirty.clear()
            try:
                first = not self.ready
                self._drain_router()
                pending = self.dev.drain(full=first)
                await asyncio.to_thread(self.dev.apply_pending, pending)
                self.ready = True
                if self.metrics is not None:
                    self.metrics.inc("tpu.mirror.refresh")
                    if pending.full is not None:
                        self.metrics.inc("tpu.mirror.recompile")
                    elif pending.delta is not None and not pending.delta.empty:
                        self.metrics.inc("tpu.mirror.delta_applied")
                if first or pending.full is not None:
                    await asyncio.to_thread(self._warm)
            except Exception:
                log.exception("match-service sync failed; host path serves")
                await asyncio.sleep(1.0)
                self._dirty.set()

    def _warm(self) -> None:
        from ..ops import encode_batch

        words, lens, is_sys = encode_batch(self.inc, [], batch=64)
        self.dev.match(words, lens, is_sys)

    # ------------------------------------------------------------------
    # rule-engine co-batching (BASELINE config 3)
    # ------------------------------------------------------------------

    def register_rule(self, rule_id: str, from_filters: List[str]) -> None:
        """Co-batch a rule's FROM filters into the device table."""
        self.unregister_rule(rule_id)
        refs: Dict[str, int] = {}
        for flt in from_filters:
            refs[flt] = 1
            self._table_add(flt, routing=False)
            aid = self._deep.get(flt, self.inc.aid_of(flt))
            self._aid_rules.setdefault(aid, set()).add(rule_id)
        self._rule_refs[rule_id] = refs
        self._hints.clear()
        self._dirty.set()

    def unregister_rule(self, rule_id: str) -> None:
        refs = self._rule_refs.pop(rule_id, None)
        if not refs:
            return
        for flt in refs:
            aid = self._deep.get(flt, self.inc.aid_of(flt))
            rules = self._aid_rules.get(aid)
            if rules is not None:
                rules.discard(rule_id)
                if not rules:
                    del self._aid_rules[aid]
            # drop the filter from the table unless routing still needs it
            if aid not in self._routing_aids and aid not in self._aid_rules:
                if flt in self._deep:
                    del self._deep[flt]
                    self._deep_trie.delete(flt)
                    self.inc.free_alias(aid)
                else:
                    self.inc.remove(flt)
        self._hints.clear()
        self._dirty.set()

    # ------------------------------------------------------------------
    # serving
    # ------------------------------------------------------------------

    def _usable(self) -> bool:
        return (
            self.ready
            and self._seen_epoch == self.router.epoch
            and self.dev.epoch == self.inc.epoch
        )

    async def prefetch(self, topic: str) -> None:
        """Async stage (connection intercept): micro-batch this topic
        through the kernel and park the answer in the hint cache."""
        if not self._usable() or topic in self._hints:
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append((topic, fut))
        self._batch_wake.set()
        try:
            await fut
        except Exception:
            pass  # publish falls back to the host path

    def hint_routes(self, topic: str):
        """Sync stage (Broker.publish): fresh hint → routes, else None."""
        hint = self._hints.get(topic)
        if hint is None or hint[0] != self.router.epoch:
            return None
        return self.router.routes_with_wild(topic, hint[1])

    def hint_rules(self, topic: str) -> Optional[List[str]]:
        """Matched rule ids for a fresh hint, else None (rule engine then
        falls back to its per-rule host matching)."""
        hint = self._hints.get(topic)
        if hint is None or hint[0] != self.router.epoch:
            return None
        return hint[2]

    def _deep_ids(self, topic: str) -> List[int]:
        if not self._deep:
            return []
        return [self._deep[f] for f in self._deep_trie.match(topic)]

    def _host_ids(self, topic: str) -> List[int]:
        return self.inc.match_host(topic) + self._deep_ids(topic)

    def _split_row(self, row: List[int]) -> Tuple[List[str], List[str]]:
        """aid row → (routing wildcard filters, rule ids)."""
        filters: List[str] = []
        rules: Set[str] = set()
        table = self.inc.accept_filters
        for aid in row:
            if aid in self._routing_aids:
                f = table[aid]
                if f is not None:
                    filters.append(f)
            r = self._aid_rules.get(aid)
            if r:
                rules.update(r)
        return filters, sorted(rules)

    def _device_rows(self, enc, n: int):
        import jax

        res = self.dev.match(*enc)
        matches, counts, sp = jax.device_get(
            (res.matches, res.n_matches, res.spilled_rows())
        )
        rows = [matches[r, : counts[r]].tolist() for r in range(n)]
        return rows, np.flatnonzero(sp[:n]).tolist()

    async def _batch_loop(self) -> None:
        from ..ops import encode_batch

        while True:
            await self._batch_wake.wait()
            self._batch_wake.clear()
            if not self._pending:
                continue
            await asyncio.sleep(self.batch_window_s)
            pending, self._pending = self._pending[: self.max_batch], \
                self._pending[self.max_batch:]
            if self._pending:
                self._batch_wake.set()
            topics = [t for t, _ in pending]
            epoch = self.router.epoch
            try:
                if not self._usable():
                    raise RuntimeError("mirror stale")
                enc = encode_batch(
                    self.inc, topics, batch=_bucket(len(topics))
                )
                rows, spilled = await asyncio.to_thread(
                    self._device_rows, enc, len(topics)
                )
                spset = set(spilled)
                for r in spilled:
                    rows[r] = self._host_ids(topics[r])
                    if self.metrics is not None:
                        self.metrics.inc("tpu.match.fallback_host")
                if self._deep:
                    # too-deep filters live host-side; merge their hits
                    for r, t in enumerate(topics):
                        if r not in spset:
                            rows[r].extend(self._deep_ids(t))
                if self.metrics is not None:
                    self.metrics.inc("tpu.match.batches")
                    self.metrics.inc("tpu.match.topics", len(topics))
                    if spilled:
                        self.metrics.inc(
                            "tpu.match.active_overflow", len(spilled)
                        )
                if len(self._hints) + len(topics) > self.hint_cap:
                    self._hints.clear()
                for (topic, fut), row in zip(pending, rows):
                    self._hints[topic] = (epoch, *self._split_row(row))
                    if not fut.done():
                        fut.set_result(None)
            except Exception:
                log.debug("device batch failed; publishes fall back",
                          exc_info=True)
                for _, fut in pending:
                    if not fut.done():
                        fut.set_result(None)

    def info(self) -> dict:
        return {
            "ready": self.ready,
            "filters": self.inc.n_filters,
            "states": self.inc.n_states,
            "rules": len(self._rule_refs),
            "device_epoch": self.dev.epoch,
            "router_epoch": self.router.epoch,
            "uploads": self.dev.uploads,
            "delta_applies": self.dev.delta_applies,
        }
