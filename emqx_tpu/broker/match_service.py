"""In-process device matcher for THIS broker's own publish path.

Round 1 left the TPU matcher reachable only through the external exhook
sidecar; the broker's own ``Broker.publish`` always walked the host trie
(VERDICT.md weak item 4).  This service closes that gap:

* it mirrors the :class:`~emqx_tpu.broker.router.Router`'s **wildcard**
  filters into an :class:`IncrementalNfa`/:class:`DeviceNfa` pair by
  consuming the router's delta log (``deltas_since`` — the mria
  bootstrap-then-rlog pattern; a log gap triggers a full resnapshot),
  exact filters stay in the router's O(1) hash map;
* concurrent publishes are **micro-batched**: the connection layer's
  async intercept stage awaits :meth:`prefetch`, which rides a deadline
  batching loop into ONE kernel call, and parks the answer in an
  epoch-validated hint cache;
* the synchronous ``Broker.publish`` then consumes the hint via
  :meth:`hint_routes` (``Broker.device_match``) — if the hint can't be
  proven fresh or is absent, publish falls back to the host trie
  unchanged, so correctness never depends on the device;
* per-row kernel spills fail open to the router's own trie
  (SURVEY.md §5.3), counted in ``tpu.match.fallback_host``.

**Churn-resilient serving** (round-3 rework, VERDICT.md item 3): hints
are no longer wholesale-invalidated by router mutations.  A hint is
stamped with the router epoch its table reflected; at consume time the
router's delta log since that epoch is checked and the hint stays valid
unless a *newly added wildcard filter* matches the topic.  Deletions are
inherently safe — :meth:`Router.routes_with_wild` resolves destinations
live, so removed filters/destinations drop out of the answer without
invalidation.  The same scheme covers rule co-batching via a rule
mutation log.  Under continuous subscribe/unsubscribe churn the device
path therefore keeps serving (duty cycle asserted in
tests/test_match_service.py) instead of collapsing to the host trie.

At low publish concurrency the batching window costs more than the host
trie answers (~12 µs); an **adaptive bypass** skips the device when the
recent arrival rate is below ``bypass_rate`` so single-client latency
stays at host-path levels.

Also co-batches the **rule engine**'s FROM filters (BASELINE config 3):
rules register their topic filters here under a separate id namespace,
and matched rule ids ride the same kernel call (see ``rule_filters``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

import numpy as np

from .. import faultinject as _fi
from .. import topic as T
from .trie import FilterTrie

log = logging.getLogger(__name__)

__all__ = ["MatchService"]


def _bucket(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class MatchService:
    """Device-backed topic matching for the broker's hot path."""

    def __init__(
        self,
        broker: Any,
        metrics: Any = None,
        depth: int = 8,
        batch_window_s: float = 0.0002,
        # 2048 is the measured serving sweet spot (BENCH_r05
        # serve_device_quarter_batch: p99 105 ms vs 398 ms at 8192 at
        # similar capacity) — the default when no override is given
        max_batch: int = 2048,
        debounce_s: float = 0.05,
        active_slots: int = 16,
        max_matches: int = 32,
        hint_cap: int = 65536,
        max_stale_deltas: int = 256,
        bypass_rate: float = 0.0,
        prefetch_timeout_s: float = 0.5,
        table: str = "auto",   # auto | native | python
        short_depth: int = 4,
        split_min: int = 256,
    ) -> None:
        from ..ops import IncrementalNfa
        from ..ops.device_table import DeviceNfa

        self.broker = broker
        self.router = broker.router
        self.metrics = metrics
        self.depth = depth
        self.batch_window_s = batch_window_s
        self.max_batch = max_batch
        self.debounce_s = debounce_s
        self.hint_cap = hint_cap
        # serving tolerates up to this many un-synced router deltas; the
        # per-topic freshness proof scans at most this many log entries
        self.max_stale_deltas = max_stale_deltas
        # publishes/s below which prefetch skips the device entirely
        # (0 disables bypassing — tests pin the device path on)
        self.bypass_rate = bypass_rate
        self.prefetch_timeout_s = prefetch_timeout_s
        # depth bucketing: topics with <= short_depth levels ride a
        # shallower kernel (~40% fewer gathers on Zipf traffic); the
        # split only happens when BOTH groups clear split_min, because a
        # second kernel dispatch has a fixed cost that must amortize
        self.short_depth = short_depth
        self.split_min = split_min

        # host table: the C++ incremental NFA when available (seconds at
        # 10M filters, Python-object-free), else the Python twin —
        # identical mutation/drain surface, property-tested equivalent
        self.inc = None
        self.table_kind = "python"
        if table in ("auto", "native"):
            try:
                from ..native.nfa import NativeNfa

                self.inc = NativeNfa(depth=depth)
                self.table_kind = "native"
            except Exception:
                if table == "native":
                    raise
                log.warning(
                    "native NFA table unavailable; python table serves "
                    "(fine below ~1M filters)", exc_info=True,
                )
        if self.inc is None:
            self.inc = IncrementalNfa(depth=depth)
        self.dev = DeviceNfa(
            self.inc, active_slots=active_slots, max_matches=max_matches,
            lazy=True,
        )
        self._ref: Dict[str, int] = {}     # wildcard filter -> route count
        self._deep: Dict[str, int] = {}    # too-deep filter -> alias aid
        self._deep_trie = FilterTrie()     # host match for too-deep filters
        # rule filters compile as REAL NFA filters tagged by aid; a filter
        # used by both routing and rules shares one aid.  Maps aid->sets:
        self._aid_rules: Dict[int, Set[str]] = {}   # aid -> rule ids
        self._rule_refs: Dict[str, Dict[str, int]] = {}  # rule_id -> {flt: 1}
        self._routing_aids: Set[int] = set()

        # rule mutation log: (gen, filters-added) — unregisters append an
        # empty entry so gen coverage stays contiguous (deleted rules are
        # harmless in stale hints: the engine skips unknown ids)
        self._rule_gen = 0
        self._rule_log: Deque[Tuple[int, Tuple[str, ...]]] = deque(maxlen=512)

        self.ready = False
        self._seen_epoch = 0          # router delta-log position (drained)
        self._synced_epoch = 0        # router epoch the DEVICE table reflects
        self._synced_rule_gen = 0     # rule gen the device table reflects
        self._dirty = asyncio.Event()
        self._pending: List[Tuple[str, asyncio.Future]] = []
        self._batch_wake = asyncio.Event()
        # topic -> (router_epoch, rule_gen, wild filters, rule ids)
        self._hints: Dict[str, Tuple[int, int, List[str], List[str]]] = {}
        self._tasks: List[asyncio.Task] = []
        self._running = False
        # arrival-rate window for the adaptive bypass
        self._win_start = time.monotonic()
        self._win_count = 0
        self._last_rate = 0.0

        self.router.listeners.append(self._on_router_mutation)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        self._bootstrap()
        sup = getattr(self, "supervisor", None)
        if sup is not None:
            # supervised (node sets .supervisor before start): a crashed
            # mirror-sync or batch loop restarts instead of freezing
            # hint freshness / prefetch waiters until broker restart
            self._tasks = [
                sup.start_child("match.sync", self._sync_loop),
                sup.start_child("match.batch", self._batch_loop),
            ]
        else:
            self._tasks = [
                asyncio.ensure_future(self._sync_loop()),
                asyncio.ensure_future(self._batch_loop()),
            ]
        self._dirty.set()

    async def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        try:
            self.router.listeners.remove(self._on_router_mutation)
        except ValueError:
            pass

    # ------------------------------------------------------------------
    # mirror maintenance (event loop)
    # ------------------------------------------------------------------

    def _on_router_mutation(self, epoch: int) -> None:
        # NO hint invalidation here: freshness is proven per-topic at
        # consume time against the delta log (see _hint_fresh)
        self._dirty.set()

    def _add(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        self._ref[flt] = n + 1
        if n == 0:
            self._table_add(flt, routing=True)

    def _del(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        if n <= 1:
            self._ref.pop(flt, None)
            if n == 1:
                self._table_del(flt, routing=True)
        else:
            self._ref[flt] = n - 1

    def _table_add(self, flt: str, routing: bool) -> None:
        try:
            self.inc.add(flt)
            aid = self.inc.aid_of(flt)
        except ValueError:
            if flt in self._deep:
                aid = self._deep[flt]
            else:
                aid = self.inc.alloc_alias(flt)
                self._deep[flt] = aid
                self._deep_trie.insert(flt)
        if routing:
            self._routing_aids.add(aid)

    def _table_del(self, flt: str, routing: bool) -> None:
        aid = self._deep.get(flt)
        if aid is None:
            aid = self.inc.aid_of(flt)
        if aid < 0:
            return
        if routing:
            self._routing_aids.discard(aid)
        if aid in self._aid_rules and self._aid_rules[aid]:
            return  # rules still reference this filter
        if flt in self._deep:
            del self._deep[flt]
            self._deep_trie.delete(flt)
            self.inc.free_alias(aid)
        else:
            self.inc.remove(flt)

    def _bootstrap(self) -> None:
        """Full resnapshot from the router (cold start / delta-log gap).
        Refcounts seed from the router's live destination count — a
        filter restored with multiple routes must survive the deletion
        of all but one of them (ADVICE.md round-2 high item 1)."""
        self._ref = {}
        for flt in self.router.wildcard_filters():
            self._ref[flt] = max(1, len(self.router.routes_of(flt)))
            if self.inc.aid_of(flt) < 0 and flt not in self._deep:
                self._table_add(flt, routing=True)
            else:
                self._routing_aids.add(
                    self._deep.get(flt, self.inc.aid_of(flt))
                )
        self._seen_epoch = self.router.epoch

    def _drain_router(self) -> None:
        deltas = self.router.deltas_since(self._seen_epoch)
        if deltas is None:
            log.info("router delta log gap: full mirror resnapshot")
            # drop filters no longer routed, then re-add from scratch
            for flt in list(self._ref):
                self._table_del(flt, routing=True)
            self._bootstrap()
            return
        for d in deltas:
            if not T.wildcard(d.filter):
                continue  # exact filters stay in the router's hash map
            if d.op == "add":
                self._add(d.filter)
            else:
                self._del(d.filter)
        self._seen_epoch = self.router.epoch

    async def _sync_loop(self) -> None:
        while True:
            await self._dirty.wait()
            await asyncio.sleep(self.debounce_s)
            self._dirty.clear()
            try:
                first = not self.ready
                self._drain_router()
                # epochs the device table will reflect once this sync lands
                router_epoch = self._seen_epoch
                rule_gen = self._rule_gen
                pending = self.dev.drain(full=first)
                if pending.full is not None:
                    # a full re-upload changes table shapes ⇒ the match
                    # jit recompiles; drop readiness so publishes take the
                    # host path instead of stalling on the compile
                    # (ADVICE.md round-2 high item 2)
                    self.ready = False
                await asyncio.to_thread(self.dev.apply_pending, pending)
                if first or pending.full is not None:
                    await asyncio.to_thread(self._warm)
                self.ready = True
                self._synced_epoch = router_epoch
                self._synced_rule_gen = rule_gen
                if self.metrics is not None:
                    self.metrics.inc("tpu.mirror.refresh")
                    if pending.full is not None:
                        self.metrics.inc("tpu.mirror.recompile")
                    elif pending.delta is not None and not pending.delta.empty:
                        self.metrics.inc("tpu.mirror.delta_applied")
            except Exception:
                log.exception("match-service sync failed; host path serves")
                await asyncio.sleep(1.0)
                self._dirty.set()

    def _warm(self) -> None:
        from ..ops import encode_batch

        # flat_cap is a jit STATIC arg — warming without it would
        # compile the wrong variant and the first live batch would still
        # stall on an XLA compile
        words, lens, is_sys = encode_batch(self.inc, [], batch=64)
        self.dev.match(words, lens, is_sys,
                       flat_cap=self.FLAT_MULT * 64)
        if self.short_depth and self.short_depth < self.depth:
            # pre-pay the short-depth kernel shape too, or the first
            # split batch stalls the serving loop on an XLA compile
            w, l, sy = encode_batch(self.inc, [], batch=64,
                                    depth=self.short_depth)
            self.dev.match(w, l, sy, flat_cap=self.FLAT_MULT * 64)

    # ------------------------------------------------------------------
    # rule-engine co-batching (BASELINE config 3)
    # ------------------------------------------------------------------

    def register_rule(self, rule_id: str, from_filters: List[str]) -> None:
        """Co-batch a rule's FROM filters into the device table."""
        self.unregister_rule(rule_id)
        refs: Dict[str, int] = {}
        for flt in from_filters:
            refs[flt] = 1
            self._table_add(flt, routing=False)
            aid = self._deep.get(flt, self.inc.aid_of(flt))
            self._aid_rules.setdefault(aid, set()).add(rule_id)
        self._rule_refs[rule_id] = refs
        self._rule_gen += 1
        self._rule_log.append((self._rule_gen, tuple(from_filters)))
        self._dirty.set()

    def unregister_rule(self, rule_id: str) -> None:
        refs = self._rule_refs.pop(rule_id, None)
        if not refs:
            return
        for flt in refs:
            aid = self._deep.get(flt, self.inc.aid_of(flt))
            rules = self._aid_rules.get(aid)
            if rules is not None:
                rules.discard(rule_id)
                if not rules:
                    del self._aid_rules[aid]
            # drop the filter from the table unless routing still needs it
            if aid not in self._routing_aids and aid not in self._aid_rules:
                if flt in self._deep:
                    del self._deep[flt]
                    self._deep_trie.delete(flt)
                    self.inc.free_alias(aid)
                else:
                    self.inc.remove(flt)
        # removal-only entry: stale hints that still name the rule are
        # harmless (the engine skips ids not in its live rule map)
        self._rule_gen += 1
        self._rule_log.append((self._rule_gen, ()))
        self._dirty.set()

    # ------------------------------------------------------------------
    # serving
    # ------------------------------------------------------------------

    def _usable(self) -> bool:
        return (
            self.ready
            and self.router.epoch - self._synced_epoch <= self.max_stale_deltas
        )

    def _hint_fresh(self, topic: str, hint_epoch: int) -> bool:
        """Prove a hint still answers correctly for ``topic``.

        Deletions never need invalidation (destinations resolve live in
        ``routes_with_wild``); only a wildcard filter ADDED after the
        hint's table epoch can make the answer incomplete."""
        if hint_epoch == self.router.epoch:
            return True
        if self.router.epoch - hint_epoch > self.max_stale_deltas:
            return False  # bound the proof before materializing deltas
        deltas = self.router.deltas_since(hint_epoch)
        if deltas is None:
            return False
        for d in deltas:
            if d.op == "add" and T.wildcard(d.filter) \
                    and T.match(topic, d.filter):
                return False
        return True

    def _rules_fresh(self, topic: str, hint_gen: int) -> bool:
        """Rule-side freshness: a rule registered after the hint whose
        FROM filter matches the topic invalidates it (ADVICE.md round-2
        medium item: rule changes don't bump the router epoch)."""
        if hint_gen == self._rule_gen:
            return True
        if self._rule_log and self._rule_log[0][0] > hint_gen + 1:
            return False  # log trimmed past the hint's gen
        for gen, filters in self._rule_log:
            if gen > hint_gen and any(T.match(topic, f) for f in filters):
                return False
        return True

    def _note_arrival(self) -> None:
        now = time.monotonic()
        dt = now - self._win_start
        if dt >= 0.05:
            self._last_rate = self._win_count / dt
            self._win_start = now
            self._win_count = 0
        self._win_count += 1

    def _should_bypass(self) -> bool:
        if self.bypass_rate <= 0:
            return False
        return not self._pending and self._last_rate < self.bypass_rate

    async def prefetch(self, topic: str) -> None:
        """Async stage (connection intercept): micro-batch this topic
        through the kernel and park the answer in the hint cache.
        Bounded by ``prefetch_timeout_s`` — a stalled device (compile,
        growth re-upload) degrades to the host path, never blocks
        publishes indefinitely."""
        self._note_arrival()
        if not self._usable():
            return
        hint = self._hints.get(topic)
        if hint is not None and self._hint_fresh(topic, hint[0]) \
                and self._rules_fresh(topic, hint[1]):
            return
        if self._should_bypass():
            if self.metrics is not None:
                self.metrics.inc("tpu.match.bypass")
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append((topic, fut))
        self._batch_wake.set()
        try:
            await asyncio.wait_for(fut, self.prefetch_timeout_s)
        except Exception:
            # timeout/cancel: publish falls back to the host path
            log.debug("prefetch for %r timed out", topic, exc_info=True)

    async def prefetch_many(self, topics) -> None:
        """Batched prefetch for the fanout pipeline: every topic missing
        a fresh hint is enqueued in the SAME event-loop tick, so the
        whole set rides one batching window — one kernel call for the
        batch instead of one ``prefetch`` await per message.  Bounded by
        ``prefetch_timeout_s`` like the single-topic path."""
        if _fi._injector is not None:
            # chaos seam: a raised dispatch fault is caught by the
            # fanout pipeline (host trie serves); a delay simulates a
            # slow kernel round trip
            act = _fi._injector.act("match.dispatch")
            if act == "raise":
                raise _fi.InjectedFault("match.dispatch")
            if act == "delay":
                await _fi._injector.pause()
        if not self._usable():
            return
        waits: List[asyncio.Future] = []
        loop = asyncio.get_running_loop()
        for topic in topics:
            self._note_arrival()
            hint = self._hints.get(topic)
            if hint is not None and self._hint_fresh(topic, hint[0]) \
                    and self._rules_fresh(topic, hint[1]):
                continue
            fut = loop.create_future()
            self._pending.append((topic, fut))
            waits.append(fut)
        if not waits:
            return
        self._batch_wake.set()
        try:
            await asyncio.wait_for(
                asyncio.gather(*waits), self.prefetch_timeout_s
            )
        except Exception:
            # timeout/cancel: those topics fall back to the host trie
            log.debug("prefetch_many (%d topics) timed out", len(waits),
                      exc_info=True)

    def hint_available(self, topic: str) -> bool:
        """Non-consuming freshness peek (observability/tracing): True iff
        a device hint would serve this topic right now.  No metrics, no
        cache mutation — safe to call from taps."""
        hint = self._hints.get(topic)
        return hint is not None and self._hint_fresh(topic, hint[0])

    def hint_routes(self, topic: str):
        """Sync stage (Broker.publish): provably-fresh hint → routes,
        else None (host trie serves)."""
        hint = self._hints.get(topic)
        if hint is None:
            return None
        if not self._hint_fresh(topic, hint[0]):
            self._hints.pop(topic, None)
            if self.metrics is not None:
                self.metrics.inc("tpu.match.hint_stale")
            return None
        if self.metrics is not None:
            self.metrics.inc("tpu.match.hint_served")
        # move-to-end: a served hint is recent; eviction takes from the
        # other end of the dict (insertion order doubles as LRU order)
        self._hints[topic] = self._hints.pop(topic)
        return self.router.routes_with_wild(topic, hint[2])

    def hint_rules(self, topic: str) -> Optional[List[str]]:
        """Matched rule ids for a fresh hint, else None (rule engine then
        falls back to its per-rule host matching)."""
        hint = self._hints.get(topic)
        if hint is None:
            return None
        if not self._rules_fresh(topic, hint[1]):
            self._hints.pop(topic, None)
            if self.metrics is not None:
                self.metrics.inc("tpu.match.hint_stale")
            return None
        # a rules-only working set is just as hot as a routing one:
        # refresh LRU recency so it survives eviction (see hint_routes)
        self._hints[topic] = self._hints.pop(topic)
        return hint[3]

    def _deep_ids(self, topic: str) -> List[int]:
        if not self._deep:
            return []
        return [self._deep[f] for f in self._deep_trie.match(topic)]

    def _host_ids(self, topic: str) -> List[int]:
        return self.inc.match_host(topic) + self._deep_ids(topic)

    def _split_row(self, row: List[int]) -> Tuple[List[str], List[str]]:
        """aid row → (routing wildcard filters, rule ids)."""
        filters: List[str] = []
        rules: Set[str] = set()
        table = self.inc.accept_filters
        for aid in row:
            if aid in self._routing_aids:
                f = table[aid]
                if f is not None:
                    filters.append(f)
            r = self._aid_rules.get(aid)
            if r:
                rules.update(r)
        return filters, sorted(rules)

    # flat-output capacity per padded batch row: readback is the serving
    # bottleneck on remote-attached devices (BASELINE.md tunnel table),
    # and ~6 ids/topic covers the workload's fan-out tail
    from ..ops.match_kernel import SERVE_FLAT_MULT as FLAT_MULT

    def _device_rows(self, enc, n: int):
        B = enc[0].shape[0]
        res = self.dev.match(*enc, flat_cap=self.FLAT_MULT * B)
        return self._readback_rows(res, n, self.dev.max_matches)

    @staticmethod
    def _readback_rows(res, n: int, k: int):
        import jax

        from ..ops.match_kernel import decode_flat

        # fetch the kernel's own outputs and OR the spill flags on host:
        # res.spilled_rows() would build NEW lazy device ops here, i.e.
        # an extra dispatch round trip per batch on the readback path
        matches, counts, aover, mover = jax.device_get(
            (res.matches, res.n_matches, res.active_overflow,
             res.match_overflow)
        )
        sp = (aover > 0) | (mover > 0)
        rows = [seg.tolist()
                for seg in decode_flat(matches, counts, k)[:n]]
        return rows, np.flatnonzero(sp[:n]).tolist()

    def _device_rows_grouped(self, encs):
        """Dispatch EVERY group's kernel first (dispatch only holds the
        device lock), then read back — group 2 executes on device while
        group 1's results stream back, so a depth split costs one extra
        dispatch, not a second full round trip."""
        handles = [
            (self.dev.match(
                *enc, flat_cap=self.FLAT_MULT * enc[0].shape[0]), n)
            for enc, n in encs
        ]
        return [self._readback_rows(res, n, self.dev.max_matches)
                for res, n in handles]

    def _depth_groups(self, topics: List[str]) -> List[Tuple[List[int], int]]:
        """Partition batch indices into (indices, kernel_depth) groups.
        Kernel depth bounds TOPIC length, not filter depth, so short
        topics are exact through a shallow walk of the same table."""
        sd = self.short_depth
        everything = [(list(range(len(topics))), self.depth)]
        if not sd or sd >= self.depth:
            return everything
        short = [i for i, t in enumerate(topics) if t.count("/") < sd]
        if len(short) < self.split_min or \
                len(topics) - len(short) < self.split_min:
            return everything
        sset = set(short)
        long_ = [i for i in range(len(topics)) if i not in sset]
        return [(short, sd), (long_, self.depth)]

    async def _batch_loop(self) -> None:
        from ..ops import encode_batch

        while True:
            await self._batch_wake.wait()
            self._batch_wake.clear()
            if not self._pending:
                continue
            await asyncio.sleep(self.batch_window_s)
            pending, self._pending = self._pending[: self.max_batch], \
                self._pending[self.max_batch:]
            if self._pending:
                self._batch_wake.set()
            topics = [t for t, _ in pending]
            # the hint's provenance is the epoch the DEVICE table
            # reflects (not the live router epoch — the table may lag;
            # freshness is then proven forward from here at consume time)
            epoch = self._synced_epoch
            rule_gen = self._synced_rule_gen
            try:
                if not self._usable():
                    raise RuntimeError("mirror stale")
                # aid-reuse guard: if a freed accept id is handed out
                # again while this batch is in flight, the device rows
                # may name it under its OLD filter — translating through
                # the live accept_filters would be wrong at any epoch
                reuses0 = self.inc.aid_reuses
                groups = self._depth_groups(topics)
                encs = [
                    (encode_batch(self.inc, [topics[i] for i in idx],
                                  batch=_bucket(len(idx)), depth=d),
                     len(idx))
                    for idx, d in groups
                ]
                results = await asyncio.to_thread(
                    self._device_rows_grouped, encs
                )
                rows: List[Any] = [None] * len(topics)
                spilled: List[int] = []
                for (idx, _d), (grows, gspill) in zip(groups, results):
                    for j, i in enumerate(idx):
                        rows[i] = grows[j]
                    spilled.extend(idx[j] for j in gspill)
                if self.inc.aid_reuses != reuses0:
                    raise RuntimeError("aid reused mid-flight")
                if self.metrics is not None:
                    # counted only once the whole batch is known good, so
                    # batches/topics counters stay consistent
                    self.metrics.inc("tpu.match.batches", len(groups))
                spset = set(spilled)
                for r in spilled:
                    rows[r] = self._host_ids(topics[r])
                    if self.metrics is not None:
                        self.metrics.inc("tpu.match.fallback_host")
                if self._deep:
                    # too-deep filters live host-side; merge their hits
                    for r, t in enumerate(topics):
                        if r not in spset:
                            rows[r].extend(self._deep_ids(t))
                if self.metrics is not None:
                    self.metrics.inc("tpu.match.topics", len(topics))
                    if spilled:
                        self.metrics.inc(
                            "tpu.match.active_overflow", len(spilled)
                        )
                for (topic, fut), row in zip(pending, rows):
                    # pop-then-insert: a refreshed hint is ACTIVE — plain
                    # assignment would keep its stale dict position and
                    # let the post-insert prune evict it ahead of colder
                    # entries, wasting the device work just spent on it
                    self._hints.pop(topic, None)
                    self._hints[topic] = (epoch, rule_gen,
                                          *self._split_row(row))
                    if not fut.done():
                        fut.set_result(None)
                # evict AFTER insert, least-recently-SERVED first (dict
                # order is recency: hint_routes re-appends on a hit).
                # Post-insert pruning makes the cap a true invariant
                # even when a single batch exceeds it (the batch's own
                # oldest entries go too), counts refreshed-in-place
                # topics as the no-ops they are, and the metric is the
                # exact deletion count.  The old full-clear thrashed
                # working sets just over hint_cap between full-cache
                # and cold-cache — the hot head of a Zipf working set
                # must survive the arrival of its own cold tail.
                excess = len(self._hints) - self.hint_cap
                if excess > 0:
                    it = iter(self._hints)
                    for k in [next(it) for _ in range(excess)]:
                        del self._hints[k]
                    if self.metrics is not None:
                        self.metrics.inc("tpu.match.hint_evicted", excess)
            except Exception:
                log.debug("device batch failed; publishes fall back",
                          exc_info=True)
                for _, fut in pending:
                    if not fut.done():
                        fut.set_result(None)

    def info(self) -> dict:
        return {
            "ready": self.ready,
            "filters": self.inc.n_filters,
            "states": self.inc.n_states,
            "rules": len(self._rule_refs),
            "device_epoch": self.dev.epoch,
            "router_epoch": self.router.epoch,
            "synced_epoch": self._synced_epoch,
            "uploads": self.dev.uploads,
            "delta_applies": self.dev.delta_applies,
        }
