"""In-process device matcher for THIS broker's own publish path.

Round 1 left the TPU matcher reachable only through the external exhook
sidecar; the broker's own ``Broker.publish`` always walked the host trie
(VERDICT.md weak item 4).  This service closes that gap:

* it mirrors the :class:`~emqx_tpu.broker.router.Router`'s **wildcard**
  filters into an :class:`IncrementalNfa`/:class:`DeviceNfa` pair by
  consuming the router's delta log (``deltas_since`` — the mria
  bootstrap-then-rlog pattern; a log gap triggers a full resnapshot),
  exact filters stay in the router's O(1) hash map;
* concurrent publishes are **micro-batched**: the connection layer's
  async intercept stage awaits :meth:`prefetch`, which rides a deadline
  batching loop into ONE kernel call, and parks the answer in an
  epoch-validated hint cache;
* the synchronous ``Broker.publish`` then consumes the hint via
  :meth:`hint_routes` (``Broker.device_match``) — if the hint can't be
  proven fresh or is absent, publish falls back to the host trie
  unchanged, so correctness never depends on the device;
* per-row kernel spills fail open to the router's own trie
  (SURVEY.md §5.3), counted in ``tpu.match.fallback_host``.

**Churn-resilient serving** (round-3 rework, VERDICT.md item 3): hints
are no longer wholesale-invalidated by router mutations.  A hint is
stamped with the router epoch its table reflected; at consume time the
router's delta log since that epoch is checked and the hint stays valid
unless a *newly added wildcard filter* matches the topic.  Deletions are
inherently safe — :meth:`Router.routes_with_wild` resolves destinations
live, so removed filters/destinations drop out of the answer without
invalidation.  The same scheme covers rule co-batching via a rule
mutation log.  Under continuous subscribe/unsubscribe churn the device
path therefore keeps serving (duty cycle asserted in
tests/test_match_service.py) instead of collapsing to the host trie.

At low publish concurrency the batching window costs more than the host
trie answers (~12 µs); an **adaptive bypass** skips the device when the
recent arrival rate is below ``bypass_rate`` so single-client latency
stays at host-path levels.

Also co-batches the **rule engine**'s FROM filters (BASELINE config 3):
rules register their topic filters here under a separate id namespace,
and matched rule ids ride the same kernel call (see ``rule_filters``).

**Deadline-aware serve plane** (opt-in, ``match.deadline.enable``): the
fixed-window batch loop is replaced by a continuous-batching loop in
which every prefetch carries a latency *budget* (``match.deadline_ms``,
default = the measured CPU-iso serve p99) and latency is enforced, not
emergent:

* the loop dispatches a **partial batch** the moment the oldest waiter's
  budget (minus the EWMA-estimated dispatch time) is about to expire —
  ``broker.match.deadline_dispatch`` counts these forced flushes;
* the batch bound **adapts to the arrival rate** (EWMA, the fanout-gate
  estimator shape): a batch covers at most the budget's worth of
  arrivals, so batch size tracks load instead of pinning p99 to the
  worst-case fill time (BENCH_r05: batch 8192 → p99 398 ms, 2048 →
  105 ms);
* the short/long dual-lane depth split gets **per-lane caps** derived
  from the observed short-topic fraction, so a deep-topic flood cannot
  starve the cheap shallow kernel's latency;
* every device dispatch runs under a **per-dispatch timeout** with
  immediate CPU fallback: the host NFA + deep-filter trie answer the
  whole batch and mint hints (``broker.match.cpu_fallback``), so a hung
  kernel costs one timeout, not ``prefetch_timeout_s`` per waiter;
* consecutive dispatch failures trip a **circuit breaker**
  (``match.breaker.threshold``) into CPU-serve mode with the
  ``match_degraded`` alarm raised; a supervised recovery child
  (``match.probe``) re-dispatches a canary batch every
  ``match.breaker.probe_interval`` and closes the breaker (and clears
  the alarm) when the device answers again;
* sustained overload walks the :class:`~emqx_tpu.broker.olp.Olp`
  **brownout ladder**: stage 1 shrinks the adaptive batch caps, stage 2
  sheds QoS0 prefetches to the CPU trie, stage 3 is full CPU serve —
  degradation is latency-first, never queue-depth-first.

**Streaming table lifecycle** (opt-in, ``match.segments.enable``): the
delta path is promoted to the PRIMARY lifecycle — the service never
rebuilds or recompiles on the hot path:

* **persistent compacted segments** (``storage/segments.py``): cold
  start loads the flattened table from a versioned, checksummed segment
  file and replays only the diff against the live router, instead of
  re-adding every filter (64 s at 10M, BENCH_r03/r05); a corrupt
  segment is rejected by checksum and falls back to the full rebuild;
* **background delta compaction**: a supervised ``table.compact`` child
  periodically builds a compacted replacement table + device twin OFF
  the event loop, writes the next segment, and swaps both in atomically
  on the loop (``table.swap`` chaos seam fires BEFORE any state
  mutates, so a mid-swap kill is a no-op and the supervised restart
  resumes).  Mutations landing during the build are tracked in a dirty
  set and fixed up at swap; in-flight device batches spanning the swap
  are discarded via the ``_table_gen`` guard (same ``_StaleRace``
  fail-open as aid reuse).  Hints survive the swap untouched — they
  carry router epochs and filter STRINGS, never aids;
* **dirty-region device upload** (``DeviceNfa.dirty_regions``): a table
  resize pads the device buffers in place and scatters only the tracked
  dirty rows (the rehashed edge table ships whole when it moved),
  replacing the whole-table ``device_put`` on growth;
* **padded-shape kernel cache** (``ops/kernel_cache.py``): serve
  dispatches ride AOT-compiled executables keyed on padded shapes, and
  the NEXT pow2 shape pre-warms in the background (``table.prewarm``)
  before growth reaches it — a resize is served from the cache instead
  of stalling a prefetch on an XLA compile.

**Overlapped serve pipeline** (opt-in, ``match.pipeline.enable``): the
dispatch tax BENCH_r05 measured (match kernel ~17 ms p99 vs 398 ms
served at batch 8192 — the gap is host-side encode, serialized
dispatch, and a d2h readback sized to the table) is killed by
overlapping the three serve stages, the way the FPGA XML-filtering
architecture streams documents through match units while I/O overlaps
compute:

* **encode off the loop, overlapped**: ``encode_batch`` for batch N+1
  runs in a worker thread while batch N computes on device; the batch
  operand buffers are DONATED to the kernel (the ``_scatter_rows``
  donation idiom), so the chain never holds two generations of encode
  buffers;
* **double-buffered dispatch**: up to ``match.pipeline.depth``
  (default 2) batches sit past dispatch awaiting readback
  (``broker.match.pipeline_inflight``); the serve loop goes back to
  batching the moment a dispatch lands, instead of parking on the
  round trip;
* **match-proportional two-phase readback** in a supervised
  ``match.readback`` child: phase 1 ships the tiny packed per-row
  meta vector (counts + fail-open flags, 4·B bytes), phase 2 ships
  exactly ``sum(counts)`` ids from the on-device-compacted flat
  buffer — ``tpu.match.readback_bytes`` is 4·(B + Σcounts) per batch
  instead of the 4·FLAT_MULT·B slab the serial path reads;
* **per-slot staleness guards**: every in-flight slot carries the
  table generation + aid-reuse counters it dispatched against; a
  segment swap or aid reuse landing mid-flight discards exactly the
  stale slot (CPU trie answers it, no breaker strike) while fresher
  slots keep their device answers;
* the ``match.readback`` chaos seam (raise / delay / hang) sits at the
  d2h boundary of BOTH the pipelined child and the flag-off path; a
  killed readback child resolves its in-flight slots immediately
  (waiters fail over to the CPU trie) and the supervised restart
  resumes consuming.

**Multichip serve backend** (opt-in, ``match.multichip.enable``): the
match TABLE shards by topic-prefix over a dp×tp device mesh
(``parallel/multichip_serve.py``) and real publish traffic serves from
ALL chips — the on-device analog of the reference's cluster routing
(ekka/mria replicated route tables), and the dryrun→serve step for
every MULTICHIP_r05 configuration:

* each ``tp`` shard owns the filters whose root token hashes to it
  (8 chips hold 8× the filters — the path past 10M toward 100M);
  publish batches are fanned over ``tp`` and sharded over ``dp``;
* per-shard matches translate through a local→service accept-id map ON
  DEVICE and leave the mesh as the dense compact contract
  (``CompactFanoutResult``: per-row disjoint id segments,
  concat-no-dedup), so ring/ICI + d2h traffic is proportional to
  MATCHES, never table width (ROADMAP dispatch-tax residual (d));
* maintenance rides the SAME drain/apply cycle: ``_table_add``/
  ``_table_del`` note mutations into per-shard host subtables, the
  sync loop applies deltas off the event loop, a compaction swap
  repartitions from the fresh aid space (single-chip path serves
  while the partition rebuilds);
* per-shard segments persist next to the main segment with an
  epoch-guarded, checksummed manifest — a cold start only seeds from
  them when the service epoch still matches, else it repartitions;
* failure semantics compose unchanged: a dead (``kill_shard``) or
  fault-injected (``match.shard``) shard raises at dispatch and the
  batch fails over to the CPU trie exactly like any other device
  failure (breaker strike in deadline mode, probe recovery through
  the mesh, ``_StaleRace``/stale-slot discards stay strike-free).

Flag off, the pre-deadline fixed-window loop serves byte-identically.
In BOTH modes a killed/crashed serve loop fails its in-flight waiters
over to the CPU path immediately (and re-arms on supervised restart)
instead of parking them for the full prefetch timeout.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

import numpy as np

from .. import faultinject as _fi
from .. import topic as T
from ..observe.flightrec import STAGES as _FR_STAGES
from ..ops.kernel_cache import CompileMiss
from .trie import FilterTrie

log = logging.getLogger(__name__)

__all__ = ["MatchService"]


# packed flight-recorder stage ids (observe/flightrec.py STAGES)
_SID_WAIT = _FR_STAGES.index("match_wait")
_SID_ENCODE = _FR_STAGES.index("match_encode")
_SID_DISPATCH = _FR_STAGES.index("match_dispatch")
_SID_READBACK = _FR_STAGES.index("match_readback")


class _StaleRace(RuntimeError):
    """A benign serving race (aid reused mid-flight): the batch answer
    can't be trusted, but the device itself is healthy — falls back to
    the CPU path WITHOUT counting against the circuit breaker."""


def _bucket(n: int, minimum: int = 64) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _fresh_add(fresh: Any, new_deep: Dict[str, int], flt: str) -> None:
    """Add ``flt`` to a compaction build's fresh table (the stateless
    twin of ``MatchService._table_add``)."""
    try:
        fresh.add(flt)
    except ValueError:
        if flt not in new_deep:
            new_deep[flt] = fresh.alloc_alias(flt)


def _build_compacted(table_kind: str, depth: int, filters: List[str],
                     deep_filters: List[str], routing: Set[str],
                     active_slots: int, max_matches: int,
                     compact_output: bool, kcache: Any,
                     dirty_threshold: float, segment_path: str,
                     join: bool = False):
    """Worker-thread half of a compaction cycle: build the fresh
    compacted table + device twin from the snapshot, write the next
    segment, and pre-pay the kernel compiles for the fresh shapes.
    Pure with respect to the service — every write lands on objects
    created here; the event-loop swap step publishes them."""
    from ..ops.compiler import _bucket as pow2
    from ..ops.device_table import DeviceNfa
    from ..storage.segments import save_segment

    if table_kind == "native":
        from ..native.nfa import NativeNfa

        fresh = NativeNfa(depth=depth)
        fresh.bulk_add(filters)
    else:
        from ..ops import IncrementalNfa

        fresh = IncrementalNfa(
            depth=depth,
            state_bucket=pow2(max(2 * len(filters), 8), 1024),
            # ~50% post-build edge load: the swapped-in table keeps
            # enough headroom that live churn doesn't hit a growth
            # boundary (and its compile-miss window) right after a swap
            edge_bucket=pow2(max(len(filters), 8), 64))
        for flt in filters:
            fresh.add(flt)
        fresh.track_regions = True
    new_deep = {flt: fresh.alloc_alias(flt) for flt in deep_filters}
    new_routing: Set[int] = set()
    for flt in routing:
        aid = new_deep.get(flt)
        if aid is None:
            aid = fresh.aid_of(flt)
        if aid >= 0:
            new_routing.add(aid)
    # the next segment lands BEFORE the swap: a crash after this point
    # leaves a valid fresh segment on disk and the old table serving.
    # With the join backend on, the relation persists too (built clean
    # from the fresh table — the full-rebuild-on-compact contract).
    save_segment(segment_path, fresh, deep=new_deep,
                 routing_aids=new_routing, filters=filters,
                 join_relation=join)
    newdev = DeviceNfa(
        fresh, active_slots=active_slots, max_matches=max_matches,
        compact_output=compact_output, lazy=True,
    )
    newdev.kernel_cache = kcache
    newdev.dirty_full_threshold = dirty_threshold
    newdev.dirty_regions = hasattr(fresh, "track_regions")
    if join:
        newdev.join_enabled = True
    newdev.sync(full=True)
    if kcache is not None:
        s, hb, _d = fresh.shape_key()
        kcache.prewarm_shape(s, hb)
    return fresh, newdev, new_deep, new_routing


class MatchService:
    """Device-backed topic matching for the broker's hot path."""

    def __init__(
        self,
        broker: Any,
        metrics: Any = None,
        depth: int = 8,
        batch_window_s: float = 0.0002,
        # 2048 is the measured serving sweet spot (BENCH_r05
        # serve_device_quarter_batch: p99 105 ms vs 398 ms at 8192 at
        # similar capacity) — the default when no override is given
        max_batch: int = 2048,
        debounce_s: float = 0.05,
        active_slots: int = 16,
        max_matches: int = 32,
        hint_cap: int = 65536,
        max_stale_deltas: int = 256,
        bypass_rate: float = 0.0,
        prefetch_timeout_s: float = 0.5,
        table: str = "auto",   # auto | native | python
        short_depth: int = 4,
        split_min: int = 256,
        deadline: bool = False,
        deadline_s: float = 0.041,
        pipeline: bool = False,
        pipeline_depth: int = 2,
        breaker_threshold: int = 5,
        breaker_probe_interval_s: float = 1.0,
        dispatch_timeout_s: Optional[float] = None,
        alarms: Any = None,
        olp: Any = None,
        segments: bool = False,
        segments_dir: str = "",
        compact_interval_s: float = 30.0,
        compact_min_mutations: int = 1024,
        dirty_threshold: float = 0.5,
        prewarm: bool = True,
        backend: str = "hash",
        autotune: bool = True,
        autotune_reps: int = 3,
        multichip: bool = False,
        multichip_tp: int = 0,
        multichip_native: bool = True,
        multichip_ep: bool = False,
        multichip_ep_slack: float = 2.0,
        multichip_ep_micro: int = 8,
        multichip_ep_compact: bool = False,
        multichip_degraded: bool = False,
        multichip_degraded_threshold: int = 3,
        multichip_ep_overflow_warn: float = 0.5,
        multichip_ep_autotune: bool = False,
        multichip_ep_grow_threshold: float = 0.05,
        multichip_ep_shrink_threshold: float = 0.01,
        multichip_ep_max_cap_class: int = 3,
        multichip_balance_budget: int = 64,
        readback_mode: str = "chunked",
        readback_auto_slack: float = 1.0,
        hists: Any = None,
        flightrec: Any = None,
    ) -> None:
        from ..ops import IncrementalNfa
        from ..ops.device_table import DeviceNfa

        self.broker = broker
        self.router = broker.router
        self.metrics = metrics
        self.depth = depth
        self.batch_window_s = batch_window_s
        self.max_batch = max_batch
        self.debounce_s = debounce_s
        self.hint_cap = hint_cap
        # serving tolerates up to this many un-synced router deltas; the
        # per-topic freshness proof scans at most this many log entries
        self.max_stale_deltas = max_stale_deltas
        # publishes/s below which prefetch skips the device entirely
        # (0 disables bypassing — tests pin the device path on)
        self.bypass_rate = bypass_rate
        self.prefetch_timeout_s = prefetch_timeout_s
        # depth bucketing: topics with <= short_depth levels ride a
        # shallower kernel (~40% fewer gathers on Zipf traffic); the
        # split only happens when BOTH groups clear split_min, because a
        # second kernel dispatch has a fixed cost that must amortize
        self.short_depth = short_depth
        self.split_min = split_min
        # deadline-aware serve plane (module docstring).  Off = the
        # fixed-window loop, byte-identical to the pre-deadline path.
        self.deadline = bool(deadline)
        self.deadline_s = deadline_s
        # overlapped serve pipeline (module docstring).  Off = the
        # serial dispatch→readback round trip, byte-identical to PR 10.
        self.pipeline = bool(pipeline)
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight_q: Optional[asyncio.Queue] = None
        self._inflight_n = 0
        self.breaker_threshold = breaker_threshold
        self.breaker_probe_interval_s = breaker_probe_interval_s
        # per-dispatch bound: well under the waiter timeout so a hung
        # kernel degrades to ONE CPU-served batch, not a stalled queue
        self.dispatch_timeout_s = (
            dispatch_timeout_s if dispatch_timeout_s is not None
            else min(max(4.0 * deadline_s, 0.1),
                     max(prefetch_timeout_s * 0.8, 0.05)))
        self.alarms = alarms
        self.olp = olp

        # host table: the C++ incremental NFA when available (seconds at
        # 10M filters, Python-object-free), else the Python twin —
        # identical mutation/drain surface, property-tested equivalent
        self.inc = None
        self.table_kind = "python"
        if table in ("auto", "native"):
            try:
                from ..native.nfa import NativeNfa

                self.inc = NativeNfa(depth=depth)
                self.table_kind = "native"
            except Exception:
                if table == "native":
                    raise
                log.warning(
                    "native NFA table unavailable; python table serves "
                    "(fine below ~1M filters)", exc_info=True,
                )
        if self.inc is None:
            self.inc = IncrementalNfa(depth=depth)
        self.dev = DeviceNfa(
            self.inc, active_slots=active_slots, max_matches=max_matches,
            lazy=True,
        )
        # streaming table lifecycle (module docstring; opt-in, flag off
        # keeps every structure below inert and the serve path unchanged)
        self.segments = bool(segments) and bool(segments_dir)
        self.segments_dir = segments_dir
        self.compact_interval_s = compact_interval_s
        self.compact_min_mutations = compact_min_mutations
        self.prewarm = bool(prewarm)
        self.kcache = None
        self._table_gen = 0            # bumped by every segment swap
        self._mut_count = 0            # table mutations since last segment
        self._compact_dirty: Set[str] = set()   # filters touched mid-build
        self._compact_recording = False
        self._compact_abandoned = 0
        self._segment_loaded = False
        self._segment_tried = False
        self._prewarm_busy = False
        self._hydrate_child: Any = None
        if self.segments:
            from ..ops.kernel_cache import MatchKernelCache

            self.kcache = MatchKernelCache()
            self.dev.kernel_cache = self.kcache
            self.dev.dirty_full_threshold = dirty_threshold
            if hasattr(self.inc, "track_regions"):
                self.inc.track_regions = True
                self.dev.dirty_regions = True
        # kernel backend routing (ISSUE 13): "hash" = the cuckoo-probe
        # kernel (default, byte-identical to the pre-join path),
        # "join" = the sorted-relation kernel, "auto" = per-shape picks
        # from a measured, persisted autotuner.  join/auto turn the
        # DeviceNfa relation mirror on; flag off every join structure
        # stays unbuilt.
        self.backend = backend
        # phase-2 readback shape (module docstring): "chunked" = the
        # pow2 binary decomposition (byte-identical to PR 16), "ragged"
        # = ONE padded-to-capacity-class transfer per batch (two d2h
        # round trips total, meta + payload), "auto" = ragged exactly
        # when the total is not a power of two (pow2 totals are one
        # chunk either way, so the decomposition already costs 2).
        self.readback_mode = readback_mode
        # auto-mode ragged crossover (satellite, ISSUE 18): padding
        # slack tolerated before auto falls back to chunked; 1.0 admits
        # every pow2-capacity class (byte-identical to the PR 17 rule)
        self.readback_auto_slack = float(readback_auto_slack)
        self.tuner = None
        self._tuning: Set[str] = set()
        self._seg_join_seed = None   # (epoch, shape_key, arrays)
        # reservoir of recently SERVED topics: what autotune measures
        # with, so picks reflect real traffic shape, not dummy batches
        self._topic_sample: Deque[str] = deque(maxlen=256)
        if backend in ("join", "join-pallas", "auto"):
            self.dev.enable_join()
        if backend == "auto" and autotune:
            from ..ops.join_match import BackendAutotuner

            self.tuner = BackendAutotuner(
                path=(os.path.join(segments_dir, "autotune.json")
                      if self.segments else None),
                reps=autotune_reps)
        if self.kcache is not None and backend == "auto":
            # prewarm must cover BOTH kernel families, or the first
            # auto-routed join dispatch on a fresh shape eats a
            # CompileMiss → CPU hop (ISSUE 13 bugfix)
            self.kcache.auto_backends = ("hash", "join")
        # multichip serve backend (module docstring; opt-in, flag off
        # leaves self.mc None and every seam below one None-test so the
        # single-chip path is byte-identical — spy-asserted)
        self.mc = None
        if multichip:
            try:
                from ..parallel.multichip_serve import MultichipMatcher

                self.mc = MultichipMatcher(
                    depth=depth, tp=multichip_tp,
                    active_slots=active_slots, max_matches=max_matches,
                    metrics=metrics, kernel_cache=self.kcache,
                    native=multichip_native, ep=multichip_ep,
                    ep_slack=multichip_ep_slack,
                    ep_micro_matches=multichip_ep_micro,
                    ep_compact=multichip_ep_compact,
                    degraded=multichip_degraded,
                    degraded_fail_threshold=multichip_degraded_threshold,
                    ep_overflow_warn=multichip_ep_overflow_warn,
                    ep_autotune=multichip_ep_autotune,
                    ep_grow_threshold=multichip_ep_grow_threshold,
                    ep_shrink_threshold=multichip_ep_shrink_threshold,
                    ep_max_cap_class=multichip_ep_max_cap_class,
                    balance_budget=multichip_balance_budget)
            except Exception:
                log.exception("multichip serve backend unavailable; "
                              "single-chip path serves")
        # degraded-mesh service state (inert unless the mc degraded
        # flag is on): the mesh_degraded alarm latch and the supervised
        # mesh.rebuild child's running flag
        self._mesh_alarmed = False
        self._mesh_rebuilding = False
        self._ref: Dict[str, int] = {}     # wildcard filter -> route count
        self._deep: Dict[str, int] = {}    # too-deep filter -> alias aid
        self._deep_trie = FilterTrie()     # host match for too-deep filters
        # rule filters compile as REAL NFA filters tagged by aid; a filter
        # used by both routing and rules shares one aid.  Maps aid->sets:
        self._aid_rules: Dict[int, Set[str]] = {}   # aid -> rule ids
        self._rule_refs: Dict[str, Dict[str, int]] = {}  # rule_id -> {flt: 1}
        self._routing_aids: Set[int] = set()

        # rule mutation log: (gen, filters-added) — unregisters append an
        # empty entry so gen coverage stays contiguous (deleted rules are
        # harmless in stale hints: the engine skips unknown ids)
        self._rule_gen = 0
        self._rule_log: Deque[Tuple[int, Tuple[str, ...]]] = deque(maxlen=512)

        self.ready = False
        self._seen_epoch = 0          # router delta-log position (drained)
        self._synced_epoch = 0        # router epoch the DEVICE table reflects
        self._synced_rule_gen = 0     # rule gen the device table reflects
        self._dirty = asyncio.Event()
        self._pending: List[Tuple[str, asyncio.Future]] = []
        self._batch_wake = asyncio.Event()
        # topic -> (router_epoch, rule_gen, wild filters, rule ids)
        self._hints: Dict[str, Tuple[int, int, List[str], List[str]]] = {}
        self._tasks: List[asyncio.Task] = []
        self._running = False
        # arrival-rate window for the adaptive bypass
        self._win_start = time.monotonic()
        self._win_count = 0
        self._last_rate = 0.0
        # deadline-mode serving state: EWMA arrival rate + short-lane
        # fraction (per-lane caps), EWMA dispatch latency (partial-flush
        # trigger), circuit breaker, brownout cache
        self._rate_ewma: Optional[float] = None
        self._short_frac: Optional[float] = None
        self._win_short = 0
        self._est_dispatch_s = 0.005
        # split dispatch-vs-readback estimate (ROADMAP dispatch-tax
        # residual (c)): the combined EWMA above times the WHOLE
        # t0→resolve span, which in pipeline mode includes time a slot
        # sits queued for readback — queue-wait polluting the
        # partial-flush trigger.  The split components are fed from the
        # stage timers where each stage actually runs (encode+dispatch
        # in the worker thread, readback in the readback worker), so
        # their sum is the true device round trip.  The combined
        # estimate stays as the fallback while the split is cold.
        self._est_disp_s = 0.004
        self._est_rb_s = 0.001
        self._est_split_samples = 0
        self._breaker_failures = 0
        self._breaker_open = False
        self._probe_child: Any = None
        self._last_brownout = 0

        # stage-level latency observatory (observe/hist.py): direct
        # histogram references, None = zero-call recording sites.  The
        # match_* histograms are written by the (single in-flight)
        # worker-thread stages; match_wait by the serve loop — one
        # writer per histogram, merged at read time.
        self.hists = hists
        self._h_wait = self._h_encode = None
        self._h_dispatch = self._h_readback = None
        if hists is not None:
            self._h_wait = hists.hist("obs.stage.match_wait")
            self._h_encode = hists.hist("obs.stage.match_encode")
            self._h_dispatch = hists.hist("obs.stage.match_dispatch")
            self._h_readback = hists.hist("obs.stage.match_readback")
        # always-on flight recorder (observe/flightrec.py): per-writer
        # event rings + the breaker/brownout dump triggers
        self.flightrec = flightrec
        self._ring_loop = self._ring_disp = self._ring_rb = None
        if flightrec is not None:
            self._ring_loop = flightrec.ring("match.serve")
            self._ring_disp = flightrec.ring("match.encode")
            self._ring_rb = flightrec.ring("match.readback")

        self.router.listeners.append(self._on_router_mutation)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._running = True
        self._bootstrap()
        if self.mc is not None:
            # seed the shard partition: per-shard segments when the
            # main table cold-started from ITS segment and the epochs
            # still agree, else a full repartition from the live aid
            # space (note_add events during bootstrap are superseded —
            # rebuild clears the pending log)
            if not (self.segments and self._segment_loaded
                    and self.mc.load_segments(self.segments_dir,
                                              self.inc.epoch)):
                self.mc.rebuild(self._mc_pairs())
        serve_loop = self._deadline_loop if self.deadline \
            else self._batch_loop
        if self.pipeline:
            # in-flight slot queue: maxsize bounds batches QUEUED for
            # readback; with one more in the readback child itself, at
            # most pipeline_depth batches sit past dispatch (depth 2 =
            # classic double buffering)
            self._inflight_q = asyncio.Queue(
                maxsize=max(1, self.pipeline_depth - 1))
        sup = getattr(self, "supervisor", None)
        if sup is not None:
            # supervised (node sets .supervisor before start): a crashed
            # mirror-sync or batch loop restarts instead of freezing
            # hint freshness / prefetch waiters until broker restart
            self._tasks = [
                sup.start_child("match.sync", self._sync_loop),
                sup.start_child("match.batch", serve_loop),
            ]
            if self.pipeline:
                self._tasks.append(
                    sup.start_child("match.readback", self._readback_loop))
            if self.segments:
                self._tasks.append(
                    sup.start_child("table.compact", self._compact_loop))
        else:
            self._tasks = [
                asyncio.ensure_future(self._sync_loop()),
                asyncio.ensure_future(serve_loop()),
            ]
            if self.pipeline:
                self._tasks.append(
                    asyncio.ensure_future(self._readback_loop()))
            if self.segments:
                self._tasks.append(
                    asyncio.ensure_future(self._compact_loop()))
        if self._segment_loaded and getattr(
                self.inc, "_pending_trie", None) is not None:
            # hydrate the restored trie in the background so the first
            # live mutation doesn't pay the relink on the event loop
            if sup is not None:
                self._hydrate_child = sup.start_child(
                    "table.hydrate", self._hydrate_loop,
                    restart="temporary")
            else:
                self._hydrate_child = asyncio.ensure_future(
                    self._hydrate_loop())
        self._dirty.set()

    async def _hydrate_loop(self) -> None:
        await asyncio.to_thread(self.inc._hydrate)

    async def stop(self) -> None:
        self._running = False
        if self._probe_child is not None:
            self._probe_child.cancel()
            self._probe_child = None
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        try:
            self.router.listeners.remove(self._on_router_mutation)
        except ValueError:
            pass  # already unhooked (double stop is legal)
        if self.mc is not None and getattr(self.mc, "ep_autotune",
                                           False):
            # a capacity-rebuild compile must not outlive the service:
            # left running it keeps XLA on every host core after stop
            await asyncio.to_thread(self.mc.drain_resize, 60.0)

    # ------------------------------------------------------------------
    # mirror maintenance (event loop)
    # ------------------------------------------------------------------

    def _on_router_mutation(self, epoch: int) -> None:
        # NO hint invalidation here: freshness is proven per-topic at
        # consume time against the delta log (see _hint_fresh)
        self._dirty.set()

    def _add(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        self._ref[flt] = n + 1
        if n == 0:
            self._table_add(flt, routing=True)

    def _del(self, flt: str) -> None:
        n = self._ref.get(flt, 0)
        if n <= 1:
            self._ref.pop(flt, None)
            if n == 1:
                self._table_del(flt, routing=True)
        else:
            self._ref[flt] = n - 1

    def _table_add(self, flt: str, routing: bool) -> None:
        try:
            self.inc.add(flt)
            aid = self.inc.aid_of(flt)
            if self.mc is not None:
                # mirror the mutation into the shard partition (deep
                # aliases stay host-only — the deep trie serves them)
                self.mc.note_add(flt, aid)
        except ValueError:
            if flt in self._deep:
                aid = self._deep[flt]
            else:
                aid = self.inc.alloc_alias(flt)
                self._deep[flt] = aid
                self._deep_trie.insert(flt)
        if routing:
            self._routing_aids.add(aid)
        self._note_mutation(flt)

    def _table_del(self, flt: str, routing: bool) -> None:
        aid = self._deep.get(flt)
        if aid is None:
            aid = self.inc.aid_of(flt)
        if aid < 0:
            return
        if routing:
            self._routing_aids.discard(aid)
        if aid in self._aid_rules and self._aid_rules[aid]:
            return  # rules still reference this filter
        if flt in self._deep:
            del self._deep[flt]
            self._deep_trie.delete(flt)
            self.inc.free_alias(aid)
        else:
            self.inc.remove(flt)
            if self.mc is not None:
                self.mc.note_del(flt)
        self._note_mutation(flt)

    def _note_mutation(self, flt: str) -> None:
        if not self.segments:
            return
        self._mut_count += 1
        if self._compact_recording:
            # a compaction build is in flight: remember the touched
            # filter so the swap fixes up exactly the changed set
            self._compact_dirty.add(flt)

    def _bootstrap(self) -> None:
        """Full resnapshot from the router (cold start / delta-log gap).
        Refcounts seed from the router's live destination count — a
        filter restored with multiple routes must survive the deletion
        of all but one of them (ADVICE.md round-2 high item 1).

        With segments enabled, the FIRST bootstrap tries the on-disk
        segment instead: load the compacted table, then replay only the
        diff against the live router (the delta-log tail) — a corrupt
        or rejected segment falls through to the full rebuild below."""
        if self.segments and not self._segment_tried:
            self._segment_tried = True
            if self._load_segment():
                return
        self._ref = {}
        for flt in self.router.wildcard_filters():
            self._ref[flt] = max(1, len(self.router.routes_of(flt)))
            if self.inc.aid_of(flt) < 0 and flt not in self._deep:
                self._table_add(flt, routing=True)
            else:
                self._routing_aids.add(
                    self._deep.get(flt, self.inc.aid_of(flt))
                )
        self._seen_epoch = self.router.epoch

    # ------------------------------------------------------------------
    # streaming table lifecycle (opt-in, match.segments.enable)
    # ------------------------------------------------------------------

    @property
    def _segment_path(self) -> str:
        return os.path.join(self.segments_dir, "match_table.seg.npz")

    def _load_segment(self) -> bool:
        """Cold-start from the persisted segment: restore the table +
        id-space bookkeeping, then reconcile against the live router.
        Returns False (full rebuild serves) on ANY defect — missing
        file, checksum reject, injected ``table.load`` fault."""
        from ..storage.segments import (
            SegmentError, load_segment, restore_incremental,
        )

        path = self._segment_path
        if not os.path.exists(path):
            return False
        t0 = time.perf_counter()
        try:
            if _fi._injector is not None:
                # chaos seam: a load fault behaves exactly like a
                # corrupt segment — reject and rebuild from the router
                if _fi._injector.act("table.load") == "raise":
                    raise SegmentError("injected table.load fault")
            seg = load_segment(path)
            if seg.depth != self.depth:
                raise SegmentError(
                    f"segment depth {seg.depth} != table depth "
                    f"{self.depth}")
            if seg.kind == "state" and self.table_kind == "python":
                inc = restore_incremental(seg)
                self.inc = inc
                if self.segments:
                    inc.track_regions = True
                self._deep = dict(seg.deep)
                self._routing_aids = set(seg.routing_aids)
                if seg.join_start is not None:
                    # persisted sorted relation: seeds the join mirror
                    # at the first full sync iff the epoch still
                    # matches (no drift since the segment was written)
                    self._seg_join_seed = (
                        seg.epoch,
                        (int(seg.node_tab.shape[0]),
                         int(seg.edge_tab.shape[0]), seg.depth),
                        (seg.join_start, seg.join_word, seg.join_next),
                    )
            else:
                # native table (or a kind mismatch): replay the filter
                # blob through the bulk path — one native call, not one
                # ctypes round trip per filter
                if hasattr(self.inc, "bulk_add"):
                    self.inc.bulk_add(seg.filters)
                else:
                    for flt in seg.filters:
                        self.inc.add(flt)
                self._deep = {}
                self._routing_aids = set()
                for flt in seg.deep:
                    self._table_add(flt, routing=False)
            self._deep_trie = FilterTrie()
            for flt in self._deep:
                self._deep_trie.insert(flt)
            # the restored table replaces self.inc: rebind the device
            # twin so drains read the live arrays
            self._rebind_dev(self.inc)
            self._reconcile_with_router(
                set(seg.filters) | set(seg.deep),
                aids_valid=(seg.kind == "state"
                            and self.table_kind == "python"))
        except SegmentError:
            log.warning("segment %s rejected; full rebuild serves",
                        path, exc_info=True)
            return False
        except Exception:
            log.exception("segment load failed; full rebuild serves")
            return False
        self._segment_loaded = True
        self._mut_count = 0
        if self.metrics is not None:
            self.metrics.set("tpu.table.segment_load_s",
                             round(time.perf_counter() - t0, 4))
        log.info("match table cold-started from segment %s "
                 "(%d filters, %.1f ms)", path, self.inc.n_filters,
                 (time.perf_counter() - t0) * 1e3)
        return True

    def _rebind_dev(self, inc) -> None:
        from ..ops.device_table import DeviceNfa

        dev = DeviceNfa(
            inc, active_slots=self.dev.active_slots,
            max_matches=self.dev.max_matches,
            compact_output=self.dev.compact_output, lazy=True,
        )
        dev.kernel_cache = self.kcache
        dev.dirty_full_threshold = self.dev.dirty_full_threshold
        dev.dirty_regions = (self.segments
                             and hasattr(inc, "track_regions"))
        if self.backend in ("join", "join-pallas", "auto"):
            seed, self._seg_join_seed = self._seg_join_seed, None
            dev.enable_join(seed=seed)
        self.dev = dev

    def _reconcile_with_router(self, table_set: Set[str],
                               aids_valid: bool) -> None:
        """Replay the delta tail: diff the restored table against the
        live router so only CHANGED filters pay table mutations."""
        routed = self.router.wildcard_filters()
        routed_set = set(routed)
        self._ref = {
            flt: max(1, len(self.router.routes_of(flt)))
            for flt in routed
        }
        if not aids_valid:
            # fresh aid space (native bulk reload): derive the routing
            # aids for the surviving set — native aid_of is a C walk
            for flt in routed_set & table_set:
                aid = self._deep.get(flt, self.inc.aid_of(flt))
                if aid >= 0:
                    self._routing_aids.add(aid)
        for flt in routed_set - table_set:
            self._table_add(flt, routing=True)
        for flt in table_set - routed_set:
            # no rules exist at cold start: anything unrouted goes (a
            # segment-persisted rule filter re-adds at register_rule)
            self._table_del(flt, routing=True)
        self._seen_epoch = self.router.epoch

    def _drain_router(self) -> None:
        deltas = self.router.deltas_since(self._seen_epoch)
        if deltas is None:
            log.info("router delta log gap: full mirror resnapshot")
            # drop filters no longer routed, then re-add from scratch
            for flt in list(self._ref):
                self._table_del(flt, routing=True)
            self._bootstrap()
            return
        for d in deltas:
            if not T.wildcard(d.filter):
                continue  # exact filters stay in the router's hash map
            if d.op == "add":
                self._add(d.filter)
            else:
                self._del(d.filter)
        self._seen_epoch = self.router.epoch

    async def _sync_loop(self) -> None:
        while True:
            await self._dirty.wait()
            await asyncio.sleep(self.debounce_s)
            self._dirty.clear()
            try:
                first = not self.ready
                self._drain_router()
                # epochs the device table will reflect once this sync lands
                router_epoch = self._seen_epoch
                rule_gen = self._rule_gen
                pending = self.dev.drain(full=first)
                if pending.full is not None:
                    # a full re-upload changes table shapes ⇒ the match
                    # jit recompiles; drop readiness so publishes take the
                    # host path instead of stalling on the compile
                    # (ADVICE.md round-2 high item 2)
                    self.ready = False
                await asyncio.to_thread(self.dev.apply_pending, pending)
                if first or pending.full is not None:
                    await asyncio.to_thread(self._warm)
                if self.mc is not None and self.mc.dirty:
                    # shard partition applies in lockstep with the
                    # device twin so both reflect _synced_epoch below
                    await asyncio.to_thread(self._mc_apply)
                if self.mc is not None:
                    self._mesh_watch()
                self.ready = True
                self._synced_epoch = router_epoch
                self._synced_rule_gen = rule_gen
                if self.metrics is not None:
                    self.metrics.inc("tpu.mirror.refresh")
                    if pending.full is not None:
                        self.metrics.inc("tpu.mirror.recompile")
                    elif pending.delta is not None and not pending.delta.empty:
                        self.metrics.inc("tpu.mirror.delta_applied")
                if self.segments:
                    if self.metrics is not None:
                        self.metrics.set("tpu.table.dirty_rows_uploaded",
                                         self.dev.dirty_rows_uploaded)
                        if self.kcache is not None:
                            self.metrics.set(
                                "tpu.table.compile_cache_hits",
                                self.kcache.hits)
                    self._maybe_prewarm()
            except Exception:
                log.exception("match-service sync failed; host path serves")
                await asyncio.sleep(1.0)
                self._dirty.set()

    def _warm(self) -> None:
        from ..ops import encode_batch

        if _fi._injector is not None:
            # chaos seam: the compile/warm step is where growth
            # re-uploads and cold starts stall — a raise here rides the
            # _sync_loop's existing failure path (host trie serves,
            # retry after 1 s); runs inside to_thread, so a delay is a
            # plain blocking sleep
            act = _fi._injector.act("match.compile")
            if act == "raise":
                raise _fi.InjectedFault("match.compile")
            if act == "delay":
                time.sleep(_fi._injector.last_delay)
        # flat_cap is a jit STATIC arg — warming without it would
        # compile the wrong variant and the first live batch would still
        # stall on an XLA compile.  Pipeline mode dispatches through the
        # DONATED jit twin, a separate executable: warm that variant too
        # (fresh operands each pass — donation consumes them).  Under
        # backend routing every family auto can pick must warm, or the
        # first re-routed batch stalls exactly like an unwarmed shape.
        donates = (False, True) if self.pipeline else (False,)
        backends = (("hash", "join") if self.backend == "auto"
                    else (self.backend,))
        for donate in donates:
            for be in backends:
                words, lens, is_sys = encode_batch(self.inc, [], batch=64)
                self.dev.match(words, lens, is_sys,
                               flat_cap=self.FLAT_MULT * 64,
                               donate_inputs=donate, backend=be)
                if self.short_depth and self.short_depth < self.depth:
                    # pre-pay the short-depth kernel shape too, or the
                    # first split batch stalls the loop on an XLA compile
                    w, l, sy = encode_batch(self.inc, [], batch=64,
                                            depth=self.short_depth)
                    self.dev.match(w, l, sy,
                                   flat_cap=self.FLAT_MULT * 64,
                                   donate_inputs=donate, backend=be)

    # ------------------------------------------------------------------
    # multichip serve backend (opt-in, match.multichip.enable)
    # ------------------------------------------------------------------

    def _mc_pairs(self) -> List[Tuple[str, int]]:
        """(filter, service aid) for every NFA-resident filter (routing
        + rules; deep aliases excluded — the host trie serves them):
        the full repartition input for cold start / compaction swap."""
        ruled = {f for refs in self._rule_refs.values() for f in refs}
        out: List[Tuple[str, int]] = []
        for flt in set(self._ref) | ruled:
            if flt in self._deep:
                continue
            aid = self.inc.aid_of(flt)
            if aid >= 0:
                out.append((flt, aid))
        return out

    def _mc_apply(self) -> None:
        """WORKER-THREAD step: fold the noted mutations (or a queued
        repartition) into the shard subtables + stacked device arrays.
        Any failure leaves the single-chip path serving — the partition
        re-applies on the next sync pass."""
        mc = self.mc
        try:
            first = not mc.ready
            if mc.apply_pending() and first:
                # pre-pay the mesh step compiles for the serve shapes
                # (the _warm twin); covers the short lane when split
                depths = ((self.short_depth, self.depth)
                          if self.short_depth
                          and self.short_depth < self.depth
                          else (self.depth,))
                mc.warm(batches=(64,), depths=depths)
            if self.segments and mc._persist_due:
                mc.save_segments(self.segments_dir, self.inc.epoch)
        except Exception:
            log.exception("multichip apply failed; single-chip path "
                          "serves")

    def _mc_active(self):
        """The multichip matcher when it may serve the next dispatch,
        else None (single-chip device path).  One attribute test on the
        flag-off path."""
        mc = self.mc
        return mc if mc is not None and mc.ready else None

    # ------------------------------------------------------------------
    # degraded mesh: health ladder + online shard rebuild
    # (opt-in, match.multichip.degraded.enable)
    # ------------------------------------------------------------------

    def _mesh_watch(self) -> None:
        """Reconcile the mesh health ladder with the service's alarm /
        flight-recorder / rebuild machinery.  Called from the serve
        paths after a shard failure surfaces and from the sync loop;
        cheap when healthy (one attribute walk, no allocation)."""
        mc = self.mc
        if mc is None or not getattr(mc, "degraded", False):
            return
        dead = mc.dead_shards
        if self.metrics is not None:
            self.metrics.set("tpu.mesh.state", mc.mesh_state())
        if dead and not self._mesh_alarmed:
            self._mesh_alarmed = True
            if self.alarms is not None:
                self.alarms.activate(
                    "mesh_degraded",
                    {"dead_shards": list(dead), "tp": mc.tp},
                    "mesh shard(s) dead; degraded serving with CPU fill",
                )
            if self.flightrec is not None:
                # the forensic payoff: what the serve path was doing
                # for the last few hundred batches before the shard
                # died
                self.flightrec.dump("mesh_degraded")
        elif not dead and self._mesh_alarmed:
            self._mesh_alarmed = False
            if self.alarms is not None:
                self.alarms.deactivate("mesh_degraded")
        if dead and not self._mesh_rebuilding:
            self._mesh_rebuilding = True
            sup = getattr(self, "supervisor", None)
            if sup is not None:
                # supervised rebuild child: a crashing rebuild restarts
                # per policy instead of leaving the shard out forever
                sup.start_child("mesh.rebuild", self._mesh_rebuild_loop,
                                restart="transient")
            else:
                try:
                    asyncio.ensure_future(self._mesh_rebuild_loop())
                except RuntimeError:
                    # no running loop (sync-context caller, e.g. a
                    # direct-call test): the next loop-side watch
                    # starts the rebuild
                    self._mesh_rebuilding = False

    async def _mesh_rebuild_loop(self) -> None:
        """Online shard rebuild (transient supervised child): lowest
        dead shard first, reconstruct its subtable OFF the serve path
        (degraded serving continues on the survivors), canary the
        rebuilt shard against the CPU trie, re-admit only on bit
        parity.  A crash — including an injected ``mesh.rebuild``
        fault — restarts the child per supervisor policy and the
        rebuild starts over; a clean return means every shard is live
        again.  ``_mesh_rebuilding`` stays True across crash-restarts
        so ``_mesh_watch`` never starts a second child."""
        mc = self.mc
        while self._running and mc is not None and mc.dead_shards:
            t = mc.dead_shards[0]
            await asyncio.to_thread(
                mc.rebuild_shard, t, self._mc_pairs(),
                self.segments_dir if self.segments else None,
                self.inc.epoch)
            if not await self._mesh_canary(t):
                mc.readmit_canary_fails += 1
                if self.metrics is not None:
                    self.metrics.inc("tpu.mesh.readmit_canary_fails")
                log.error("mesh shard %d rebuild canary FAILED; shard "
                          "stays out", t)
                await asyncio.sleep(0.05)
                continue
            mc.revive_shard(t)
            # in-flight slots dispatched against the degraded plane
            # discard via the table-generation guard — no breaker
            # strike; those publishes re-serve from the CPU trie
            self._table_gen += 1
            log.warning("mesh shard %d rebuilt and re-admitted "
                        "(canary passed)", t)
        self._mesh_rebuilding = False
        self._mesh_watch()

    async def _mesh_canary(self, t: int) -> bool:
        """Bit-parity canary gating shard ``t``'s re-admission: push
        the rebuilt shard's own filters' topics through the mesh with
        ``t`` treated as live (other dead shards stay masked) and
        compare every on-device row against the CPU trie.  Aids the
        degraded plane CPU-fills anyway (other dead shards') are
        credited on the device side, same as the serve path.  True
        only when at least one row was actually checked and every
        checked row matched."""
        mc = self.mc
        topics = mc.canary_topics(t)
        if not topics:
            return True     # shard owns nothing: vacuous pass
        try:
            rows, spilled = await asyncio.to_thread(
                mc.canary_rows, topics, _bucket(len(topics)), t)
        except Exception:
            log.exception("mesh canary dispatch for shard %d failed", t)
            return False
        fill = mc.dead_aids(exclude=t)
        sp = set(spilled)
        checked = 0
        for i, topic in enumerate(topics):
            if i in sp:
                continue
            host = set(self._host_ids(topic))
            if set(rows[i]) | (host & fill) != host:
                log.error("mesh canary mismatch on %r (shard %d)",
                          topic, t)
                return False
            checked += 1
        return checked > 0

    def mesh_info(self) -> Optional[Dict[str, Any]]:
        """Mesh health snapshot for ``ctl mesh`` / ``GET /api/v5/mesh``
        — None when the multichip backend is off."""
        mc = self.mc
        if mc is None:
            return None
        out = mc.info()
        out["alarmed"] = self._mesh_alarmed
        out["rebuilding"] = self._mesh_rebuilding
        return out

    # ------------------------------------------------------------------
    # kernel backend routing (opt-in, match.backend)
    # ------------------------------------------------------------------

    def _backend_for(self, b: int, d: int) -> str:
        """Which kernel family serves a (batch, depth) group: the pinned
        backend, or — under ``auto`` — the autotuner's measured pick for
        the current table shape.  An unmeasured shape serves hash (the
        known-good default) and schedules a background measurement; the
        dispatch path never waits on one."""
        if self.backend != "auto":
            return self.backend
        t = self.tuner
        if t is None:
            return "hash"
        s, hb, _depth = self.inc.shape_key()
        # exact pick, else the pow2 (S, Hb)-family consensus: a growth
        # step inherits the family's measured answer instead of
        # re-measuring cold (ROADMAP join residual (d))
        pick = t.pick_for(b, d, s, hb)
        if pick is not None:
            return pick
        sig = t.sig(b, d, s, hb)
        if sig not in self._tuning and self._topic_sample:
            self._tuning.add(sig)
            # non-daemon, like the kernel cache's background compile: a
            # daemon thread racing XLA teardown at exit segfaults
            import threading

            threading.Thread(
                target=self._autotune_measure, args=(sig, b, d),
                name="match-autotune",
            ).start()
        return "hash"

    def _autotune_measure(self, sig: str, b: int, d: int) -> None:
        """Measurement thread: time hash vs join on the reservoir of
        recently served topics at exactly the dispatch shape, record
        the pick (persisted when segments are on).  Failures leave the
        default routing — a lost measurement is retried on a later
        dispatch of the same shape."""
        import jax

        from ..ops import encode_batch

        try:
            topics = list(self._topic_sample)
            if not topics or self.tuner is None:
                return
            names = (topics * (b // len(topics) + 1))[:b]
            inc, dev = self.inc, self.dev

            def runner(be):
                def go():
                    enc = encode_batch(inc, names, batch=b, depth=d)
                    res = dev.match(
                        *enc, flat_cap=self.FLAT_MULT * b, backend=be)
                    jax.device_get(res.n_matches)   # block to completion
                return go

            runners = {"hash": runner("hash"), "join": runner("join")}
            # the Pallas join walk competes when the relation fits its
            # VMEM budget — same answer bits, so losing shapes simply
            # never route to it
            try:
                from ..ops.pallas_match import supports_join_table

                if dev._jarrs is not None and supports_join_table(
                        dev.arrays()[0], *dev._jarrs):
                    runners["join-pallas"] = runner("join-pallas")
            except Exception:
                log.debug("join-pallas candidate probe for %s failed",
                          sig, exc_info=True)
            self.tuner.measure(sig, runners)
            if self.metrics is not None:
                self.metrics.inc("tpu.match.autotune_picks")
        except Exception:
            log.debug("autotune measurement for %s failed", sig,
                      exc_info=True)
        finally:
            self._tuning.discard(sig)

    async def _compact_loop(self) -> None:
        """Supervised ``table.compact`` child: periodically folds the
        accumulated mutations into a fresh compacted segment OFF the
        event loop and swaps it in atomically — serving never blocks on
        compaction (same supervise idiom as ``match.probe``)."""
        while True:
            await asyncio.sleep(self.compact_interval_s)
            if not self.ready:
                continue
            if self._mut_count < self.compact_min_mutations \
                    and os.path.exists(self._segment_path):
                continue
            try:
                await self._compact_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # leave the live table serving; the supervised child
                # retries next interval (an injected table.swap fault
                # lands here when unsupervised)
                log.exception("table compaction failed; retrying next "
                              "interval")

    def _snapshot_filters(self) -> Tuple[List[str], List[str], Set[str]]:
        """(nfa filters, deep filters, routing filter strings) — all
        service-level state, no table iteration."""
        ruled = {f for refs in self._rule_refs.values() for f in refs}
        deep = set(self._deep)
        nfa = sorted((set(self._ref) | ruled) - deep)
        return nfa, sorted(deep), set(self._ref)

    async def _compact_once(self) -> bool:
        """One compaction cycle: snapshot → background build + segment
        write → fixup + atomic swap.  Returns False when abandoned
        (too much churn landed mid-build; retried next interval)."""
        filters, deep_filters, routing = self._snapshot_filters()
        self._compact_dirty = set()
        self._compact_recording = True
        try:
            built = await asyncio.to_thread(
                _build_compacted, self.table_kind, self.depth,
                filters, deep_filters, routing,
                self.dev.active_slots, self.dev.max_matches,
                self.dev.compact_output, self.kcache,
                self.dev.dirty_full_threshold, self._segment_path,
                self.backend in ("join", "join-pallas", "auto"),
            )
        finally:
            self._compact_recording = False
        if len(self._compact_dirty) > 4096:
            # churn outran the build: abandon (the live table is
            # correct; only the compaction is stale) and retry
            self._compact_abandoned += 1
            log.warning("table compaction abandoned: %d filters "
                        "changed mid-build", len(self._compact_dirty))
            return False
        mc = self.mc
        if mc is not None and getattr(mc, "ep_autotune", False):
            # popularity balance pass rides the compaction worker
            # cadence: it STAGES a placement override map that the
            # repartition triggered by _swap_in below applies — so a
            # remap always lands with a fresh aid space and the
            # table-gen guard discarding in-flight slots.  A failure
            # (including an injected ep.rebalance fault) is a no-op:
            # the old placement keeps serving.
            try:
                await asyncio.to_thread(mc.plan_rebalance)
            except Exception:
                log.warning("EP balance pass failed; placement "
                            "unchanged", exc_info=True)
        self._swap_in(built)
        return True

    def _swap_in(self, built: Tuple[Any, ...]) -> None:
        """Atomic (single event-loop step) swap of the compacted table +
        device twin.  The chaos seam fires FIRST: a kill mid-swap
        mutates nothing, serving continues on the old table, and the
        supervised restart simply compacts again."""
        if _fi._injector is not None:
            if _fi._injector.act("table.swap") == "raise":
                raise _fi.InjectedFault("table.swap")
        fresh, newdev, new_deep, new_routing = built
        # fix up filters that changed while the build ran
        for flt in self._compact_dirty:
            routed = flt in self._ref
            ruled = any(flt in refs for refs in self._rule_refs.values())
            have = flt in new_deep or fresh.aid_of(flt) >= 0
            if (routed or ruled) and not have:
                _fresh_add(fresh, new_deep, flt)
            elif not (routed or ruled) and have:
                if flt in new_deep:
                    fresh.free_alias(new_deep.pop(flt))
                else:
                    fresh.remove(flt)
                continue
            aid = new_deep.get(flt, fresh.aid_of(flt))
            if aid >= 0:
                (new_routing.add if routed
                 else new_routing.discard)(aid)
        # remap rule ids into the fresh aid space from the live registry
        new_aid_rules: Dict[int, Set[str]] = {}
        for rule_id, refs in self._rule_refs.items():
            for flt in refs:
                aid = new_deep.get(flt, fresh.aid_of(flt))
                if aid >= 0:
                    new_aid_rules.setdefault(aid, set()).add(rule_id)
        new_trie = FilterTrie()
        for flt in new_deep:
            new_trie.insert(flt)
        self.inc = fresh
        self.dev = newdev
        self._deep = new_deep
        self._deep_trie = new_trie
        self._routing_aids = new_routing
        self._aid_rules = new_aid_rules
        # the fresh table reflects every drained delta + the fixups:
        # hints stay valid (they carry router epochs + filter strings,
        # never aids), in-flight device batches discard via the gen guard
        self._table_gen += 1
        self._synced_epoch = self._seen_epoch
        self._synced_rule_gen = self._rule_gen
        self._mut_count = len(self._compact_dirty)
        self._compact_dirty = set()
        self.ready = True
        if self.mc is not None:
            # the fresh table reassigned EVERY aid: repartition the
            # shard subtables from the new space; mc.ready drops and
            # the single-chip path serves until the rebuild applies
            self.mc.rebuild(self._mc_pairs())
            self._dirty.set()
        if self.metrics is not None:
            self.metrics.inc("tpu.table.compact_runs")
        log.info("compacted table swapped in (gen %d, %d filters)",
                 self._table_gen, fresh.n_filters)
        self._maybe_prewarm()   # cover the fresh table's next shapes

    def _maybe_prewarm(self) -> None:
        """Pre-pay the NEXT pow2 shapes' kernel compiles in the
        background once occupancy nears a growth boundary, so the
        resize is served from the cache (module docstring)."""
        if self.kcache is None or not self.prewarm or self._prewarm_busy:
            return
        nxt = self._next_shapes()
        if not nxt:
            return
        targets = [t for t in nxt if not self.kcache.shape_covered(*t)]
        if not targets:
            return
        self._prewarm_busy = True

        async def prewarm() -> None:
            try:
                for s, hb in targets:
                    await asyncio.to_thread(
                        self.kcache.prewarm_shape, s, hb)
            finally:
                self._prewarm_busy = False

        sup = getattr(self, "supervisor", None)
        if sup is not None:
            sup.start_child("table.prewarm", prewarm,
                            restart="temporary")
        else:
            asyncio.ensure_future(prewarm())

    def _next_shapes(self) -> List[Tuple[int, int]]:
        from ..ops.compiler import BUCKET_SLOTS

        s, hb, _d = self.inc.shape_key()
        n_states = int(self.inc.n_states)
        n_edges = getattr(self.inc, "n_edges", None)
        if n_edges is None:
            n_edges = self.inc.memory_bytes()["n_edges"]
        out: List[Tuple[int, int]] = []
        near_s = (s - n_states) <= max(s // 4, 8)
        # edge growth triggers at 3/4 load; start warming at ~55%
        near_hb = n_edges >= (hb * BUCKET_SLOTS * 11) // 20
        if near_s:
            out.append((2 * s, hb))
        if near_hb:
            out.append((s, 2 * hb))
        if near_s and near_hb:
            out.append((2 * s, 2 * hb))
        return out

    # ------------------------------------------------------------------
    # rule-engine co-batching (BASELINE config 3)
    # ------------------------------------------------------------------

    def register_rule(self, rule_id: str, from_filters: List[str]) -> None:
        """Co-batch a rule's FROM filters into the device table."""
        self.unregister_rule(rule_id)
        refs: Dict[str, int] = {}
        for flt in from_filters:
            refs[flt] = 1
            self._table_add(flt, routing=False)
            aid = self._deep.get(flt, self.inc.aid_of(flt))
            self._aid_rules.setdefault(aid, set()).add(rule_id)
        self._rule_refs[rule_id] = refs
        self._rule_gen += 1
        self._rule_log.append((self._rule_gen, tuple(from_filters)))
        self._dirty.set()

    def unregister_rule(self, rule_id: str) -> None:
        refs = self._rule_refs.pop(rule_id, None)
        if not refs:
            return
        for flt in refs:
            aid = self._deep.get(flt, self.inc.aid_of(flt))
            rules = self._aid_rules.get(aid)
            if rules is not None:
                rules.discard(rule_id)
                if not rules:
                    del self._aid_rules[aid]
            # drop the filter from the table unless routing still needs it
            if aid not in self._routing_aids and aid not in self._aid_rules:
                if flt in self._deep:
                    del self._deep[flt]
                    self._deep_trie.delete(flt)
                    self.inc.free_alias(aid)
                else:
                    self.inc.remove(flt)
        # removal-only entry: stale hints that still name the rule are
        # harmless (the engine skips ids not in its live rule map)
        self._rule_gen += 1
        self._rule_log.append((self._rule_gen, ()))
        self._dirty.set()

    # ------------------------------------------------------------------
    # serving
    # ------------------------------------------------------------------

    def _usable(self) -> bool:
        return (
            self.ready
            and self.router.epoch - self._synced_epoch <= self.max_stale_deltas
        )

    def _hint_fresh(self, topic: str, hint_epoch: int) -> bool:
        """Prove a hint still answers correctly for ``topic``.

        Deletions never need invalidation (destinations resolve live in
        ``routes_with_wild``); only a wildcard filter ADDED after the
        hint's table epoch can make the answer incomplete."""
        if hint_epoch == self.router.epoch:
            return True
        if self.router.epoch - hint_epoch > self.max_stale_deltas:
            return False  # bound the proof before materializing deltas
        deltas = self.router.deltas_since(hint_epoch)
        if deltas is None:
            return False
        for d in deltas:
            if d.op == "add" and T.wildcard(d.filter) \
                    and T.match(topic, d.filter):
                return False
        return True

    def _rules_fresh(self, topic: str, hint_gen: int) -> bool:
        """Rule-side freshness: a rule registered after the hint whose
        FROM filter matches the topic invalidates it (ADVICE.md round-2
        medium item: rule changes don't bump the router epoch)."""
        if hint_gen == self._rule_gen:
            return True
        if self._rule_log and self._rule_log[0][0] > hint_gen + 1:
            return False  # log trimmed past the hint's gen
        for gen, filters in self._rule_log:
            if gen > hint_gen and any(T.match(topic, f) for f in filters):
                return False
        return True

    def _note_arrival(self, topic: Optional[str] = None) -> None:
        now = time.monotonic()
        dt = now - self._win_start
        if dt >= 0.05:
            self._last_rate = self._win_count / dt
            if self.deadline:
                # EWMA-smooth the windowed rate (the fanout-gate
                # estimator shape) for the adaptive batch bound, and
                # track the short-lane traffic fraction for per-lane caps
                a = 0.5
                self._rate_ewma = (
                    self._last_rate if self._rate_ewma is None
                    else self._rate_ewma * (1.0 - a) + self._last_rate * a)
                frac = self._win_short / max(1, self._win_count)
                self._short_frac = (
                    frac if self._short_frac is None
                    else self._short_frac * (1.0 - a) + frac * a)
                self._win_short = 0
            self._win_start = now
            self._win_count = 0
        self._win_count += 1
        if topic is not None and self._is_short(topic):
            self._win_short += 1

    def _is_short(self, topic: str) -> bool:
        return topic.count("/") < self.short_depth

    def _should_bypass(self) -> bool:
        if self.bypass_rate <= 0:
            return False
        return not self._pending and self._last_rate < self.bypass_rate

    async def prefetch(self, topic: str, qos: int = 0) -> None:
        """Async stage (connection intercept): micro-batch this topic
        through the kernel and park the answer in the hint cache.
        Bounded by ``prefetch_timeout_s`` — a stalled device (compile,
        growth re-upload) degrades to the host path, never blocks
        publishes indefinitely.  In deadline mode the waiter carries its
        latency budget, and breaker-open / brownout states short-circuit
        straight to the CPU path (``qos`` feeds the stage-2 QoS0 shed)."""
        if not self.deadline:
            self._note_arrival()
            if not self._usable():
                return
            hint = self._hints.get(topic)
            if hint is not None and self._hint_fresh(topic, hint[0]) \
                    and self._rules_fresh(topic, hint[1]):
                return
            if self._should_bypass():
                if self.metrics is not None:
                    self.metrics.inc("tpu.match.bypass")
                return
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            # the match_wait stamp rides as the LAST element (only when
            # histograms are on — entries stay 2-tuples otherwise);
            # every consumer indexes from the front, and the deadline
            # accounting below is mode-gated, so the extra element is
            # invisible outside the histogram record
            if self._h_wait is not None:
                self._pending.append((topic, fut, time.perf_counter_ns()))
            else:
                self._pending.append((topic, fut))
            self._batch_wake.set()
            try:
                await asyncio.wait_for(fut, self.prefetch_timeout_s)
            except Exception:
                # timeout/cancel: publish falls back to the host path
                log.debug("prefetch for %r timed out", topic, exc_info=True)
            return
        self._note_arrival(topic)
        if not self._usable():
            return
        hint = self._hints.get(topic)
        if hint is not None and self._hint_fresh(topic, hint[0]) \
                and self._rules_fresh(topic, hint[1]):
            return
        if self._should_bypass():
            if self.metrics is not None:
                self.metrics.inc("tpu.match.bypass")
            return
        lvl = self._brownout()
        if self._breaker_open or lvl >= 3 or (lvl >= 2 and qos == 0):
            # CPU serve: no enqueue, no waiting — Broker.publish walks
            # the host trie when no fresh hint exists
            if self.metrics is not None:
                self.metrics.inc("broker.match.cpu_fallback")
            return
        loop = asyncio.get_running_loop()
        fut2: asyncio.Future = loop.create_future()
        if self._h_wait is not None:
            self._pending.append((topic, fut2,
                                  loop.time() + self.deadline_s,
                                  time.perf_counter_ns()))
        else:
            self._pending.append(
                (topic, fut2, loop.time() + self.deadline_s))
        self._batch_wake.set()
        try:
            await asyncio.wait_for(fut2, self.prefetch_timeout_s)
        except Exception:
            log.debug("prefetch for %r timed out", topic, exc_info=True)

    async def prefetch_many(self, topics, qos_of=None) -> None:
        """Batched prefetch for the fanout pipeline: every topic missing
        a fresh hint is enqueued in the SAME event-loop tick, so the
        whole set rides one batching window — one kernel call for the
        batch instead of one ``prefetch`` await per message.  Bounded by
        ``prefetch_timeout_s`` like the single-topic path.

        ``topics`` may be a ``{topic: max_qos}`` mapping (the fanout
        pipeline passes one), which doubles as ``qos_of`` for the
        deadline-mode brownout stage-2 QoS0 shed."""
        if not self._usable():
            return
        if qos_of is None and isinstance(topics, dict):
            qos_of = topics
        deadline = self.deadline
        lvl = self._brownout() if deadline else 0
        if deadline and (self._breaker_open or lvl >= 3):
            # full CPU serve: the whole batch falls to the host trie
            if self.metrics is not None:
                self.metrics.inc("broker.match.cpu_fallback", len(topics))
            return
        waits: List[asyncio.Future] = []
        loop = asyncio.get_running_loop()
        deadline_t = loop.time() + self.deadline_s if deadline else 0.0
        shed = 0
        for topic in topics:
            self._note_arrival(topic if deadline else None)
            hint = self._hints.get(topic)
            if hint is not None and self._hint_fresh(topic, hint[0]) \
                    and self._rules_fresh(topic, hint[1]):
                continue
            if deadline and lvl >= 2 and qos_of is not None \
                    and qos_of.get(topic, 1) == 0:
                shed += 1   # brownout stage 2: QoS0 rides the CPU trie
                continue
            fut = loop.create_future()
            if self._h_wait is not None:
                ts = time.perf_counter_ns()
                self._pending.append(
                    (topic, fut, deadline_t, ts) if deadline
                    else (topic, fut, ts))
            elif deadline:
                self._pending.append((topic, fut, deadline_t))
            else:
                self._pending.append((topic, fut))
            waits.append(fut)
        if shed and self.metrics is not None:
            self.metrics.inc("broker.match.cpu_fallback", shed)
        if not waits:
            return
        self._batch_wake.set()
        try:
            await asyncio.wait_for(
                asyncio.gather(*waits), self.prefetch_timeout_s
            )
        except Exception:
            # timeout/cancel: those topics fall back to the host trie
            log.debug("prefetch_many (%d topics) timed out", len(waits),
                      exc_info=True)

    def hint_available(self, topic: str) -> bool:
        """Non-consuming freshness peek (observability/tracing): True iff
        a device hint would serve this topic right now.  No metrics, no
        cache mutation — safe to call from taps."""
        hint = self._hints.get(topic)
        return hint is not None and self._hint_fresh(topic, hint[0])

    def hint_routes(self, topic: str):
        """Sync stage (Broker.publish): provably-fresh hint → routes,
        else None (host trie serves)."""
        hint = self._hints.get(topic)
        if hint is None:
            return None
        if not self._hint_fresh(topic, hint[0]):
            self._hints.pop(topic, None)
            if self.metrics is not None:
                self.metrics.inc("tpu.match.hint_stale")
            return None
        if self.metrics is not None:
            self.metrics.inc("tpu.match.hint_served")
        # move-to-end: a served hint is recent; eviction takes from the
        # other end of the dict (insertion order doubles as LRU order)
        self._hints[topic] = self._hints.pop(topic)
        return self.router.routes_with_wild(topic, hint[2])

    def hint_rules(self, topic: str) -> Optional[List[str]]:
        """Matched rule ids for a fresh hint, else None (rule engine then
        falls back to its per-rule host matching)."""
        hint = self._hints.get(topic)
        if hint is None:
            return None
        if not self._rules_fresh(topic, hint[1]):
            self._hints.pop(topic, None)
            if self.metrics is not None:
                self.metrics.inc("tpu.match.hint_stale")
            return None
        # a rules-only working set is just as hot as a routing one:
        # refresh LRU recency so it survives eviction (see hint_routes)
        self._hints[topic] = self._hints.pop(topic)
        return hint[3]

    def _deep_ids(self, topic: str) -> List[int]:
        if not self._deep:
            return []
        return [self._deep[f] for f in self._deep_trie.match(topic)]

    def _host_ids(self, topic: str) -> List[int]:
        return self.inc.match_host(topic) + self._deep_ids(topic)

    def _split_row(self, row: List[int]) -> Tuple[List[str], List[str]]:
        """aid row → (routing wildcard filters, rule ids)."""
        filters: List[str] = []
        rules: Set[str] = set()
        table = self.inc.accept_filters
        for aid in row:
            if aid in self._routing_aids:
                f = table[aid]
                if f is not None:
                    filters.append(f)
            r = self._aid_rules.get(aid)
            if r:
                rules.update(r)
        return filters, sorted(rules)

    # flat-output capacity per padded batch row: readback is the serving
    # bottleneck on remote-attached devices (BASELINE.md tunnel table),
    # and ~6 ids/topic covers the workload's fan-out tail
    from ..ops.match_kernel import SERVE_FLAT_MULT as FLAT_MULT

    def _device_rows(self, enc, n: int):
        B = enc[0].shape[0]
        res = self.dev.match(*enc, flat_cap=self.FLAT_MULT * B)
        return self._readback_rows(res, n, self.dev.max_matches)

    @staticmethod
    def _readback_rows(res, n: int, k: int):
        import jax

        from ..ops.match_kernel import decode_flat

        # fetch the kernel's own outputs and OR the spill flags on host:
        # res.spilled_rows() would build NEW lazy device ops here, i.e.
        # an extra dispatch round trip per batch on the readback path
        matches, counts, aover, mover = jax.device_get(
            (res.matches, res.n_matches, res.active_overflow,
             res.match_overflow)
        )
        sp = (aover > 0) | (mover > 0)
        rows = [seg.tolist()
                for seg in decode_flat(matches, counts, k)[:n]]
        return rows, np.flatnonzero(sp[:n]).tolist()

    @staticmethod
    def _readback_rows_twophase(res, n: int, k: int,
                                mode: str = "chunked",
                                auto_slack: float = 1.0):
        """Match-proportional two-phase d2h: phase 1 ships the packed
        (B,) ``row_meta`` vector (counts + fail-open flags), phase 2
        exactly ``sum(counts)`` ids from the flat buffer — the first
        Σ nk[:n] entries are the real rows by the cumsum-offset
        construction (padding rows pack strictly after).  ``mode``
        picks the phase-2 transfer shape: "chunked" is the pow2 binary
        decomposition (popcount(total) transfers, zero padding bytes),
        "ragged" ONE padded-to-capacity-class transfer (a batch then
        costs exactly TWO d2h round trips, meta + payload), "auto"
        ragged when the total is not a power of two AND the capacity
        padding stays within ``auto_slack``·total extra ids (a pow2
        total is one chunk either way — identical bytes AND trips).
        ``auto_slack`` is the crossover knob (``match.readback
        .auto_slack``): pow2 capacity classes pad < total for any
        non-pow2 total, so the 1.0 default always takes the ragged
        trip — exactly the pre-knob heuristic; a low-bandwidth link
        dials it down to keep byte-bloated totals on the chunked
        path.  Returns ``(rows, spilled row indices, d2h bytes
        shipped, d2h round trips performed)``."""
        import jax

        from ..ops.match_kernel import (
            decode_row_meta, fetch_flat_prefix, fetch_flat_ragged,
            ragged_capacity,
        )

        meta = jax.device_get(res.row_meta)
        nk, sp = decode_row_meta(meta)
        nk = np.minimum(nk, k)
        total = int(nk[:n].sum())
        ragged = mode == "ragged" or (
            mode == "auto" and bool(total & (total - 1))
            and (ragged_capacity(total, int(res.matches.shape[0]))
                 - total) <= auto_slack * total)
        if ragged:
            ids = fetch_flat_ragged(res.matches, total)
            nbytes = 4 * (meta.size +
                          ragged_capacity(total, int(res.matches.shape[0])))
            trips = 1 + (1 if total else 0)
        else:
            ids = fetch_flat_prefix(res.matches, total)
            nbytes = 4 * (meta.size + total)
            trips = 1 + bin(total).count("1")
        offs = np.cumsum(nk[:n]) - nk[:n]
        rows = [ids[o:o + c].tolist() for o, c in zip(offs, nk[:n])]
        return rows, np.flatnonzero(sp[:n]).tolist(), nbytes, trips

    def _encode_dispatch(self, inc, dev, topics, groups, donate):
        """WORKER-THREAD stage: encode every depth group and dispatch
        its kernel — both OFF the event loop (the encode of a 2048
        batch held the loop ~2.3 ms per dispatch; vocab dict reads are
        GIL-atomic, and any concurrently-landed mutation is caught by
        the per-flight aid-reuse/table-gen guards or the hint freshness
        proof).  Dispatch only holds the device lock; the returned
        handles are lazy device results, so group 2 executes while
        group 1's answers stream back and — in pipeline mode — batch
        N+1 encodes while batch N computes.  ``donate`` hands the
        operand buffers to the kernel (pipeline mode; nothing reads
        them after dispatch)."""
        from ..ops import encode_batch

        handles = []
        enc_ns = disp_ns = 0
        gen = self._table_gen
        multichip = getattr(dev, "is_multichip", False)
        # autotune reservoir: a slice of what this dispatch actually
        # serves (deque append is GIL-atomic; readers tolerate skew)
        self._topic_sample.extend(topics[:8])
        for idx, d in groups:
            be = "hash" if multichip else \
                self._backend_for(_bucket(len(idx)), d)
            t0 = time.perf_counter_ns()
            if multichip:
                # the shard partition's SHARED vocab assigns different
                # word ids than the service table — encode there, then
                # fan the batch over the mesh (rows come back already
                # translated to service accept ids)
                enc = dev.encode([topics[i] for i in idx],
                                 batch=_bucket(len(idx)), depth=d)
                t1 = time.perf_counter_ns()
                res = dev.dispatch(
                    enc, block_compile=(dev.kernel_cache is None))
                t2 = time.perf_counter_ns()
            else:
                enc = encode_batch(inc, [topics[i] for i in idx],
                                   batch=_bucket(len(idx)), depth=d)
                t1 = time.perf_counter_ns()
                res = dev.match(
                    *enc, flat_cap=self.FLAT_MULT * enc[0].shape[0],
                    # serving never parks behind XLA: an uncompiled
                    # shape raises CompileMiss (CPU trie answers, shape
                    # warms in the background) instead of stalling
                    block_compile=(dev.kernel_cache is None),
                    donate_inputs=donate, backend=be)
                t2 = time.perf_counter_ns()
            if be in ("join", "join-pallas") and self.metrics is not None:
                # this worker is the single in-flight encode stage, so
                # the counter has one writer (same as the histograms)
                self.metrics.inc("tpu.match.backend_join_dispatches")
            enc_ns += t1 - t0
            disp_ns += t2 - t1
            # stage spans: this worker is the single in-flight encode
            # stage, so it is the sole writer of these two histograms
            # and its flight-recorder ring
            if self._h_encode is not None:
                self._h_encode.record(t1 - t0)
                self._h_dispatch.record(t2 - t1)
            if self._ring_disp is not None:
                self._ring_disp.push(_SID_ENCODE, t0, t1 - t0,
                                     len(idx), gen)
                self._ring_disp.push(_SID_DISPATCH, t1, t2 - t1,
                                     len(idx), gen)
            handles.append((res, len(idx)))
        return handles, enc_ns, disp_ns

    def _readback_groups(self, handles, dev, proportional):
        """WORKER-THREAD stage: block on every group's d2h.  Serial
        (flag-off) mode reads the full flat slab exactly as PR 10 did
        unless ``match.readback.mode`` asks for the ragged contract;
        ``proportional`` (pipeline mode) rides the two-phase contract
        in the configured transfer shape.  Returns ``([(rows,
        spilled)...], total d2h bytes, readback ns, d2h round
        trips)``."""
        out = []
        nbytes = 0
        t0 = time.perf_counter_ns()
        total = 0
        trips = 0
        multichip = getattr(dev, "is_multichip", False)
        for res, n in handles:
            if multichip:
                # dense compact contract off the mesh: d2h is already
                # matches-proportional in BOTH serve modes, one
                # device_get round trip
                rows, sp, b = dev.readback(res, n)
                t = 1
            elif proportional or self.readback_mode != "chunked":
                rows, sp, b, t = self._readback_rows_twophase(
                    res, n, dev.max_matches, mode=self.readback_mode,
                    auto_slack=self.readback_auto_slack)
            else:
                rows, sp = self._readback_rows(res, n, dev.max_matches)
                # the slab cost: the flat id buffer + counts and both
                # overflow vectors (what device_get above shipped) in
                # one round trip
                b = 4 * int(res.matches.size + 3 * res.n_matches.size)
                t = 1
            nbytes += b
            total += n
            trips += t
            out.append((rows, sp))
        rb_ns = time.perf_counter_ns() - t0
        # single writer: the flag-off serve loop's to_thread hop OR the
        # pipelined readback child — never both in one mode
        if self._h_readback is not None:
            self._h_readback.record(rb_ns)
        if self._ring_rb is not None:
            self._ring_rb.push(_SID_READBACK, t0, rb_ns, total,
                               self._table_gen)
        return out, nbytes, rb_ns, trips

    def _depth_groups(self, topics: List[str]) -> List[Tuple[List[int], int]]:
        """Partition batch indices into (indices, kernel_depth) groups.
        Kernel depth bounds TOPIC length, not filter depth, so short
        topics are exact through a shallow walk of the same table."""
        sd = self.short_depth
        everything = [(list(range(len(topics))), self.depth)]
        if not sd or sd >= self.depth:
            return everything
        short = [i for i, t in enumerate(topics) if t.count("/") < sd]
        if len(short) < self.split_min or \
                len(topics) - len(short) < self.split_min:
            return everything
        sset = set(short)
        long_ = [i for i in range(len(topics)) if i not in sset]
        return [(short, sd), (long_, self.depth)]

    async def _batch_loop(self) -> None:
        """The pre-deadline fixed-window serve loop (default): wake,
        sleep the batching window, pop up to ``max_batch`` waiters, one
        kernel dispatch.  Byte-identical to the PR-6 path except for the
        waiter-failover fix shared with the deadline loop: a killed or
        crashed run resolves its in-flight waiters immediately (CPU path
        serves) and a restart re-arms the wake on a non-empty queue."""
        try:
            if self._pending:
                # supervisor restart mid-backlog: the dead run consumed
                # the wake — never stall waiters on a non-empty queue
                # (mirrors the fanout _run re-arm fix from PR 3)
                self._batch_wake.set()
            while True:
                await self._batch_wake.wait()
                self._batch_wake.clear()
                if not self._pending:
                    continue
                await asyncio.sleep(self.batch_window_s)
                pending, self._pending = self._pending[: self.max_batch], \
                    self._pending[self.max_batch:]
                if self._pending:
                    self._batch_wake.set()
                await self._serve_batch(pending)
        finally:
            self._fail_over_waiters()

    def _rec_wait(self, pending: List[Any]) -> None:
        """Record each popped waiter's queue wait (enqueue → dispatch
        start) + one flight-recorder event per batch.  Only reachable
        with histograms on — the stamps ride the waiter tuples' tail."""
        h = self._h_wait
        if h is None or not pending:
            return
        now_ns = time.perf_counter_ns()
        rec = h.record
        oldest = now_ns
        n = 0
        for p in pending:
            ts = p[-1]
            # the stamp is an int (perf_counter_ns); a deadline tail is
            # a float and a bare test-injected waiter ends in a future —
            # neither is a stamp, and recording must never be the thing
            # that kills the serve loop
            if type(ts) is not int:
                continue
            rec(now_ns - ts)
            n += 1
            if ts < oldest:
                oldest = ts
        if n and self._ring_loop is not None:
            self._ring_loop.push(_SID_WAIT, oldest, now_ns - oldest,
                                 n, self._table_gen)

    async def _serve_batch(self, pending: List[Any]) -> None:
        """Fixed-window dispatch: device rows → hints, any failure
        resolves the waiters empty-handed (host trie serves)."""
        self._rec_wait(pending)
        if self.pipeline:
            await self._pipeline_dispatch(pending, deadline_mode=False)
            return
        topics = [p[0] for p in pending]
        # the hint's provenance is the epoch the DEVICE table
        # reflects (not the live router epoch — the table may lag;
        # freshness is then proven forward from here at consume time)
        epoch = self._synced_epoch
        rule_gen = self._synced_rule_gen
        try:
            if not self._usable():
                raise RuntimeError("mirror stale")
            rows = await self._dispatch_guarded(topics)
            self._mint_hints(pending, rows, epoch, rule_gen)
        except Exception:
            log.debug("device batch failed; publishes fall back",
                      exc_info=True)
            for p in pending:
                if not p[1].done():
                    p[1].set_result(None)

    async def _fault_gate(self) -> None:
        """The ``match.dispatch`` chaos seam, shared by both serve loops
        and the breaker's recovery probe.  ``hang`` parks until the
        caller's per-dispatch timeout (or cancellation) rescues it."""
        if _fi._injector is not None:
            act = _fi._injector.act("match.dispatch")
            if act == "raise":
                raise _fi.InjectedFault("match.dispatch")
            if act == "delay":
                await _fi._injector.pause()
            elif act == "hang":
                await _fi._injector.hang()

    async def _readback_gate(self) -> None:
        """The ``match.readback`` chaos seam at the d2h boundary,
        shared by the flag-off serve path and the pipelined
        ``match.readback`` child.  ``hang`` parks until the pipelined
        per-slot timeout (or the waiters' prefetch timeout on the
        flag-off path) rescues it."""
        if _fi._injector is not None:
            act = _fi._injector.act("match.readback")
            if act == "raise":
                raise _fi.InjectedFault("match.readback")
            if act == "delay":
                await _fi._injector.pause()
            elif act == "hang":
                await _fi._injector.hang()

    async def _dispatch_guarded(self, topics: List[str]) -> List[Any]:
        await self._fault_gate()
        return await self._device_serve(topics)

    async def _device_serve(self, topics: List[str]) -> List[Any]:
        """Encode + kernel dispatch + readback + spill/deep merge for one
        batch; returns one aid row per topic.  Raises :class:`_StaleRace`
        when a freed accept id was handed out mid-flight (benign — the
        answer is untrusted but the device is healthy)."""
        # aid-reuse guard: if a freed accept id is handed out
        # again while this batch is in flight, the device rows
        # may name it under its OLD filter — translating through
        # the live accept_filters would be wrong at any epoch.
        # The table-gen guard is the segment-swap twin: a compacted
        # table swapped in mid-flight reassigned EVERY aid.
        inc = self.inc
        dev = self._mc_active() or self.dev
        reuses0 = inc.aid_reuses
        gen0 = self._table_gen
        groups = self._depth_groups(topics)
        handles, enc_ns, disp_ns = await asyncio.to_thread(
            self._encode_dispatch, inc, dev, topics, groups, False
        )
        await self._readback_gate()
        results, nbytes, rb_ns, trips = await asyncio.to_thread(
            self._readback_groups, handles, dev, False
        )
        self._note_split((enc_ns + disp_ns) / 1e9, rb_ns / 1e9)
        if self.metrics is not None:
            self.metrics.inc("tpu.match.readback_bytes", nbytes)
            self.metrics.inc("tpu.match.readback_roundtrips", trips)
        return self._collect_rows(topics, groups, results,
                                  inc, reuses0, gen0)

    def _collect_rows(self, topics: List[str], groups, results,
                      inc, reuses0: int, gen0: int) -> List[Any]:
        """Loop-side epilogue shared by the serial path and the
        pipelined readback child: stitch group results back into batch
        order, enforce the per-flight staleness guards, re-run spilled
        rows on the host tables, merge deep-filter hits."""
        rows: List[Any] = [None] * len(topics)
        spilled: List[int] = []
        for (idx, _d), (grows, gspill) in zip(groups, results):
            for j, i in enumerate(idx):
                rows[i] = grows[j]
            spilled.extend(idx[j] for j in gspill)
        if self.inc.aid_reuses != reuses0 or inc is not self.inc \
                or self._table_gen != gen0:
            raise _StaleRace("aid reused or table swapped mid-flight")
        if self.metrics is not None:
            # counted only once the whole batch is known good, so
            # batches/topics counters stay consistent
            self.metrics.inc("tpu.match.batches", len(groups))
        spset = set(spilled)
        for r in spilled:
            rows[r] = self._host_ids(topics[r])
            if self.metrics is not None:
                self.metrics.inc("tpu.match.fallback_host")
        mc = self.mc
        if mc is not None and mc.degraded_serving:
            # degraded mesh: replicated rows lost the dead shards'
            # answer segments — CPU-fill ONLY those aids (a live
            # EP-routed row never intersects: every literal-root match
            # lives on the root's owner shard, which is alive, and
            # wildcard-root filters ride the replicated micro-table)
            fill = mc.dead_aids()
            if fill:
                filled = 0
                for r, t in enumerate(topics):
                    if r in spset:
                        continue    # host-served: already complete
                    add = [a for a in self._host_ids(t) if a in fill]
                    if add:
                        rows[r].extend(add)
                        filled += 1
                if filled:
                    mc.cpu_filled_rows += filled
                    if self.metrics is not None:
                        self.metrics.inc("tpu.mesh.cpu_filled_rows",
                                         filled)
            self._mesh_watch()
        if self._deep:
            # too-deep filters live host-side; merge their hits
            for r, t in enumerate(topics):
                if r not in spset:
                    rows[r].extend(self._deep_ids(t))
        if self.metrics is not None:
            self.metrics.inc("tpu.match.topics", len(topics))
            if spilled:
                self.metrics.inc(
                    "tpu.match.active_overflow", len(spilled)
                )
        return rows

    def _mint_hints(self, pending: List[Any], rows: List[Any],
                    epoch: int, rule_gen: int) -> None:
        for p, row in zip(pending, rows):
            topic, fut = p[0], p[1]
            # pop-then-insert: a refreshed hint is ACTIVE — plain
            # assignment would keep its stale dict position and
            # let the post-insert prune evict it ahead of colder
            # entries, wasting the device work just spent on it
            self._hints.pop(topic, None)
            self._hints[topic] = (epoch, rule_gen,
                                  *self._split_row(row))
            if not fut.done():
                fut.set_result(None)
        self._evict()
        if self.deadline and self.metrics is not None:
            self._count_misses(pending)

    def _evict(self) -> None:
        # evict AFTER insert, least-recently-SERVED first (dict
        # order is recency: hint_routes re-appends on a hit).
        # Post-insert pruning makes the cap a true invariant
        # even when a single batch exceeds it (the batch's own
        # oldest entries go too), counts refreshed-in-place
        # topics as the no-ops they are, and the metric is the
        # exact deletion count.  The old full-clear thrashed
        # working sets just over hint_cap between full-cache
        # and cold-cache — the hot head of a Zipf working set
        # must survive the arrival of its own cold tail.
        excess = len(self._hints) - self.hint_cap
        if excess > 0:
            it = iter(self._hints)
            for k in [next(it) for _ in range(excess)]:
                del self._hints[k]
            if self.metrics is not None:
                self.metrics.inc("tpu.match.hint_evicted", excess)

    def _count_misses(self, pending: List[Any]) -> None:
        try:
            now = asyncio.get_running_loop().time()
        except RuntimeError:
            # no running loop (direct sync call in tests): deadline
            # accounting is loop-time based, so there is nothing to count
            return
        late = sum(1 for p in pending if len(p) > 2 and now > p[2])
        if late:
            self.metrics.inc("broker.match.deadline_miss", late)

    def _note_split(self, disp_s: float, rb_s: float) -> None:
        """Feed the split dispatch-vs-readback estimate from the stage
        timers: ``disp_s`` is the worker-thread encode+dispatch span,
        ``rb_s`` the d2h readback span — neither includes queue-wait,
        which the combined ``_est_dispatch_s`` EWMA picks up in
        pipeline mode (slots sit in the inflight queue inside its
        t0→resolve window)."""
        self._est_disp_s = self._est_disp_s * 0.7 + disp_s * 0.3
        self._est_rb_s = self._est_rb_s * 0.7 + rb_s * 0.3
        if self._est_split_samples < 1 << 30:
            self._est_split_samples += 1

    #: split-estimate warm threshold: below this many component
    #: samples the combined EWMA serves (the histograms/timers are
    #: cold right after start or a long idle gap)
    SPLIT_WARM = 8

    def _dispatch_est(self) -> float:
        """The dispatch-time estimate the partial-flush trigger and the
        adaptive bound subtract from the budget: the split components'
        sum once warm (queue-wait-free), the combined EWMA as the cold
        fallback."""
        if self._est_split_samples >= self.SPLIT_WARM:
            return self._est_disp_s + self._est_rb_s
        return self._est_dispatch_s

    def _fail_over_waiters(self) -> None:
        """Serve-loop death (kill, crash, stop): resolve every in-flight
        waiter NOW so each blocked ``prefetch`` falls to the CPU path
        immediately instead of burning the full ``prefetch_timeout_s``."""
        pending, self._pending = self._pending, []
        if not pending:
            return
        for p in pending:
            if not p[1].done():
                p[1].set_result(None)
        if self.metrics is not None:
            self.metrics.inc("broker.match.cpu_fallback", len(pending))
        log.warning("match serve loop exited with %d waiter(s) in "
                    "flight; failed over to the CPU path", len(pending))

    # ------------------------------------------------------------------
    # deadline-aware continuous-batching serve loop (opt-in)
    # ------------------------------------------------------------------

    async def _deadline_loop(self) -> None:
        """Continuous batching under a latency budget: dispatch when the
        adaptive bound fills OR the oldest waiter's remaining budget no
        longer covers the (EWMA-estimated) dispatch time — whichever
        comes first.  See the module docstring for the full ladder."""
        loop = asyncio.get_running_loop()
        try:
            if self._pending:
                # restart mid-backlog: the dead run consumed the wake
                self._batch_wake.set()
            while True:
                await self._batch_wake.wait()
                self._batch_wake.clear()
                while self._pending:
                    if not self._device_ok():
                        # breaker open / brownout stage 3 / mirror gone
                        # stale with waiters queued: CPU answers them now
                        self._cpu_serve(self._pop_batch(len(self._pending)))
                        continue
                    bound = self._deadline_bound()
                    slack = (self._pending[0][2] - loop.time()
                             - self._dispatch_est())
                    if len(self._pending) < bound and slack > 0:
                        # gather window: admit more arrivals, but never
                        # wait past the oldest waiter's budget; geometric
                        # re-check keeps idle wakeups bounded while the
                        # wake event stays responsive to new arrivals
                        wait = min(slack,
                                   max(self.batch_window_s, slack / 4))
                        try:
                            await asyncio.wait_for(
                                self._batch_wake.wait(), wait)
                        except asyncio.TimeoutError:
                            pass
                        self._batch_wake.clear()
                        continue
                    if len(self._pending) < bound \
                            and self.metrics is not None:
                        # partial batch forced out by the budget — the
                        # deadline doing its job, not an anomaly
                        self.metrics.inc("broker.match.deadline_dispatch")
                    await self._serve_batch_deadline(self._pop_batch(bound))
        finally:
            self._fail_over_waiters()

    def _deadline_bound(self) -> int:
        """Arrival-rate-adaptive batch bound: a batch covers at most the
        budget's worth of arrivals after the estimated dispatch time is
        paid, so fill latency + dispatch fits the budget at any load —
        floored at the arrivals landing DURING one dispatch, or the loop
        would fall behind by construction (an infeasible budget degrades
        to throughput mode, never to a diverging queue).  Brownout stage
        1+ shrinks the cap (half, then quarter)."""
        rate = (self._rate_ewma if self._rate_ewma is not None
                else self._last_rate)
        est = self._dispatch_est()
        headroom = max(self.deadline_s - est,
                       self.deadline_s * 0.25)
        bound = max(1, min(self.max_batch,
                           max(int(rate * headroom),
                               int(rate * est * 1.2))))
        lvl = self._brownout()
        if lvl:
            bound = max(1, bound >> min(lvl, 2))
        return bound

    def _lane_caps(self, bound: int) -> Tuple[int, int]:
        """Per-lane (short-topic, long-topic) caps from the observed
        short-lane traffic fraction — a deep-topic flood cannot consume
        the whole bound and starve the cheap shallow kernel.  25% slack
        per lane so a lagging estimate never starves shifting traffic."""
        if not self.short_depth or self.short_depth >= self.depth:
            return bound, bound
        frac = self._short_frac if self._short_frac is not None else 0.5
        short = min(bound, max(1, int(bound * frac * 1.25) + 1))
        long_ = min(bound, max(1, int(bound * (1.0 - frac) * 1.25) + 1))
        return short, long_

    def _pop_batch(self, bound: int) -> List[Any]:
        """Pop up to ``bound`` waiters from the queue head, honoring the
        per-lane caps; waiters whose lane is full stay queued IN ORDER
        (their budget forces the next dispatch soon enough).  The scan is
        bounded so a deep backlog can't turn the pop quadratic."""
        short_cap, long_cap = self._lane_caps(bound)
        pend = self._pending
        take: List[Any] = []
        rest: List[Any] = []
        limit = min(len(pend), 4 * bound)
        pos = 0
        while pos < limit and len(take) < bound:
            entry = pend[pos]
            pos += 1
            if self._is_short(entry[0]):
                if short_cap > 0:
                    short_cap -= 1
                    take.append(entry)
                else:
                    rest.append(entry)
            elif long_cap > 0:
                long_cap -= 1
                take.append(entry)
            else:
                rest.append(entry)
        rest.extend(pend[pos:])
        self._pending = rest
        return take

    async def _serve_batch_deadline(self, pending: List[Any]) -> None:
        """One deadline-mode dispatch: chaos seam + per-dispatch timeout
        around the kernel call; ANY failure answers the whole batch from
        the CPU tables immediately and feeds the circuit breaker."""
        if not pending:
            return
        self._rec_wait(pending)
        if self.pipeline:
            await self._pipeline_dispatch(pending, deadline_mode=True)
            return
        topics = [p[0] for p in pending]
        epoch = self._synced_epoch
        rule_gen = self._synced_rule_gen
        t0 = time.monotonic()
        try:
            rows = await asyncio.wait_for(
                self._dispatch_guarded(topics), self.dispatch_timeout_s)
        except asyncio.CancelledError:
            # loop death mid-dispatch: the finally-failover resolves
            self._pending = pending + self._pending
            raise
        except _StaleRace:
            self._cpu_serve(pending)    # benign race: no breaker strike
            return
        except CompileMiss:
            # fresh padded shape not compiled yet: the CPU trie answers
            # NOW while the kernel cache warms it in the background —
            # the device is healthy, so no breaker strike
            self._cpu_serve(pending)
            return
        except Exception:
            log.debug("deadline dispatch failed; CPU trie serves the "
                      "batch", exc_info=True)
            self._breaker_note_failure()
            self._cpu_serve(pending)
            return
        self._breaker_note_ok()
        # EWMA dispatch-time estimate drives the partial-flush trigger
        dt = time.monotonic() - t0
        self._est_dispatch_s = self._est_dispatch_s * 0.7 + dt * 0.3
        self._mint_hints(pending, rows, epoch, rule_gen)

    def _cpu_serve(self, pending: List[Any]) -> None:
        """Answer a batch from the CPU tables (host NFA walk + deep
        trie), minting hints at the MIRROR's epoch so the device outage
        stays invisible to publishes — this is the fallback the whole
        ladder bottoms out on (broker/trie.py answers every query the
        device table does)."""
        if not pending:
            return
        # the host table reflects every drained delta (_seen_epoch) and
        # the live rule gen — host answers are as fresh as serving gets
        epoch = self._seen_epoch
        rule_gen = self._rule_gen
        deep = (self._deep_trie.match_many([p[0] for p in pending])
                if self._deep else None)
        rows_of: Dict[str, List[int]] = {}
        for p in pending:
            topic, fut = p[0], p[1]
            row = rows_of.get(topic)
            if row is None:
                row = list(self.inc.match_host(topic))
                if deep is not None:
                    row.extend(self._deep[f] for f in deep[topic])
                rows_of[topic] = row
            self._hints.pop(topic, None)
            self._hints[topic] = (epoch, rule_gen, *self._split_row(row))
            if not fut.done():
                fut.set_result(None)
        self._evict()
        if self.metrics is not None:
            self.metrics.inc("broker.match.cpu_fallback", len(pending))
            self._count_misses(pending)
        # a shard failure lands here (the failed batch CPU-serves):
        # reconcile the mesh ladder — alarm, state metric, rebuild
        self._mesh_watch()

    # ------------------------------------------------------------------
    # overlapped serve pipeline (opt-in, match.pipeline.enable)
    # ------------------------------------------------------------------

    async def _pipeline_dispatch(self, pending: List[Any],
                                 deadline_mode: bool) -> None:
        """Pipeline-mode front half of a serve batch: encode + dispatch
        in a worker thread (donated operand buffers), then hand the
        in-flight slot to the ``match.readback`` child and return — the
        serve loop goes straight back to batching (and encoding batch
        N+1) while this batch computes on device.  Every slot carries
        the aid-reuse/table-gen guards it dispatched against, so a swap
        or reuse landing mid-flight discards exactly the stale slot."""
        if not pending:
            return
        topics = [p[0] for p in pending]
        epoch = self._synced_epoch
        rule_gen = self._synced_rule_gen
        inc = self.inc
        dev = self._mc_active() or self.dev
        reuses0 = inc.aid_reuses
        gen0 = self._table_gen
        t0 = time.monotonic()
        try:
            if not self._usable():
                raise RuntimeError("mirror stale")
            await self._fault_gate()
            groups = self._depth_groups(topics)
            dispatch = asyncio.to_thread(
                self._encode_dispatch, inc, dev, topics, groups, True)
            if deadline_mode:
                handles, enc_ns, disp_ns = await asyncio.wait_for(
                    dispatch, self.dispatch_timeout_s)
            else:
                handles, enc_ns, disp_ns = await dispatch
            slot = (pending, topics, groups, handles, inc, dev,
                    reuses0, gen0, epoch, rule_gen, t0, deadline_mode,
                    enc_ns + disp_ns)
            await self._inflight_q.put(slot)   # backpressure at depth
            self._inflight_n += 1
            self._set_inflight_metric()
        except asyncio.CancelledError:
            # loop death mid-dispatch (or mid-put): the finally-failover
            # resolves these waiters immediately
            self._pending = pending + self._pending
            raise
        except _StaleRace:
            self._cpu_serve(pending)        # benign race: no strike
        except CompileMiss:
            self._cpu_serve(pending)        # shape warms in background
        except Exception:
            log.debug("pipelined dispatch failed; CPU trie serves the "
                      "batch", exc_info=True)
            if deadline_mode:
                self._breaker_note_failure()
            self._cpu_serve(pending)

    async def _readback_loop(self) -> None:
        """Supervised ``match.readback`` child: drains the in-flight
        slot queue, rides the two-phase match-proportional d2h, and
        mints hints — the back half of the double-buffered chain.  A
        kill resolves every queued slot's waiters NOW (CPU path serves)
        and the supervised restart resumes consuming."""
        try:
            while True:
                slot = await self._inflight_q.get()
                try:
                    await self._finish_slot(slot)
                finally:
                    self._inflight_n -= 1
                    self._set_inflight_metric()
        finally:
            self._fail_over_slots()

    async def _finish_slot(self, slot: Tuple[Any, ...]) -> None:
        """Readback + guard check + hint mint for one in-flight slot;
        ANY failure (chaos seam, timeout, stale guard) answers the
        slot's batch from the CPU tables.  The finally backstop keeps
        the kill path from stranding waiters on the prefetch timeout."""
        (pending, topics, groups, handles, inc, dev, reuses0, gen0,
         epoch, rule_gen, t0, deadline_mode, dispatch_ns) = slot
        try:
            try:
                await self._readback_gate()
                results, nbytes, rb_ns, trips = await asyncio.wait_for(
                    asyncio.to_thread(
                        self._readback_groups, handles, dev, True),
                    self.dispatch_timeout_s)
                self._note_split(dispatch_ns / 1e9, rb_ns / 1e9)
                if self.metrics is not None:
                    self.metrics.inc("tpu.match.readback_bytes", nbytes)
                    self.metrics.inc("tpu.match.readback_roundtrips",
                                     trips)
                rows = self._collect_rows(topics, groups, results,
                                          inc, reuses0, gen0)
            except asyncio.CancelledError:
                raise
            except _StaleRace:
                # the swap/reuse happened AFTER this slot dispatched:
                # only this slot's answer is untrusted — CPU serves it,
                # no breaker strike (the device is healthy)
                self._cpu_serve(pending)
                return
            except Exception:
                log.debug("pipelined readback failed; CPU trie serves "
                          "the batch", exc_info=True)
                if deadline_mode:
                    self._breaker_note_failure()
                self._cpu_serve(pending)
                return
            if deadline_mode:
                self._breaker_note_ok()
                # full dispatch→readback time feeds the partial-flush
                # estimate: with the stages overlapped this is the
                # latency a waiter actually experiences
                dt = time.monotonic() - t0
                self._est_dispatch_s = (
                    self._est_dispatch_s * 0.7 + dt * 0.3)
            self._mint_hints(pending, rows, epoch, rule_gen)
        finally:
            for p in pending:
                if not p[1].done():
                    p[1].set_result(None)

    def _fail_over_slots(self) -> None:
        """Readback-child death: resolve every queued slot's waiters so
        their publishes fall to the CPU path immediately instead of
        burning the full prefetch timeout (the in-flight twin of
        :meth:`_fail_over_waiters`)."""
        q = self._inflight_q
        n = 0
        while q is not None and not q.empty():
            slot = q.get_nowait()
            for p in slot[0]:
                if not p[1].done():
                    p[1].set_result(None)
                    n += 1
        self._inflight_n = 0
        self._set_inflight_metric()
        if n:
            if self.metrics is not None:
                self.metrics.inc("broker.match.cpu_fallback", n)
            log.warning("match readback loop exited with %d waiter(s) "
                        "in flight; failed over to the CPU path", n)

    def _set_inflight_metric(self) -> None:
        if self.metrics is not None:
            self.metrics.set("broker.match.pipeline_inflight",
                             self._inflight_n)

    # ------------------------------------------------------------------
    # circuit breaker + brownout
    # ------------------------------------------------------------------

    def _brownout(self) -> int:
        olp = self.olp
        lvl = 0 if olp is None else olp.brownout_level()
        if lvl != self._last_brownout:
            if lvl > self._last_brownout and self.flightrec is not None:
                # brownout ESCALATION: capture what the last few
                # hundred batches were doing when the ladder stepped
                # (de-escalation is recovery, nothing to forensic)
                self.flightrec.dump("brownout")
            self._last_brownout = lvl
            if self.metrics is not None:
                self.metrics.set("broker.match.brownout_level", lvl)
        return lvl

    def _device_ok(self) -> bool:
        """May the next dispatch go to the device?"""
        if self._breaker_open or not self._usable():
            return False
        return self._brownout() < 3

    def _breaker_note_ok(self) -> None:
        self._breaker_failures = 0

    def _breaker_note_failure(self) -> None:
        self._breaker_failures += 1
        if (not self._breaker_open
                and self._breaker_failures >= self.breaker_threshold):
            self._trip_breaker()

    def _trip_breaker(self) -> None:
        self._breaker_open = True
        self._set_breaker_metric(1)
        log.error("match-service breaker OPEN after %d consecutive "
                  "dispatch failures; CPU trie serves",
                  self._breaker_failures)
        if self.alarms is not None:
            self.alarms.activate(
                "match_degraded",
                {"failures": self._breaker_failures},
                "device match dispatch failing; serving from CPU trie",
            )
        if self.flightrec is not None:
            # the forensic payoff: what the serve path was doing for
            # the last few hundred batches before the trip
            self.flightrec.dump("breaker_trip")
        sup = getattr(self, "supervisor", None)
        if sup is not None:
            # supervised recovery child: a crashing probe restarts per
            # policy instead of leaving the breaker open forever
            self._probe_child = sup.start_child(
                "match.probe", self._probe_loop, restart="transient")
        else:
            self._probe_child = asyncio.ensure_future(self._probe_loop())

    def _close_breaker(self) -> None:
        self._breaker_open = False
        self._breaker_failures = 0
        self._set_breaker_metric(0)
        log.warning("match-service breaker closed: device dispatch "
                    "healthy again")
        if self.alarms is not None:
            self.alarms.deactivate("match_degraded")

    def _set_breaker_metric(self, state: int) -> None:
        if self.metrics is not None:
            self.metrics.set("broker.match.breaker_state", state)

    async def _probe_loop(self) -> None:
        """Breaker recovery: every ``probe_interval``, push one canary
        batch through the full dispatch seam (same chaos gate, same
        timeout).  First success closes the breaker and ends the child
        (transient — a clean return is 'recovered')."""
        while self._running and self._breaker_open:
            await asyncio.sleep(self.breaker_probe_interval_s)
            if not self._running:
                return
            self._set_breaker_metric(2)
            try:
                await asyncio.wait_for(
                    self._probe_guarded(), self.dispatch_timeout_s)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.debug("match breaker probe failed; staying open",
                          exc_info=True)
                self._set_breaker_metric(1)
                continue
            self._close_breaker()
            return

    async def _probe_guarded(self) -> None:
        await self._fault_gate()
        await asyncio.to_thread(self._probe_dispatch)

    def _probe_dispatch(self) -> None:
        """One tiny dispatch through the warmed kernel shape — proves
        encode → device → readback end to end without touching the
        serving counters.  With the multichip backend active the probe
        rides the mesh, so a dead shard keeps the breaker open until
        the shard recovers."""
        from ..ops import encode_batch

        mc = self._mc_active()
        if mc is not None:
            enc = mc.encode(["probe/health"], batch=64)
            res = mc.dispatch(enc)
            mc.readback(res, 1)
            return
        enc = encode_batch(self.inc, ["probe/health"], batch=64)
        res = self.dev.match(*enc, flat_cap=self.FLAT_MULT * 64)
        self._readback_rows(res, 1, self.dev.max_matches)

    def info(self) -> dict:
        return {
            "ready": self.ready,
            "filters": self.inc.n_filters,
            "states": self.inc.n_states,
            "rules": len(self._rule_refs),
            "device_epoch": self.dev.epoch,
            "router_epoch": self.router.epoch,
            "synced_epoch": self._synced_epoch,
            "uploads": self.dev.uploads,
            "delta_applies": self.dev.delta_applies,
            "deadline": self.deadline,
            "pipeline": self.pipeline,
            "pipeline_depth": self.pipeline_depth,
            "pipeline_inflight": self._inflight_n,
            "breaker": "open" if self._breaker_open else "closed",
            "breaker_failures": self._breaker_failures,
            "brownout": self._last_brownout,
            "est_dispatch_ms": round(self._est_dispatch_s * 1e3, 3),
            # the split components (satellite of ROADMAP dispatch-tax
            # (c)): what the partial-flush trigger actually subtracts
            # once warm, and whether it is warm
            "est_disp_ms": round(self._est_disp_s * 1e3, 3),
            "est_readback_ms": round(self._est_rb_s * 1e3, 3),
            "est_split_warm": (
                self._est_split_samples >= self.SPLIT_WARM),
            "pending": len(self._pending),
            # kernel backend routing (ISSUE 13)
            "backend": self.backend,
            "readback_mode": self.readback_mode,
            "readback_auto_slack": self.readback_auto_slack,
            "join_rebuilds": self.dev.join_rebuilds,
            "autotune": (self.tuner.info()
                         if self.tuner is not None else None),
            # multichip serve backend (ISSUE 15)
            "multichip": (self.mc.info() if self.mc is not None
                          else None),
            "segments": ({
                "dir": self.segments_dir,
                "loaded": self._segment_loaded,
                "table_gen": self._table_gen,
                "mutations": self._mut_count,
                "abandoned": self._compact_abandoned,
                "grow_applies": self.dev.grow_applies,
                "dirty_rows_uploaded": self.dev.dirty_rows_uploaded,
                "kernel_cache": (self.kcache.info()
                                 if self.kcache is not None else None),
            } if self.segments else None),
        }
