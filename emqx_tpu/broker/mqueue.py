"""Session message queue with priorities and drop policy.

Behavioral reference: ``apps/emqx/src/emqx_mqueue.erl`` [U] (SURVEY.md
§2.1): bounded per-session queue buffering messages that cannot be
delivered yet (inflight window full / client offline).  Semantics kept:

* ``max_len`` bound (0 = unbounded); when full the **lowest-priority
  oldest** message is dropped to admit a higher-priority one, else the
  incoming message is dropped (emqx drops the queue head within the same
  priority band — oldest first).
* optional ``store_qos0`` — QoS0 messages may bypass storage when the
  client is disconnected.
* per-topic priorities via ``priorities`` map + ``default_priority``.
* dropped messages are returned so callers can emit ``message.dropped``.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, Iterator, List, Optional, Tuple

from .message import Message

__all__ = ["MQueue"]


class MQueue:
    def __init__(
        self,
        max_len: int = 1000,
        store_qos0: bool = True,
        priorities: Optional[Dict[str, int]] = None,
        default_priority: int = 0,
    ) -> None:
        self.max_len = max_len
        self.store_qos0 = store_qos0
        self.priorities = priorities or {}
        self.default_priority = default_priority
        self._qs: Dict[int, Deque[Message]] = {}
        self._len = 0
        self.dropped = 0
        # queued messages carrying a Message-Expiry-Interval: while 0,
        # filter_expired short-circuits — nothing CAN expire, and the
        # O(queue) sweep per ack-driven dequeue was the dominant cost of
        # the acknowledged-delivery path under backlog.  Monotone
        # overcount (decremented on the expiry sweep itself, not on
        # pop/evict): a stale positive only costs one sweep.
        self._expiring = 0

    def __len__(self) -> int:
        return self._len

    def is_empty(self) -> bool:
        return self._len == 0

    def _prio(self, msg: Message) -> int:
        return self.priorities.get(msg.topic, self.default_priority)

    def insert(self, msg: Message) -> Optional[Message]:
        """Queue ``msg``; returns the dropped message if the bound forced
        one out (possibly ``msg`` itself), else None."""
        if msg.qos == 0 and not self.store_qos0:
            self.dropped += 1
            return msg
        prio = self._prio(msg)
        if self.max_len > 0 and self._len >= self.max_len:
            victim = self._drop_lowest_upto(prio)
            if victim is None:
                self.dropped += 1
                return msg  # nothing lower-priority to evict
            self.dropped += 1
            self._push(prio, msg)
            return victim
        self._push(prio, msg)
        return None

    def insert_many(self, msgs: List[Message]) -> List[Message]:
        """Bulk :meth:`insert` — returns every dropped message.  The
        fast path (no bound pressure, no priorities, QoS0 storable)
        appends the whole run into one band without per-message method
        dispatch; anything else falls through to ``insert`` per message
        so drop policy stays identical."""
        if not msgs:
            return []
        room = (self.max_len - self._len) if self.max_len > 0 else len(msgs)
        if (
            room >= len(msgs)
            and not self.priorities
            and (self.store_qos0 or all(m.qos != 0 for m in msgs))
        ):
            prio = self.default_priority
            q = self._qs.get(prio)
            if q is None:
                q = self._qs[prio] = deque()
            q.extend(msgs)
            self._len += len(msgs)
            for m in msgs:
                if "Message-Expiry-Interval" in m.properties:
                    self._expiring += 1
            return []
        dropped: List[Message] = []
        for m in msgs:
            victim = self.insert(m)
            if victim is not None:
                dropped.append(victim)
        return dropped

    def _push(self, prio: int, msg: Message) -> None:
        q = self._qs.get(prio)
        if q is None:
            q = self._qs[prio] = deque()
        q.append(msg)
        self._len += 1
        if "Message-Expiry-Interval" in msg.properties:
            self._expiring += 1

    def _drop_lowest_upto(self, prio: int) -> Optional[Message]:
        """Evict the oldest message from the lowest priority band ≤ prio."""
        for p in sorted(self._qs):
            if p > prio:
                return None
            q = self._qs[p]
            if q:
                self._len -= 1
                victim = q.popleft()
                if not q:
                    del self._qs[p]
                return victim
        return None

    def pop(self) -> Optional[Message]:
        """Dequeue the highest-priority oldest message."""
        for p in sorted(self._qs, reverse=True):
            q = self._qs[p]
            if q:
                self._len -= 1
                msg = q.popleft()
                if not q:
                    del self._qs[p]
                return msg
        return None

    def peek(self) -> Optional[Message]:
        for p in sorted(self._qs, reverse=True):
            if self._qs[p]:
                return self._qs[p][0]
        return None

    def to_list(self) -> List[Message]:
        out: List[Message] = []
        for p in sorted(self._qs, reverse=True):
            out.extend(self._qs[p])
        return out

    def filter_expired(self, now: Optional[float] = None) -> List[Message]:
        """Drop and return expired messages (MQTT5 message expiry).

        O(1) while no queued message carries an expiry interval — the
        common case, and this runs on every ack-driven dequeue."""
        if self._expiring <= 0:
            return []
        expired: List[Message] = []
        expiring = 0
        for p in list(self._qs):
            q = self._qs[p]
            keep = deque()
            for m in q:
                if m.is_expired(now):
                    expired.append(m)
                else:
                    keep.append(m)
                    if "Message-Expiry-Interval" in m.properties:
                        expiring += 1
            if keep:
                self._qs[p] = keep
            else:
                del self._qs[p]
        self._expiring = expiring
        self._len -= len(expired)
        self.dropped += len(expired)
        return expired
