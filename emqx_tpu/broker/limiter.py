"""Token-bucket rate limiters.

Behavioral reference: the esockd/``emqx_limiter`` token buckets [U]
(SURVEY.md §2.1): per-listener connection rate, per-connection message
and byte rates.  ``consume`` is non-blocking (returns whether the tokens
were granted plus the wait needed) — the asyncio connection layer sleeps
the returned interval, mirroring the reference's pause/resume of the
receive loop.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

__all__ = ["TokenBucket", "LimiterGroup"]


class TokenBucket:
    """rate tokens/second, bursting to ``burst`` (defaults to rate)."""

    __slots__ = ("rate", "burst", "_tokens", "_last")

    def __init__(self, rate: float, burst: Optional[float] = None) -> None:
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self._tokens = self.burst
        self._last: Optional[float] = None

    @property
    def unlimited(self) -> bool:
        return self.rate <= 0

    def _refill(self, now: float) -> None:
        if self._last is None:
            self._last = now
            return
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now

    def consume(self, n: float = 1.0, now: Optional[float] = None) -> Tuple[bool, float]:
        """Try to take ``n`` tokens.  Returns (granted, wait_seconds) —
        wait_seconds > 0 tells the caller how long to pause before retry."""
        if self.unlimited:
            return True, 0.0
        now = now if now is not None else time.time()
        self._refill(now)
        if self._tokens >= n:
            self._tokens -= n
            return True, 0.0
        deficit = n - self._tokens
        return False, deficit / self.rate

    def tokens(self, now: Optional[float] = None) -> float:
        if self.unlimited:
            return float("inf")
        self._refill(now if now is not None else time.time())
        return self._tokens

    def retune(self, rate: float, burst: Optional[float] = None) -> None:
        """Re-rate this bucket IN PLACE (admission throttle / restore):
        call sites hold direct references to the bucket object, so a
        swap would silently detach them.  Coming from unlimited the
        bucket starts full (a fresh bucket's semantics); tightening a
        limited one clamps, so the throttle bites on the next consume."""
        was_unlimited = self.unlimited
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self._tokens = self.burst if was_unlimited \
            else min(self._tokens, self.burst)


class LimiterGroup:
    """The three reference limiter dimensions, from config keys
    ``limiter.max_conn_rate`` / ``max_messages_rate`` / ``max_bytes_rate``
    (0 = unlimited)."""

    def __init__(
        self,
        max_conn_rate: float = 0.0,
        max_messages_rate: float = 0.0,
        max_bytes_rate: float = 0.0,
    ) -> None:
        self.conn = TokenBucket(max_conn_rate)
        self._msg_rate = max_messages_rate
        self._bytes_rate = max_bytes_rate
        self._per_conn: Dict[str, Tuple[TokenBucket, TokenBucket]] = {}

    def reconfigure(
        self,
        max_conn_rate: Optional[float] = None,
        max_messages_rate: Optional[float] = None,
        max_bytes_rate: Optional[float] = None,
    ) -> None:
        """Hot update (emqx_config_handler): new connections pick up the
        new per-conn rates; the shared connect-rate bucket swaps now."""
        if max_conn_rate is not None:
            self.conn = TokenBucket(max_conn_rate)
        if max_messages_rate is not None:
            self._msg_rate = max_messages_rate
        if max_bytes_rate is not None:
            self._bytes_rate = max_bytes_rate

    def allow_connect(self, now: Optional[float] = None) -> Tuple[bool, float]:
        return self.conn.consume(1.0, now)

    def conn_buckets(self, connid: str) -> Tuple[TokenBucket, TokenBucket]:
        """(messages, bytes) buckets for one connection."""
        b = self._per_conn.get(connid)
        if b is None:
            b = self._per_conn[connid] = (
                TokenBucket(self._msg_rate), TokenBucket(self._bytes_rate)
            )
        return b

    def drop_conn(self, connid: str) -> None:
        self._per_conn.pop(connid, None)

    def tracked(self) -> int:
        return len(self._per_conn)

    def sweep_idle(self, idle_s: float, now: Optional[float] = None) -> int:
        """Evict bucket pairs idle past ``idle_s`` (per-client-state
        growth audit: every close path calls drop_conn, but a handler
        that dies between accept and close would leak its pair forever;
        this is the belt-and-braces bound).  A live-but-idle connection
        whose entry is evicted just gets a fresh pair on its next
        allow_publish — unlimited buckets identically, limited ones
        with a reset burst, both harmless."""
        now = now if now is not None else time.time()
        stale = [
            cid for cid, (msgs, byts) in self._per_conn.items()
            if (msgs._last or 0.0) < now - idle_s
            and (byts._last or 0.0) < now - idle_s
        ]
        for cid in stale:
            del self._per_conn[cid]
        return len(stale)

    def allow_publish(
        self, connid: str, nbytes: int, now: Optional[float] = None
    ) -> Tuple[bool, float]:
        # all-or-nothing: a deny by either dimension must not drain the
        # other bucket, or retry loops starve the connection
        msgs, byts = self.conn_buckets(connid)
        if msgs.tokens(now) < 1.0:
            return False, (1.0 - msgs.tokens(now)) / msgs.rate
        if byts.tokens(now) < float(nbytes):
            return False, (float(nbytes) - byts.tokens(now)) / byts.rate
        msgs.consume(1.0, now)
        byts.consume(float(nbytes), now)
        return True, 0.0
