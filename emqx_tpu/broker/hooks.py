"""Global ordered hook registry — the extension bus.

Behavioral reference: ``apps/emqx/src/emqx_hooks.erl`` [U] (SURVEY.md
§2.1, L6): named hook points with priority-ordered callback chains and
two run modes:

* :meth:`Hooks.run` — chain of ``fn(*args) -> HookResult``; ``STOP``
  short-circuits the chain (e.g. an authz deny).
* :meth:`Hooks.run_fold` — additionally threads an accumulator (e.g. the
  message being mutated by ``'message.publish'`` handlers).

Callbacks return:

* ``None`` / ``OK``            — continue, accumulator unchanged
* ``(OK, acc')``               — continue with new accumulator
* ``STOP``                     — stop, accumulator unchanged
* ``(STOP, acc')``             — stop with new accumulator

Higher priority runs first (emqx orders by priority then insertion seq).
The standard hook-point names (``'client.connect'``,
``'message.publish'``, ...) are listed in :data:`HOOK_POINTS` to mirror
the reference's ~25 points.
"""

from __future__ import annotations

import bisect
import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["OK", "STOP", "Hooks", "HOOK_POINTS"]

OK = "ok"
STOP = "stop"

HOOK_POINTS = [
    "client.connect", "client.connack", "client.connected",
    "client.disconnected", "client.authenticate", "client.authorize",
    "client.enhanced_authenticate", "client.enhanced_authenticated",
    "client.subscribe", "client.unsubscribe",
    "session.created", "session.subscribed", "session.unsubscribed",
    "session.resumed", "session.discarded", "session.takenover",
    "session.terminated",
    "message.publish", "message.delivered", "message.acked",
    "message.dropped",
    "delivery.dropped", "delivery.completed",
]


class _Callback:
    __slots__ = ("priority", "seq", "fn", "name")

    def __init__(self, priority: int, seq: int, fn: Callable, name: str):
        self.priority = priority
        self.seq = seq
        self.fn = fn
        self.name = name

    def sort_key(self):
        # higher priority first; ties broken by insertion order
        return (-self.priority, self.seq)


class Hooks:
    def __init__(self) -> None:
        self._points: Dict[str, List[_Callback]] = {}
        self._seq = itertools.count()

    def add(
        self,
        point: str,
        fn: Callable,
        priority: int = 0,
        name: Optional[str] = None,
    ) -> None:
        # copy-on-write: mutations install a NEW list, so run()/run_fold()
        # iterate the chain they started with without a per-call copy —
        # the delivered/dropped hooks fire once per fan-out leg, and the
        # defensive list() showed up in wide-fanout profiles
        cbs = list(self._points.get(point, ()))
        cb = _Callback(priority, next(self._seq), fn, name or getattr(fn, "__name__", "fn"))
        keys = [c.sort_key() for c in cbs]
        cbs.insert(bisect.bisect_right(keys, cb.sort_key()), cb)
        self._points[point] = cbs

    def delete(self, point: str, fn_or_name) -> bool:
        cbs = self._points.get(point, [])
        for i, cb in enumerate(cbs):
            if cb.fn is fn_or_name or cb.name == fn_or_name:
                self._points[point] = cbs[:i] + cbs[i + 1:]
                return True
        return False

    def callbacks(self, point: str) -> List[str]:
        return [cb.name for cb in self._points.get(point, [])]

    def has(self, point: str) -> bool:
        """True iff any callback is registered — lets per-item hot loops
        skip the dispatch (and its args tuple) entirely when idle."""
        return bool(self._points.get(point))

    # ------------------------------------------------------------------

    def run(self, point: str, args: Tuple = ()) -> str:
        """Run the chain; returns OK or STOP (whichever ended it)."""
        cbs = self._points.get(point)
        if not cbs:
            return OK          # empty chains are the hot-path common case
        for cb in cbs:         # safe: mutations replace the list (CoW)
            res = cb.fn(*args)
            if res is None:
                continue
            verdict, _ = _normalize(res, None)
            if verdict == STOP:
                return STOP
        return OK

    def run_fold(self, point: str, args: Tuple, acc: Any) -> Any:
        """Run the chain threading ``acc``; returns the final accumulator."""
        cbs = self._points.get(point)
        if not cbs:
            return acc
        for cb in cbs:         # safe: mutations replace the list (CoW)
            res = cb.fn(*args, acc)
            if res is None:
                continue
            verdict, acc = _normalize(res, acc)
            if verdict == STOP:
                break
        return acc


def _normalize(res: Any, acc: Any) -> Tuple[str, Any]:
    if res is None or res == OK:
        return OK, acc
    if res == STOP:
        return STOP, acc
    if isinstance(res, tuple) and len(res) == 2 and res[0] in (OK, STOP):
        return res[0], res[1]
    # bare return value = new accumulator, continue (convenience)
    return OK, res
