"""Host-side subscription tries — the authoritative wildcard indexes.

Behavioral reference: ``apps/emqx/src/emqx_trie.erl`` (``insert/1``,
``match/1``, ``delete/1``) and ``emqx_topic_index.erl`` [U] — reference
mount empty this round, see SURVEY.md provenance header.

Two directions of the same problem:

* :class:`FilterTrie` — indexes **wildcard filters**, answers
  "which filters match this concrete topic?" (the publish hot path;
  this is what gets compiled to the flattened NFA on device).
* :class:`TopicTrie` — indexes **concrete topics**, answers
  "which stored topics match this wildcard filter?" (the retained-message
  replay path on subscribe).

Both are refcounted: inserting the same key twice needs two deletes before
edges disappear (mirrors emqx_trie's edge counting so concurrent routes
sharing prefixes survive unrelated deletes).

These are also the **CPU baseline** for BASELINE.md's denominator: match
throughput here is what the TPU kernel is judged against.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from .. import topic as T

__all__ = ["FilterTrie", "TopicTrie"]


class _Node:
    __slots__ = ("children", "end_count")

    def __init__(self) -> None:
        self.children: Dict[str, "_Node"] = {}
        self.end_count: int = 0  # number of live inserts terminating here


class _TrieBase:
    """Shared insert/delete machinery over word paths."""

    def __init__(self) -> None:
        self._root = _Node()
        self._keys: Dict[str, int] = {}  # key -> refcount (live inserts)

    # -- mutation ----------------------------------------------------------

    def insert(self, key: str) -> bool:
        """Insert one reference to ``key``.  Returns True if it is new."""
        ws = T.words(key)
        node = self._root
        for w in ws:
            nxt = node.children.get(w)
            if nxt is None:
                nxt = node.children[w] = _Node()
            node = nxt
        node.end_count += 1
        new = key not in self._keys
        self._keys[key] = self._keys.get(key, 0) + 1
        return new

    def delete(self, key: str) -> bool:
        """Drop one reference to ``key``.  Returns True if it is now gone.

        Unknown keys are a no-op (mirrors emqx_trie:delete of absent
        filters).
        """
        if key not in self._keys:
            return False
        ws = T.words(key)
        # walk down recording the path so empty branches can be pruned
        path: List[_Node] = [self._root]
        node = self._root
        for w in ws:
            node = node.children[w]
            path.append(node)
        node.end_count -= 1
        self._keys[key] -= 1
        gone = self._keys[key] == 0
        if gone:
            del self._keys[key]
        # prune: remove child edges whose subtree is dead
        for i in range(len(ws) - 1, -1, -1):
            child = path[i + 1]
            if child.end_count == 0 and not child.children:
                del path[i].children[ws[i]]
            else:
                break
        return gone

    # -- introspection -----------------------------------------------------

    def __contains__(self, key: str) -> bool:
        return key in self._keys

    def __len__(self) -> int:
        return len(self._keys)

    def keys(self) -> Iterator[str]:
        return iter(self._keys)

    def refcount(self, key: str) -> int:
        return self._keys.get(key, 0)

    def is_empty(self) -> bool:
        return not self._keys

    def node_count(self) -> int:
        """Number of trie nodes (excluding root) — sizing input for the
        NFA compiler."""
        n = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            for c in node.children.values():
                n += 1
                stack.append(c)
        return n


class FilterTrie(_TrieBase):
    """Wildcard filters indexed; match a concrete topic against them.

    ``$share`` prefixes must be stripped by the caller before insert
    (the broker layer owns share-group bookkeeping).
    """

    def match(self, name) -> List[str]:
        """All inserted filters matching concrete topic ``name``.

        Semantics identical to the oracle ``topic.match`` over every key —
        property-tested to agree.
        """
        nw = T.words(name) if isinstance(name, str) else list(name)
        if not nw:
            return []
        out: List[str] = []
        sys_topic = nw[0].startswith("$")
        # iterative DFS (valid filters can be tens of thousands of levels
        # deep — Python recursion would blow the stack on the hot path)
        stack: List[Tuple[_Node, int, Tuple[str, ...]]] = [(self._root, 0, ())]
        while stack:
            node, i, acc = stack.pop()
            # '#' child matches the rest (incl. zero levels), unless it is
            # a root-level wildcard on a $-topic.
            hashc = node.children.get("#")
            if hashc is not None and not (i == 0 and sys_topic):
                if hashc.end_count > 0:
                    out.append(T.join(acc + ("#",)))
            if i == len(nw):
                if node.end_count > 0:
                    out.append(T.join(acc))
                continue
            w = nw[i]
            lit = node.children.get(w)
            if lit is not None:
                stack.append((lit, i + 1, acc + (w,)))
            # '+' is a distinct edge from a literal '+' level;
            # root-level '+' is disabled for $-topics.
            if w != "+":
                plus = node.children.get("+")
                if plus is not None and not (i == 0 and sys_topic):
                    stack.append((plus, i + 1, acc + ("+",)))
        return out

    def match_many(self, names: Sequence[str]) -> Dict[str, List[str]]:
        """Batch :meth:`match` with duplicate-topic dedup — the CPU
        fallback path of the deadline serve loop answers a whole failed
        dispatch batch here, and publish storms repeat topics heavily
        (one trie walk per UNIQUE topic, not per waiter)."""
        out: Dict[str, List[str]] = {}
        for name in names:
            if name not in out:
                out[name] = self.match(name)
        return out


class TopicTrie(_TrieBase):
    """Concrete topics indexed; match a wildcard filter against them
    (retained-message replay direction)."""

    def match(self, flt) -> List[str]:
        fw = T.words(flt) if isinstance(flt, str) else list(flt)
        if not fw:
            return []
        out: List[str] = []
        # iterative DFS; entries are (node, filter_pos, topic_acc).
        # filter_pos == len(fw) with a trailing '#' means "collect subtree".
        COLLECT = -1
        stack: List[Tuple[_Node, int, Tuple[str, ...]]] = [(self._root, 0, ())]
        while stack:
            node, i, acc = stack.pop()
            if i == COLLECT:
                if node.end_count > 0 and acc:
                    out.append(T.join(acc))
                for cw, child in node.children.items():
                    stack.append((child, COLLECT, acc + (cw,)))
                continue
            if i == len(fw):
                if node.end_count > 0:
                    out.append(T.join(acc))
                continue
            w = fw[i]
            if w == "#":
                # everything at or below this node — except $-topics at root
                if node.end_count > 0 and acc:
                    out.append(T.join(acc))
                for cw, child in node.children.items():
                    if i == 0 and cw.startswith("$"):
                        continue
                    stack.append((child, COLLECT, acc + (cw,)))
                continue
            if w == "+":
                for cw, child in node.children.items():
                    if i == 0 and cw.startswith("$"):
                        continue
                    stack.append((child, i + 1, acc + (cw,)))
                continue
            child = node.children.get(w)
            if child is not None:
                stack.append((child, i + 1, acc + (w,)))
        return out
